"""The orchestrator: BFS work distribution, result fan-in, worker health.

Parity with the reference's `orchestrator/orchestrator.go` (633 LoC):
- work distributor ticking every 5 s over the current BFS depth (`:160-277`)
- work-item creation from `state.Page` (`:280-303`)
- result handling -> page status update + new-layer creation (`:315-416`)
- worker registry built from status messages (`:419-449`)
- health monitor: 5-min last-seen timeout -> offline -> republish that
  worker's items at high priority with retry counts (`:472-559`)
- progress logging + `get_status` snapshot (`:562-633`)

Tick methods (`distribute_work`, `check_worker_health`, `log_progress`) are
public and side-effect-complete so tests drive them deterministically without
timers; `start()` wires the same methods to background threads.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field, replace
from datetime import datetime
from typing import Any, Dict, List, Optional

from ..bus.messages import (
    PRIORITY_HIGH,
    PRIORITY_MEDIUM,
    STATUS_SUCCESS,
    TOPIC_ALERTS,
    TOPIC_CLUSTERS,
    TOPIC_RESULTS,
    TOPIC_SPANS,
    TOPIC_WORK_QUEUE,
    TOPIC_WORKER_STATUS,
    AlertMessage,
    ClusterUpdateMessage,
    WORKER_ACTIVE,
    WORKER_BUSY,
    WORKER_IDLE,
    WORKER_OFFLINE,
    ResultMessage,
    SpanBatchMessage,
    StatusMessage,
    WorkItem,
    WorkItemConfig,
    WorkQueueMessage,
    WorkResult,
)
from .fleet import FleetView
from .tracecollect import TraceCollector
from .watchtower import Watchtower
from .journal import CrawlJournal, RecoveredCrawl
from ..config.crawler import CrawlerConfig
from ..utils import flight, resilience, trace
from ..state.datamodels import (
    PAGE_ABANDONED,
    PAGE_ERROR,
    PAGE_FETCHED,
    PAGE_PROCESSING,
    PAGE_UNFETCHED,
    Page,
    utcnow,
)

logger = logging.getLogger("dct.orchestrator")

# Circuit-breaker target name for the orchestrator's state-store ops
# (the `resilience_circuit_state{target=...}` label value).
STATE_STORE_TARGET = "state-store"

# Applied-result idempotence window: ids of results already applied,
# kept so broker redeliveries (incl. across a restart) single-count.
# Bounded — only ids within the broker's plausible redelivery horizon
# matter.  Snapshots persist only the newest SNAPSHOT-many ids: the
# cross-restart redelivery horizon is far smaller than the live window,
# and compaction fsyncs the list every ~256 events.
APPLIED_RESULTS_WINDOW = 65536
APPLIED_RESULTS_SNAPSHOT = 8192

# Work deferred while the state-store circuit is open (discovered layers
# and result applications) is retried each tick, bounded: beyond the cap
# the oldest entries drop from memory — their recovery story is the
# journal (layers are journaled before the store write; an unjournaled
# result leaves its item in-flight, so a restart requeues it).
DEFERRED_CAP = 4096


@dataclass
class OrchestratorConfig:
    """Timing knobs (`orchestrator.go:163,477,498` + config/distributed.go)."""

    distribute_interval_s: float = 5.0
    health_interval_s: float = 30.0
    worker_timeout_s: float = 300.0  # 5 min (`orchestrator.go:498`)
    max_retries: int = 3
    work_ttl_s: int = 3600
    # Co-scheduling backpressure (north star: crawl + inference shards on
    # one slice): when the summed queue_length of live TPU workers crosses
    # the HIGH watermark, crawl work distribution pauses; it resumes once
    # the backlog drains below LOW (hysteresis so the valve doesn't
    # chatter).  high=0 disables the valve.
    inference_backpressure_high: int = 64
    inference_backpressure_low: int = 32
    # Resiliency policy knobs (utils/resilience.py): state-store ops run
    # behind a retry + circuit breaker; an OPEN circuit engages the
    # dispatch backpressure valve instead of erroring the tick loop.
    state_retry_attempts: int = 2
    state_breaker_threshold: int = 5
    state_breaker_recovery_s: float = 15.0
    publish_retry_attempts: int = 3
    # Watchtower (orchestrator/watchtower.py): how often the alert
    # engine evaluates its rules over the rolling time-series store.
    # Both the distribute and health ticks call it; this limiter sets
    # the effective cadence.
    alert_eval_interval_s: float = 5.0
    # Cluster-guided frontier prioritization (`cluster/`): how long the
    # last ClusterUpdateMessage steers dispatch priorities.  Past the
    # TTL the guide is ignored — a dead cluster worker's final snapshot
    # must not promote pages forever.  0 disables expiry.
    cluster_guide_ttl_s: float = 600.0


@dataclass
class WorkerInfo:
    """Tracked per-worker state (`orchestrator.go:46-56`)."""

    id: str = ""
    status: str = WORKER_IDLE
    worker_type: str = "crawl"  # "crawl" | "tpu" (StatusMessage.worker_type)
    last_seen: Optional[datetime] = None
    current_work: Optional[str] = None
    queue_length: int = 0  # TPU workers: pending inference batches
    tasks_total: int = 0
    tasks_success: int = 0
    tasks_error: int = 0
    metadata: Dict[str, Any] = field(default_factory=dict)


class Orchestrator:
    """Central coordinator (`orchestrator.go:26-106`)."""

    def __init__(self, crawl_id: str, config: CrawlerConfig, bus, sm,
                 ocfg: Optional[OrchestratorConfig] = None,
                 clock=time.monotonic,
                 journal: Optional[CrawlJournal] = None,
                 registry=None,
                 alert_rules=None):
        from ..utils.metrics import REGISTRY
        self.crawl_id = crawl_id
        self.config = config
        self.bus = bus
        self.sm = sm
        self.ocfg = ocfg or OrchestratorConfig()
        self.clock = clock
        self.journal = journal
        registry = registry if registry is not None else REGISTRY

        self.workers: Dict[str, WorkerInfo] = {}
        self.active_work: Dict[str, WorkItem] = {}
        self.completed_work: Dict[str, WorkResult] = {}
        self.current_depth = 0
        self.total_work_items = 0
        self.completed_items = 0
        self.error_items = 0
        self.discovered_pages = 0
        self.crawl_completed = False
        self.resumed = False
        self._retry_counts: Dict[str, int] = {}  # page id -> retries
        # Work-item ids whose results were applied (insertion-ordered,
        # bounded to APPLIED_RESULTS_WINDOW): the idempotence window that
        # makes results replayed across a restart single-count.
        self._applied_results: "OrderedDict[str, None]" = OrderedDict()
        # State-store work parked while the circuit is open, retried per
        # tick (`_flush_deferred`).
        self._deferred_layers: List[List[Page]] = []
        self._deferred_results: List[tuple] = []
        # The circuit's dispatch-pause latch (separate from the
        # inference-backlog hysteresis valve `_backpressure_active`).
        self._circuit_backpressure = False
        self._backpressure_active = False
        # Bus-outbox latch: when publishes ride a durable outbox
        # (`bus/outbox.py`) and the broker outage has it near its bound,
        # dispatch pauses instead of filling the buffer to OutboxFull.
        self._outbox_backpressure = False
        # Telemetry-rich per-worker fold behind /cluster; its staleness
        # rule tracks the same timeout check_worker_health enforces.
        self.fleet = FleetView(stale_after_s=self.ocfg.worker_timeout_s,
                               registry=registry)
        # The watchtower (orchestrator/watchtower.py): rolling history
        # for every heartbeat series + the declarative alert engine,
        # evaluated on the orchestrator tick and served at /alerts.
        # Wall clock (not self.clock, which is monotonic by default):
        # the time-series store keys samples by epoch.
        self.watchtower = Watchtower(
            self.fleet, rules=alert_rules, registry=registry,
            bus=bus, eval_interval_s=self.ocfg.alert_eval_interval_s)
        # Distributed-trace assembly behind /dtraces: workers ship
        # completed spans on TOPIC_SPANS; the collector corrects each
        # worker's span walls by the clock offset the fleet estimates
        # from heartbeat send/receive walls, and merges this process's
        # own spans in at export (`orchestrator/tracecollect.py`).
        self.trace_collector = TraceCollector(
            offsets_fn=self.fleet.clock_offsets, process="orchestrator")
        # Declarative resiliency (utils/resilience.py): state-store ops
        # behind retry + circuit breaker (an open circuit engages the
        # dispatch backpressure), bus publishes behind jittered retry.
        self._state_policy = resilience.Policy(
            op="orchestrator.state_store",
            retry=resilience.RetryPolicy(
                max_attempts=self.ocfg.state_retry_attempts,
                base_delay_s=0.05, max_delay_s=0.5, jitter=0.0),
            breaker=resilience.CircuitBreaker(
                STATE_STORE_TARGET,
                failure_threshold=self.ocfg.state_breaker_threshold,
                recovery_timeout_s=self.ocfg.state_breaker_recovery_s,
                clock=clock))
        self._publish_policy = resilience.Policy(
            op="orchestrator.publish",
            retry=resilience.RetryPolicy(
                max_attempts=self.ocfg.publish_retry_attempts,
                base_delay_s=0.05, max_delay_s=0.5))

        # Cluster-guided frontier prioritization (`cluster/`): the
        # latest ClusterUpdateMessage's under-populated cluster ids and
        # channel->cluster map.  A frontier page whose channel maps to
        # an under-populated cluster dispatches at PRIORITY_HIGH — the
        # snowball steers toward the sparse corners of the embedding
        # space instead of re-crawling the dense ones.
        self._cluster_guide: Optional[Dict[str, Any]] = None
        self._cluster_prioritized = 0

        # Sharded frontier (`bus/partition.py`): when the bus exposes a
        # consistent-hash shard map, frontier pages partition by channel
        # hash into shard-owned dispatch lanes — per-lane counts kept
        # for /status + the frontier_shards flight event.
        self._frontier_lane_counts: Dict[str, int] = {}

        self._mu = threading.RLock()
        self._running = False
        self._killed = False
        self._threads: List[threading.Thread] = []
        self._started_at = 0.0

    # -- lifecycle ---------------------------------------------------------
    def start(self, seed_urls: List[str], background: bool = True,
              fresh: bool = False) -> None:
        """`orchestrator.go:106-137`, plus crash recovery.

        An existing crawl (journal or persisted state-manager snapshot)
        is RESUMED, never clobbered: coordination state is rebuilt from
        journal + state manager and in-flight pages are requeued.  Pass
        ``fresh=True`` (the ``--fresh`` flag) to explicitly discard the
        previous crawl and re-seed."""
        with self._mu:
            if self._running:
                raise RuntimeError("orchestrator is already running")
            self._running = True
        self._started_at = self.clock()
        if fresh:
            self._discard_existing_crawl()
        else:
            self._discard_foreign_journal()
        pending: List[WorkItem] = []
        if not fresh and self._has_existing_crawl():
            pending = self._resume_state()
        else:
            self.sm.initialize(seed_urls)
            self._journal_begin()
        # Subscribe BEFORE republishing in-flight work: on a synchronous
        # transport a worker can crawl a requeued item and publish its
        # result inline, which must not race the subscription.
        self.bus.subscribe(TOPIC_RESULTS, self.handle_result_payload)
        self.bus.subscribe(TOPIC_WORKER_STATUS, self.handle_status_payload)
        self.bus.subscribe(TOPIC_SPANS, self.handle_spans_payload)
        # Route the watchtower's own announcements: the coordinator logs
        # them, and a durable broker never holds alert frames as
        # unrouted dead letters just because no external tool listens.
        self.bus.subscribe(TOPIC_ALERTS, self.handle_alert_payload)
        # Cluster-state announcements feed the frontier prioritization
        # (fan-out: a missed update degrades freshness, never progress).
        self.bus.subscribe(TOPIC_CLUSTERS, self.handle_cluster_payload)
        if self.resumed:
            self._resume_requeue(pending)
        if background:
            for target, interval, name in (
                    (self.distribute_work, self.ocfg.distribute_interval_s,
                     "orch-distribute"),
                    (self._health_tick, self.ocfg.health_interval_s,
                     "orch-health")):
                t = threading.Thread(target=self._loop,
                                     args=(target, interval), daemon=True,
                                     name=name)
                t.start()
                self._threads.append(t)
        logger.info("orchestrator started", extra={
            "crawl_id": self.crawl_id, "seed_count": len(seed_urls),
            "resumed": self.resumed})

    def stop(self) -> None:
        with self._mu:
            self._running = False
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()
        self._compact_journal(force=True)
        if self.journal is not None:
            self.journal.close()
        self.sm.close()
        logger.info("orchestrator stopped", extra={"crawl_id": self.crawl_id})

    def kill(self) -> None:
        """Abrupt-death simulation (the chaos/`loadgen` seam, the twin of
        `CrawlWorker.kill`): drop everything in memory WITHOUT a journal
        snapshot or a state-manager save — the in-process analog of
        SIGKILL.  Recovery must run from the journal + the last persisted
        snapshot alone.  Handlers go silent (a dead process's bus
        subscriptions are gone; in-process buses can't unsubscribe)."""
        with self._mu:
            self._running = False
            self._killed = True
            active = len(self.active_work)
        flight.record("orch_kill", crawl_id=self.crawl_id,
                      active_work=active, depth=self.current_depth)
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()
        if self.journal is not None:
            self.journal.close()

    @property
    def is_running(self) -> bool:
        with self._mu:
            return self._running

    # -- crash recovery ----------------------------------------------------
    def _has_existing_crawl(self) -> bool:
        """Is there a previous crawl to resume — a non-empty journal, or a
        persisted state-manager snapshot with layers?"""
        if self.journal is not None and self.journal.exists():
            return True
        provider = getattr(self.sm, "provider", None)
        path_fn = getattr(self.sm, "_state_path", None)
        if provider is None or not callable(path_fn):
            return False
        try:
            existing = provider.load_json(path_fn())
        except Exception as e:
            logger.warning("existing-crawl probe failed: %s", e)
            return False
        return bool(existing and existing.get("layers"))

    def _discard_foreign_journal(self) -> None:
        """A journal recorded by a DIFFERENT crawl id (shared journal
        dir, e.g. a common --dump-dir) must not be resumed as ours —
        discard it loudly instead of silently running someone else's
        crawl."""
        if self.journal is None or not self.journal.exists():
            return
        recorded = self.journal.recorded_crawl_id()
        if recorded and recorded != self.crawl_id:
            logger.warning(
                "journal at %s belongs to crawl %r, not %r; discarding it",
                self.journal.journal_dir, recorded, self.crawl_id)
            self.journal.reset()

    def _discard_existing_crawl(self) -> None:
        """``--fresh``: drop the journal and blank the persisted state
        snapshot so ``sm.initialize`` re-seeds instead of resuming."""
        if self.journal is not None:
            self.journal.reset()
        provider = getattr(self.sm, "provider", None)
        path_fn = getattr(self.sm, "_state_path", None)
        if provider is not None and callable(path_fn):
            try:
                provider.save_json(path_fn(), {})
            except Exception as e:
                logger.warning("could not blank persisted state: %s", e)
        logger.info("fresh start requested; discarded existing crawl state")

    def _journal_begin(self) -> None:
        """Stamp the crawl identity + the seed layer so a crash before the
        first state-manager save can still rebuild layer 0."""
        if self.journal is None:
            return
        self._jappend("begin", crawl_id=self.crawl_id)
        try:
            seeds = self.sm.get_layer_by_depth(0)
        except Exception as e:
            logger.warning("seed-layer journal stamp skipped: %s", e)
            seeds = []
        if seeds:
            self._jappend("layer", depth=0,
                          pages=[p.to_dict() for p in seeds])

    def _jappend(self, kind: str, **fields) -> None:
        """Journal append that never takes the crawl down with it."""
        if self.journal is None:
            return
        try:
            self.journal.append(kind, **fields)
        except Exception as e:
            logger.error("journal append failed (%s): %s", kind, e)

    def _snapshot_dict(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "crawl_id": self.crawl_id,
                "current_depth": self.current_depth,
                "total_work_items": self.total_work_items,
                "completed_items": self.completed_items,
                "error_items": self.error_items,
                "discovered_pages": self.discovered_pages,
                "crawl_completed": self.crawl_completed,
                "active_work": {wid: item.to_dict()
                                for wid, item in self.active_work.items()},
                "retry_counts": dict(self._retry_counts),
                # Insertion order = recency; only the newest slice is
                # persisted (APPLIED_RESULTS_SNAPSHOT) so compaction
                # cost stays flat on long crawls.
                "applied_results":
                    list(self._applied_results)[-APPLIED_RESULTS_SNAPSHOT:],
            }

    def _mark_applied_locked(self, work_item_id: str) -> None:
        """Record an applied (or abandoned) work-item id in the bounded
        idempotence window; caller holds ``_mu``."""
        self._applied_results[work_item_id] = None  # crawlint: disable=LCK001
        while len(self._applied_results) > APPLIED_RESULTS_WINDOW:
            self._applied_results.popitem(last=False)

    def _compact_journal(self, force: bool = False) -> None:
        """Snapshot + truncate the event log.  The state manager is saved
        FIRST: once the journal truncates, the persisted snapshot is the
        only carrier of page statuses the dropped events described."""
        if self.journal is None:
            return
        if not force and not self.journal.should_compact():
            return
        try:
            self._state_policy.call(self.sm.save_state)
        except Exception as e:
            logger.warning("journal compaction skipped; state save "
                           "failed: %s", e)
            return
        try:
            self.journal.snapshot(self._snapshot_dict())
        except Exception as e:
            logger.error("journal snapshot failed: %s", e)

    def _find_page(self, item: WorkItem) -> Optional[Page]:
        try:
            return self.sm.get_page(item.parent_id)
        except KeyError:
            pass  # fall through to the by-url scan
        try:
            for page in self.sm.get_layer_by_depth(item.depth):
                if page.url == item.url:
                    return page
        except Exception as e:
            logger.warning("page lookup for %s failed: %s", item.id, e)
        return None

    def _resume_state(self) -> List[WorkItem]:
        """Rebuild coordination state from journal + state manager; no
        re-seed — the existing crawl continues where the dead process
        left it.  Returns the in-flight items to republish once the bus
        subscriptions are up (`_resume_requeue`)."""
        rec = self.journal.replay() if self.journal is not None \
            else RecoveredCrawl()
        # Load whatever the state manager persisted; empty seed list so a
        # backend without a persisted snapshot doesn't grow stray pages.
        self.sm.initialize([])
        # Re-add journaled pages the persisted snapshot may predate.
        # Filtered by page ID — not add_layer's URL dedup, which
        # random-walk crawls disable — so replays never duplicate layer
        # entries or clobber fresher persisted statuses.
        for depth, page_dicts in rec.layers:
            pages = []
            for d in page_dicts:
                page = Page.from_dict(d)
                try:
                    self.sm.get_page(page.id)
                except KeyError:
                    pages.append(page)
                except Exception as e:
                    logger.warning("resume: page probe failed (%s); "
                                   "skipping re-add", e)
            if not pages:
                continue
            try:
                self.sm.add_layer(pages)
            except Exception as e:
                logger.error("resume: failed to re-add layer %d: %s",
                             depth, e)
        # Replay journaled page outcomes over the (possibly stale)
        # persisted statuses.
        for page_id, (status, error) in rec.page_fixups.items():
            try:
                page = self.sm.get_page(page_id)
            except KeyError:
                continue  # page's layer event lost with a torn journal
            page.status = status
            if error:
                page.error = error
            self._update_page(page)
        with self._mu:
            self.current_depth = rec.current_depth
            self.total_work_items = rec.total_work_items
            self.completed_items = rec.completed_items
            self.error_items = rec.error_items
            self.discovered_pages = rec.discovered_pages
            self.crawl_completed = rec.crawl_completed
            self._retry_counts = dict(rec.retry_counts)
            self._applied_results = OrderedDict.fromkeys(
                sorted(rec.applied_results))
        # In-flight work: the dispatch happened but no result was
        # journaled — the result may be lost (worker died with us) or
        # still in flight.  Rebuild active_work + page state now; the
        # republish happens in `_resume_requeue` once subscriptions are
        # live.
        pending: List[WorkItem] = []
        for wid, item_dict in sorted(rec.active_work.items()):
            try:
                item = WorkItem.from_dict(item_dict)
            except Exception as e:
                logger.error("resume: undecodable journaled item %s: %s",
                             wid, e)
                continue
            with self._mu:
                self.active_work[item.id] = item
            page = self._find_page(item)
            if page is not None:
                page.status = PAGE_PROCESSING
                page.timestamp = utcnow()
                self._update_page(page)
            pending.append(item)
        # Safety sweep: PROCESSING pages nobody claims (torn dispatch
        # line, pre-journal crawls) go back to UNFETCHED so the
        # distributor re-dispatches rather than waiting forever.
        with self._mu:
            claimed = {i.parent_id for i in self.active_work.values()}
            claimed |= {i.url for i in self.active_work.values()}
        self._swept_on_resume = 0
        try:
            max_depth = self.sm.get_max_depth()
        except LookupError:
            max_depth = -1  # no layers at all: nothing to sweep
        for depth in range(max_depth + 1):
            try:
                layer = self.sm.get_layer_by_depth(depth)
            except Exception as e:
                logger.warning("resume sweep: layer %d unreadable: %s",
                               depth, e)
                continue
            for page in layer:
                if page.status == PAGE_PROCESSING \
                        and page.id not in claimed \
                        and page.url not in claimed:
                    page.status = PAGE_UNFETCHED
                    self._update_page(page)
                    self._swept_on_resume += 1
        self.resumed = True
        self._events_replayed = rec.events_replayed
        return pending

    def _resume_requeue(self, pending: List[WorkItem]) -> None:
        """Republish the resumed in-flight items at high priority under
        the SAME item id: a late result from the original delivery and
        one from the republication reconcile through active_work + the
        idempotence window.  Runs after the bus subscriptions are live."""
        requeued = 0
        for item in pending:
            with self._mu:
                if item.id not in self.active_work:
                    continue  # its result landed already
            try:
                with trace.span("orchestrator.resume_requeue",
                                trace_id=item.trace_id, work_item=item.id):
                    self._publish_policy.call(
                        self.bus.publish, TOPIC_WORK_QUEUE,
                        WorkQueueMessage.new(item, PRIORITY_HIGH,
                                             self.ocfg.work_ttl_s))
                requeued += 1
                flight.record("resume_requeue", work_item=item.id,
                              url=item.url)
            except Exception as e:
                # Leave it to the normal distributor instead.
                logger.error("resume: failed to requeue %s: %s", item.id, e)
                with self._mu:
                    self.active_work.pop(item.id, None)
                page = self._find_page(item)
                if page is not None and page.status == PAGE_PROCESSING:
                    page.status = PAGE_UNFETCHED
                    self._update_page(page)
        swept = getattr(self, "_swept_on_resume", 0)
        flight.record("orch_resume", crawl_id=self.crawl_id,
                      depth=self.current_depth, requeued=requeued,
                      swept=swept, completed=self.completed_items,
                      events_replayed=getattr(self, "_events_replayed", 0),
                      crawl_completed=self.crawl_completed)
        logger.info("resumed crawl from journal", extra={
            "crawl_id": self.crawl_id, "current_depth": self.current_depth,
            "requeued": requeued, "swept": swept,
            "completed_items": self.completed_items})
        # The resume itself is the new durable baseline.
        self._compact_journal(force=True)

    def _update_page(self, page: Page) -> None:
        """Policy-guarded page update: retries transient failures, feeds
        the breaker, and never raises into a tick loop (an OPEN circuit
        defers the write — the journal still carries the transition)."""
        try:
            self._state_policy.call(self.sm.update_page, page)
        except resilience.CircuitOpenError:
            logger.warning("state-store circuit open; page %s update "
                           "deferred", page.id)
        except Exception as e:
            logger.error("failed to update page status", extra={
                "page_url": page.url, "error": str(e)})

    def _loop(self, tick, interval_s: float) -> None:
        while self.is_running:
            deadline = self.clock() + interval_s
            # Coarse sleep in small slices so stop() is responsive.
            while self.is_running and self.clock() < deadline:
                time.sleep(0.05)
            if not self.is_running:
                return
            try:
                tick()
            except Exception as e:
                logger.error("orchestrator tick error: %s", e)

    def _health_tick(self) -> None:
        self.check_worker_health()
        self.fleet.refresh_staleness()  # bounded-memory eviction sweep
        self.watchtower.tick()
        self.requeue_stale_work()
        self._flush_deferred()
        self._compact_journal()
        self.log_progress()

    # -- co-scheduling backpressure ----------------------------------------
    def inference_backlog(self, now: Optional[datetime] = None) -> int:
        """Summed queue_length of live TPU workers — the inference-side
        backlog the crawl must not outrun.  Offline workers AND workers
        whose heartbeat is older than worker_timeout_s are excluded: a
        stale queue_length (worker died between health sweeps) must not
        hold the valve shut."""
        now = now or utcnow()
        with self._mu:
            return sum(
                w.queue_length for w in self.workers.values()
                if w.worker_type == "tpu" and w.status != WORKER_OFFLINE
                and w.last_seen is not None
                and (now - w.last_seen).total_seconds()
                <= self.ocfg.worker_timeout_s)

    def _backpressure_engaged(self) -> bool:
        """Hysteresis valve: engage at HIGH, release below LOW.  A LOW at
        or above HIGH would invert the hysteresis into per-tick chatter,
        so it is clamped to HIGH (degenerating to a plain threshold).

        An OPEN state-store circuit also engages the valve — a wedged
        backend must pause dispatch (degrade), not error the loop
        (cascade) — via its OWN latch, released the moment the breaker
        allows traffic again (it must not inherit the inference valve's
        backlog hysteresis, nor survive with that valve disabled)."""
        if self._state_policy.circuit_open:
            if not self._circuit_backpressure:
                self._circuit_backpressure = True
                flight.record("backpressure", reason="state_circuit_open",
                              target=STATE_STORE_TARGET)
                logger.warning("state-store circuit open; pausing crawl "
                               "distribution")
            return True
        if self._circuit_backpressure:
            self._circuit_backpressure = False
            logger.info("state-store circuit recovered; resuming crawl "
                        "distribution")
        # A near-full publish outbox is the broker-outage analog of the
        # state circuit: the buffered-and-retried degradation only holds
        # while there is buffer left, so dispatch pauses before the bound
        # turns publishes into OutboxFull errors.  Own latch, released
        # the moment the flusher drains back under the high-water mark.
        outbox = getattr(self.bus, "outbox", None)
        if outbox is not None:
            if self._outbox_backpressure:
                # Hysteresis: release only once the flusher has drained
                # well below the engage mark (below_low_water), so a
                # depth hovering at the boundary can't flap the valve —
                # the same discipline as the inference valve below.
                low_fn = getattr(outbox, "below_low_water", None)
                released = low_fn() if callable(low_fn) \
                    else not outbox.near_full()
                if not released:
                    return True
                self._outbox_backpressure = False
                logger.info("bus outbox drained below the low-water mark; "
                            "resuming crawl distribution")
            elif outbox.near_full():
                self._outbox_backpressure = True
                flight.record("backpressure", reason="bus_outbox_near_full",
                              depth=outbox.depth())
                logger.warning("bus outbox near its bound (%d buffered); "
                               "pausing crawl distribution", outbox.depth())
                return True
        high = self.ocfg.inference_backpressure_high
        if high <= 0:
            return False
        low = min(self.ocfg.inference_backpressure_low, high)
        backlog = self.inference_backlog()
        if self._backpressure_active:
            if backlog < low:
                self._backpressure_active = False
                logger.info("inference backlog drained; resuming crawl "
                            "distribution", extra={"backlog": backlog})
        elif backlog >= high:
            self._backpressure_active = True
            logger.warning("inference backlog high; pausing crawl "
                           "distribution", extra={
                               "backlog": backlog, "high_watermark": high})
        return self._backpressure_active

    # -- work distribution (`orchestrator.go:182-277`) ---------------------
    def distribute_work(self) -> int:
        """One distribution pass; returns the number of items published.

        The reference only advanced depth on an *empty* layer
        (`orchestrator.go:189-210`), which stalls once a layer is fully
        fetched; here a layer with no pending and no in-flight pages also
        advances.  A backed-up inference stage (TPU worker queue_length
        over the high watermark) pauses PUBLISHING — crawl admission
        follows the slowest co-scheduled stage — but never
        completion/depth bookkeeping: a crawl whose pages are all fetched
        still completes while the valve is closed.  A wedged state store
        opens the resilience circuit: the tick degrades to a no-op
        (backpressure) instead of raising."""
        if self._killed:
            return 0
        self._flush_deferred()
        # Alert evaluation rides the distribute cadence too (the
        # watchtower rate-limits itself to alert_eval_interval_s), so
        # foreground-driven orchestrators — the loadgen gate ticks
        # distribute_work directly, background=False — still alert.
        self.watchtower.tick()
        throttled = self._backpressure_engaged()
        if self.config.max_depth > 0 and \
                self.current_depth > self.config.max_depth:
            with self._mu:
                active = len(self.active_work)
            if active == 0 and not self.crawl_completed:
                logger.info("configured max depth reached",
                            extra={"max_depth": self.config.max_depth})
                self._mark_crawl_completed()
            return 0
        try:
            pages = self._state_policy.call(self.sm.get_layer_by_depth,
                                            self.current_depth)
        except resilience.CircuitOpenError:
            return 0  # backpressure engages on the next tick
        except Exception as e:
            logger.error("state-store layer read failed: %s", e)
            return 0
        pending = [p for p in pages
                   if p.status == PAGE_UNFETCHED
                   or (p.status == PAGE_ERROR and self._should_retry(p))]
        in_flight = any(p.status == PAGE_PROCESSING for p in pages)

        if not pending:
            if in_flight:
                return 0  # wait for results at this depth
            max_depth = self.sm.get_max_depth()
            if self.current_depth < max_depth:
                with self._mu:
                    self.current_depth += 1
                self._jappend("depth", depth=self.current_depth)
                logger.info("moving to next depth",
                            extra={"new_depth": self.current_depth})
                return 0
            with self._mu:
                active = len(self.active_work)
            if active == 0 and not self.crawl_completed:
                self._mark_crawl_completed()
            return 0
        if throttled:
            return 0  # pending work exists but inference must drain first
        published = 0
        for page in self._frontier_lanes(pending):
            item = self.create_work_item(page)
            with self._mu:
                self.active_work[item.id] = item
                self.total_work_items += 1
            page.status = PAGE_PROCESSING
            page.timestamp = utcnow()
            self._update_page(page)
            try:
                # The root span of the work item's trace: everything
                # downstream (bus delivery, worker processing, the result
                # leg) shares item.trace_id, so /traces shows dispatch ->
                # crawl -> result as one timeline.
                with trace.span("orchestrator.dispatch",
                                trace_id=item.trace_id, work_item=item.id,
                                depth=item.depth, platform=item.platform):
                    self._publish_policy.call(
                        self.bus.publish, TOPIC_WORK_QUEUE,
                        WorkQueueMessage.new(item,
                                             self._frontier_priority(item),
                                             self.ocfg.work_ttl_s))
                published += 1
                self._jappend("dispatch", item=item.to_dict(),
                              page_id=page.id)
                flight.record("dispatch", work_item=item.id, url=item.url,
                              depth=item.depth)
            except Exception as e:
                # Revert on publish failure (`orchestrator.go:255-268`).
                logger.error("failed to publish work item", extra={
                    "work_item_id": item.id, "error": str(e)})
                page.status = PAGE_UNFETCHED
                self._update_page(page)
                with self._mu:
                    self.active_work.pop(item.id, None)
                    self.total_work_items -= 1
        if published:
            self._compact_journal()
        return published

    def _frontier_lanes(self, pending: List[Page]) -> List[Page]:
        """Partition frontier pages into shard-owned dispatch lanes.

        With a partitioned bus (`bus/partition.py`: the bus — possibly
        behind an outbox/chaos wrapper — exposes ``shard_map``), pages
        group by the consistent hash of their CHANNEL (the same key the
        bus routes the resulting WorkQueueMessages by, so a lane's pages
        genuinely land on that lane's broker shard) and dispatch
        round-robin ACROSS lanes: publishes alternate shards instead of
        draining one channel's run into one queue, and each shard's
        outbox flushes its lane concurrently — the distribute_work
        fan-out is no longer serialized through one broker queue.  Page
        state stays coordinated through the state layer exactly as
        before (every status write goes through ``sm``); only the
        dispatch order and the broker each item rides change.  Without
        a shard map this is the identity.
        """
        smap = getattr(self.bus, "shard_map", None)
        if smap is None:
            return pending
        from ..bus.partition import channel_of

        lanes: Dict[str, List[Page]] = {}
        for page in pending:
            lanes.setdefault(
                smap.shard_for(channel_of(page.url)), []).append(page)
        counts = {sid: len(ps) for sid, ps in sorted(lanes.items())}
        with self._mu:
            changed = counts != self._frontier_lane_counts
            self._frontier_lane_counts = counts
        if changed:
            flight.record("frontier_shards", depth=self.current_depth,
                          lanes=counts)
            logger.info("frontier partitioned across %d shard lane(s): %s",
                        len(counts), counts)
        # O(n) round-robin interleave (a large pending layer re-runs
        # this every distribute tick — pop(0) shuffling would be
        # quadratic exactly at the scale this subsystem targets).
        ordered: List[Page] = []
        pools = [iter(lanes[sid]) for sid in sorted(lanes)]
        while pools:
            alive = []
            for it in pools:
                page = next(it, None)
                if page is not None:
                    ordered.append(page)
                    alive.append(it)
            pools = alive
        return ordered

    def create_work_item(self, page: Page) -> WorkItem:
        """`orchestrator.go:280-303`."""
        c = self.config
        cfg = WorkItemConfig(
            storage_root=c.storage_root, concurrency=c.concurrency,
            timeout=c.timeout, min_post_date=c.min_post_date,
            post_recency=c.post_recency, date_between_min=c.date_between_min,
            date_between_max=c.date_between_max, sample_size=c.sample_size,
            max_comments=c.max_comments, max_posts=c.max_posts,
            max_depth=c.max_depth, max_pages=c.max_pages,
            min_users=c.min_users, crawl_label=c.crawl_label,
            skip_media_download=c.skip_media_download,
            youtube_api_key=c.youtube_api_key,
            sampling_method=c.sampling_method,
            min_channel_videos=c.min_channel_videos)
        return WorkItem.new(page.url, page.depth, page.id, self.crawl_id,
                            c.platform, cfg)

    def _should_retry(self, page: Page) -> bool:
        """`orchestrator.go:306-312`, with real per-page retry tracking."""
        return self._retry_counts.get(page.id, 0) < self.ocfg.max_retries

    # -- result handling (`orchestrator.go:315-416`) -----------------------
    def handle_result_payload(self, payload: Dict[str, Any]) -> None:
        self.handle_result(ResultMessage.from_dict(payload))

    def handle_result(self, message: ResultMessage) -> None:
        if self._killed:
            return
        result = message.work_result
        with self._mu:
            if result.work_item_id in self._applied_results:
                # Idempotent apply: a result replayed across a restart
                # (bus redelivery of a frame the dead generation already
                # applied) is single-counted by work-item id.
                logger.debug("ignoring already-applied result",
                             extra={"work_item_id": result.work_item_id})
                return
            item = self.active_work.pop(result.work_item_id, None)
            if item is not None:
                self._mark_applied_locked(result.work_item_id)
                self.completed_work[result.work_item_id] = result
                if result.status == STATUS_SUCCESS:
                    self.completed_items += 1
                else:
                    self.error_items += 1
        if item is None:
            logger.warning("result for unknown work item", extra={
                "work_item_id": result.work_item_id})
            return
        flight.record("result", work_item=result.work_item_id,
                      status=result.status, worker=result.worker_id,
                      error=result.error or None)
        with trace.span("orchestrator.handle_result",
                        trace_id=item.trace_id or message.trace_id,
                        work_item=result.work_item_id, status=result.status,
                        worker=result.worker_id):
            self._apply_result(item, message, result)

    def _apply_result(self, item: WorkItem, message: ResultMessage,
                      result: WorkResult) -> None:
        applied_page: Optional[Page] = None
        try:
            layer = self._state_policy.call(self.sm.get_layer_by_depth,
                                            item.depth)
        except Exception as e:
            # Wedged store: park the whole application (page transition,
            # discovery, journal event) for the tick-loop retry.  The
            # result is NOT journaled yet, so a crash before the retry
            # leaves the item in-flight and a restart requeues it.
            logger.warning("deferring result apply for %s; state store "
                           "unavailable: %s", item.id, e)
            with self._mu:
                self._deferred_results.append((item, message, result))
                del self._deferred_results[:-DEFERRED_CAP]
            return
        for page in layer:
            if page.url != item.url:
                continue
            if result.status == STATUS_SUCCESS:
                page.status = PAGE_FETCHED
                self._retry_counts.pop(page.id, None)
            else:
                page.error = result.error
                if result.retry_recommended:
                    retries = self._retry_counts.get(page.id, 0) + 1
                    if retries >= self.ocfg.max_retries:
                        # Budget exhausted: terminal.  The retry counter
                        # is PRUNED on every terminal transition — the
                        # page's status is the durable marker, so the
                        # map stays bounded by in-flight pages.
                        page.status = PAGE_ABANDONED
                        self._retry_counts.pop(page.id, None)
                    else:
                        page.status = PAGE_ERROR
                        self._retry_counts[page.id] = retries
                else:
                    # Worker classified the failure as permanent
                    # (`worker.go:436-456`): terminal immediately.
                    page.status = PAGE_ABANDONED
                    self._retry_counts.pop(page.id, None)
            page.timestamp = result.completed_at or utcnow()
            self._update_page(page)
            applied_page = page
            break

        discovered = message.discovered_pages or result.discovered_pages
        if discovered:
            try:
                self._process_discovered(discovered, item.depth)
                with self._mu:
                    self.discovered_pages += len(discovered)
            except Exception as e:
                logger.error("failed to process discovered pages",
                             extra={"error": str(e)})
        self._jappend(
            "result", work_item_id=item.id,
            page_id=applied_page.id if applied_page is not None else "",
            status=result.status, error=result.error or "",
            page_status=applied_page.status if applied_page is not None
            else "",
            retries=(self._retry_counts.get(applied_page.id, 0)
                     if applied_page is not None else 0),
            discovered=len(discovered) if discovered else 0)
        self._compact_journal()

    def _process_discovered(self, discovered, current_depth: int) -> None:
        """`orchestrator.go:386-416`."""
        from ..state.datamodels import new_id
        pages = [Page(id=new_id(), url=dp.url, depth=current_depth + 1,
                      status=PAGE_UNFETCHED, timestamp=utcnow(),
                      parent_id=dp.parent_id)
                 for dp in discovered]
        # Journal BEFORE the store write: if the store is wedged the
        # pages are still recoverable (live via the deferred retry,
        # across a crash via the layer event).
        self._jappend("layer", depth=current_depth + 1,
                      pages=[p.to_dict() for p in pages])
        self._add_layer_or_defer(pages)
        logger.info("added discovered pages as new layer", extra={
            "count": len(pages), "new_depth": current_depth + 1})

    def _add_layer_or_defer(self, pages: List[Page]) -> None:
        try:
            self._state_policy.call(self.sm.add_layer, pages)
        except Exception as e:
            logger.warning("deferring %d discovered pages; state store "
                           "unavailable: %s", len(pages), e)
            with self._mu:
                self._deferred_layers.append(pages)
                del self._deferred_layers[:-DEFERRED_CAP]

    def _flush_deferred(self) -> None:
        """Re-attempt state-store work parked while the circuit was open
        (discovered layers, result applications).  Failures re-defer."""
        with self._mu:
            if not self._deferred_layers and not self._deferred_results:
                return
        if self._state_policy.circuit_open:
            return  # still shedding; the valve keeps dispatch paused
        with self._mu:
            layers, self._deferred_layers = self._deferred_layers, []
            results, self._deferred_results = self._deferred_results, []
        for pages in layers:
            self._add_layer_or_defer(pages)
        for item, message, result in results:
            self._apply_result(item, message, result)

    # -- distributed-trace fold (`tracecollect.py`) ------------------------
    def handle_spans_payload(self, payload: Dict[str, Any]) -> None:
        if self._killed:
            return
        self.trace_collector.observe(SpanBatchMessage.from_dict(payload))

    def get_dtraces(self, limit: int = 0) -> Dict[str, Any]:
        """The ``/dtraces`` JSON body (assembled distributed traces);
        registered via `utils.metrics.set_dtraces_provider` by the CLI."""
        return self.trace_collector.export(limit=limit)

    # -- watchtower (`watchtower.py`) --------------------------------------
    def handle_alert_payload(self, payload: Dict[str, Any]) -> None:
        """Log fleet alert announcements at the coordinator (firing at
        WARNING, the rest at INFO); never raises into the bus."""
        if self._killed:
            return
        try:
            msg = AlertMessage.from_dict(payload)
        except Exception as e:
            logger.debug("undecodable alert announcement: %s", e)
            return
        logger.log(
            logging.WARNING if msg.state == "firing" else logging.INFO,
            "fleet alert %s: %s -> %s (value=%s)",
            msg.rule, msg.prev_state, msg.state, msg.value)

    def get_alerts(self) -> Dict[str, Any]:
        """The ``/alerts`` JSON body (alert lifecycle state + log);
        registered via `utils.metrics.set_alerts_provider` by the CLI."""
        return self.watchtower.get_alerts()

    def get_tenants(self) -> Dict[str, Any]:
        """The ``/tenants`` JSON body (per-tenant spend + error budgets);
        registered via `utils.metrics.set_tenants_provider` by the CLI."""
        return self.watchtower.get_tenants()

    # -- cluster-guided frontier (`cluster/`) ------------------------------
    def handle_cluster_payload(self, payload: Dict[str, Any]) -> None:
        """Fold a ClusterUpdateMessage into the frontier-priority guide;
        never raises into the bus."""
        if self._killed:
            return
        try:
            msg = ClusterUpdateMessage.from_dict(payload)
            msg.validate()
        except Exception as e:
            logger.debug("undecodable cluster update: %s", e)
            return
        with self._mu:
            self._cluster_guide = {
                "worker_id": msg.worker_id,
                "k": msg.k,
                "step": msg.step,
                "vectors": msg.vectors,
                "underpopulated": set(int(c) for c in msg.underpopulated),
                "channel_clusters": {
                    ch.lower(): int(c)
                    for ch, c in msg.channel_clusters.items()},
                "inertia": msg.inertia,
                "received_at": self.clock(),
            }

    @staticmethod
    def _channel_of(url: str) -> str:
        """Channel name from a frontier URL — ONE rule shared with the
        partitioned bus's routing key (`bus/partition.py:channel_of`),
        so the cluster guide's channel map and the sharded frontier's
        lane assignment agree on what 'the same channel' means."""
        from ..bus.partition import channel_of

        return channel_of(url)

    def _frontier_priority(self, item: WorkItem) -> int:
        """PRIORITY_HIGH when the page's channel last landed in an
        under-populated cluster — the cluster-guided snowball: frontier
        budget flows to the sparse corners of the embedding space.
        A guide older than ``cluster_guide_ttl_s`` is ignored: a dead
        cluster worker's final snapshot must not steer dispatch
        forever."""
        with self._mu:
            guide = self._cluster_guide
        if not guide or not guide["underpopulated"]:
            return PRIORITY_MEDIUM
        ttl = self.ocfg.cluster_guide_ttl_s
        if ttl > 0 and self.clock() - guide["received_at"] > ttl:
            return PRIORITY_MEDIUM
        cluster = guide["channel_clusters"].get(self._channel_of(item.url))
        if cluster is None or cluster not in guide["underpopulated"]:
            return PRIORITY_MEDIUM
        with self._mu:
            self._cluster_prioritized += 1
        flight.record("cluster_priority", work_item=item.id, url=item.url,
                      cluster=int(cluster))
        return PRIORITY_HIGH

    # -- worker registry (`orchestrator.go:419-449`) -----------------------
    def handle_status_payload(self, payload: Dict[str, Any]) -> None:
        self.handle_status(StatusMessage.from_dict(payload))

    def handle_status(self, message: StatusMessage) -> None:
        if self._killed:
            return
        if self.fleet.observe(message):
            # Only heartbeats the fleet ACCEPTED reach the time-series
            # fold: a reordered/redelivered older frame carries lower
            # cumulative breach counts, which the store's reset-aware
            # increase() would misread as a counter restart and count
            # as phantom breaches — enough to fire a zero-budget burn
            # rule on a healthy fleet.
            self.watchtower.observe_status(message)
        with self._mu:
            worker = self.workers.get(message.worker_id)
            if worker is None:
                worker = WorkerInfo(id=message.worker_id)
                self.workers[message.worker_id] = worker
            worker.status = message.status
            worker.worker_type = message.worker_type or "crawl"
            worker.last_seen = message.timestamp or utcnow()
            worker.queue_length = message.queue_length
            worker.tasks_total = message.tasks_processed
            worker.tasks_success = message.tasks_success
            worker.tasks_error = message.tasks_error
            if message.current_work is not None:
                worker.current_work = message.current_work
                # Record the claim so failed-worker reassignment knows which
                # items this worker held (the busy heartbeat carries the
                # item id, `worker.go:255-263`).
                item = self.active_work.get(message.current_work)
                if item is not None:
                    item.assigned_to = message.worker_id
                    item.assigned_at = worker.last_seen

    # -- health monitoring (`orchestrator.go:472-559`) ---------------------
    def check_worker_health(self, now: Optional[datetime] = None) -> List[str]:
        """Mark silent workers offline and reassign their work; returns the
        failed worker IDs."""
        now = now or utcnow()
        failed: List[str] = []
        with self._mu:
            for worker_id, worker in self.workers.items():
                if worker.status == WORKER_OFFLINE or worker.last_seen is None:
                    continue
                silence = (now - worker.last_seen).total_seconds()
                if silence > self.ocfg.worker_timeout_s:
                    logger.warning("worker appears to have failed", extra={
                        "worker_id": worker_id,
                        "last_seen": str(worker.last_seen)})
                    worker.status = WORKER_OFFLINE
                    failed.append(worker_id)
                    flight.record("worker_offline", worker=worker_id,
                                  silence_s=round(silence, 1))
        if failed:
            self.reassign_work_from_failed_workers(failed)
        return failed

    def requeue_stale_work(self, now: Optional[datetime] = None) -> int:
        """Age out active work whose result never arrived within
        ``work_ttl_s`` even though its worker still heartbeats (lost frame,
        wedged handler): republish at high priority up to the retry budget,
        then drop the item and mark its page errored so the crawl can't
        stall forever on one in-flight entry."""
        now = now or utcnow()
        if self._killed:
            return 0
        with self._mu:
            stale = [i for i in self.active_work.values()
                     if i.created_at is not None and
                     (now - i.created_at).total_seconds() >
                     self.ocfg.work_ttl_s]
        requeued = 0
        for item in stale:
            if item.retry_count >= self.ocfg.max_retries:
                logger.error("abandoning stale work item past retry budget",
                             extra={"work_item_id": item.id, "url": item.url})
                with self._mu:
                    self.active_work.pop(item.id, None)
                    self.error_items += 1
                    # Abandons join the idempotence window too: their
                    # journal fold must also be replay-safe.
                    self._mark_applied_locked(item.id)
                abandoned_page_id = ""
                try:
                    layer = self._state_policy.call(
                        self.sm.get_layer_by_depth, item.depth)
                except Exception as e:
                    # Wedged store: the journaled abandon below still
                    # carries the page id, so the terminal status is
                    # replayed on resume even though the live write
                    # couldn't land.
                    logger.warning("abandon: state store unavailable "
                                   "(%s); page fixup deferred", e)
                    layer = []
                for page in layer:
                    if page.url == item.url:
                        # Terminal: abandoned pages carry no live retry
                        # counter (the status itself blocks re-dispatch).
                        page.status = PAGE_ABANDONED
                        page.error = "work item expired without result"
                        self._retry_counts.pop(page.id, None)
                        self._update_page(page)
                        abandoned_page_id = page.id
                        break
                self._jappend("abandon", work_item_id=item.id,
                              page_id=abandoned_page_id or item.parent_id,
                              page_status=PAGE_ABANDONED,
                              error="work item expired without result")
                continue
            # Rotate the item id on requeue (generation suffix) so a late
            # result from the stale attempt can't complete the fresh one —
            # and mutate under the lock so the result handler never sees a
            # half-updated entry still keyed in active_work.
            with self._mu:
                if item.id not in self.active_work:
                    continue  # result arrived between snapshot and requeue
                self.active_work.pop(item.id, None)
                fresh = replace(item,
                                id=(item.id.rsplit("#", 1)[0] +
                                    f"#{item.retry_count + 1}"),
                                retry_count=item.retry_count + 1,
                                assigned_to="", created_at=now)
                self.active_work[fresh.id] = fresh
            try:
                with trace.span("orchestrator.requeue",
                                trace_id=fresh.trace_id, work_item=fresh.id,
                                retry=fresh.retry_count):
                    self._publish_policy.call(
                        self.bus.publish, TOPIC_WORK_QUEUE,
                        WorkQueueMessage.new(fresh, PRIORITY_HIGH,
                                             self.ocfg.work_ttl_s))
                requeued += 1
                self._jappend("requeue", old_id=item.id,
                              item=fresh.to_dict(),
                              page_id=fresh.parent_id)
                flight.record("requeue", work_item=fresh.id,
                              retry=fresh.retry_count)
                logger.warning("requeued stale work item", extra={
                    "work_item_id": fresh.id,
                    "retry_count": fresh.retry_count})
            except Exception as e:
                logger.error("failed to requeue stale work item", extra={
                    "work_item_id": fresh.id, "error": str(e)})
        return requeued

    def reassign_work_from_failed_workers(self, failed: List[str]) -> int:
        """`orchestrator.go:520-559`."""
        reassigned = 0
        with self._mu:
            items = [i for i in self.active_work.values()
                     if i.assigned_to in failed]
        for item in items:
            with self._mu:
                if item.id not in self.active_work:
                    continue  # result landed before the reassignment
                self.active_work.pop(item.id, None)
                fresh = replace(item,
                                id=(item.id.rsplit("#", 1)[0] +
                                    f"#{item.retry_count + 1}"),
                                retry_count=item.retry_count + 1,
                                assigned_to="", created_at=utcnow())
                self.active_work[fresh.id] = fresh
            try:
                with trace.span("orchestrator.reassign",
                                trace_id=fresh.trace_id, work_item=fresh.id,
                                retry=fresh.retry_count):
                    self._publish_policy.call(
                        self.bus.publish, TOPIC_WORK_QUEUE,
                        WorkQueueMessage.new(fresh, PRIORITY_HIGH,
                                             self.ocfg.work_ttl_s))
                reassigned += 1
                self._jappend("reassign", old_id=item.id,
                              item=fresh.to_dict(),
                              page_id=fresh.parent_id)
                flight.record("reassign", work_item=fresh.id,
                              retry=fresh.retry_count)
                logger.info("reassigned work item from failed worker", extra={
                    "work_item_id": fresh.id, "retry_count": fresh.retry_count})
            except Exception as e:
                logger.error("failed to reassign work item", extra={
                    "work_item_id": fresh.id, "error": str(e)})
        return reassigned

    # -- progress / status (`orchestrator.go:562-633`) ---------------------
    def _mark_crawl_completed(self) -> None:
        with self._mu:
            self.crawl_completed = True
        self._jappend("completed")
        metadata = {
            "status": "completed",
            "end_time": utcnow().isoformat(),
            "total_work_items": self.total_work_items,
            "completed_items": self.completed_items,
            "error_items": self.error_items,
            "discovered_pages": self.discovered_pages,
            "max_depth_reached": self.current_depth,
            "duration_s": self.clock() - self._started_at,
        }
        try:
            self.sm.update_crawl_metadata(self.crawl_id, metadata)
        except Exception as e:
            logger.error("failed to update crawl completion metadata",
                         extra={"error": str(e)})
        flight.record("crawl_completed", crawl_id=self.crawl_id,
                      completed=self.completed_items,
                      errors=self.error_items)
        logger.info("crawl marked as completed", extra={"stats": metadata})

    def log_progress(self) -> None:
        with self._mu:
            active_workers = sum(
                1 for w in self.workers.values()
                if w.status in (WORKER_ACTIVE, WORKER_BUSY, WORKER_IDLE))
            logger.info("crawl progress status", extra={
                "current_depth": self.current_depth,
                "active_work": len(self.active_work),
                "completed_work": self.completed_items,
                "error_work": self.error_items,
                "total_work": self.total_work_items,
                "total_workers": len(self.workers),
                "active_workers": active_workers,
                "discovered_pages": self.discovered_pages,
                "uptime_s": self.clock() - self._started_at})

    def get_status(self) -> Dict[str, Any]:
        """`orchestrator.go:596-633`."""
        backlog = self.inference_backlog()
        with self._mu:
            tpu = {k: w for k, w in self.workers.items()
                   if w.worker_type == "tpu"}
            return {
                "crawl_id": self.crawl_id,
                "is_running": self._running,
                "platform": self.config.platform,
                "current_depth": self.current_depth,
                "worker_count": len(self.workers),
                "crawl_worker_count": len(self.workers) - len(tpu),
                "tpu_worker_count": len(tpu),
                "inference_backlog": backlog,
                "backpressure_active": (self._backpressure_active or self._circuit_backpressure),
                "state_circuit": self._state_policy.breaker.state,
                "resumed": self.resumed,
                "frontier_lanes": dict(self._frontier_lane_counts) or None,
                "cluster_guide": {
                    "step": self._cluster_guide["step"],
                    "vectors": self._cluster_guide["vectors"],
                    "underpopulated": sorted(
                        self._cluster_guide["underpopulated"]),
                    "channels_mapped": len(
                        self._cluster_guide["channel_clusters"]),
                    "prioritized_items": self._cluster_prioritized,
                } if self._cluster_guide else None,
                "workers": {k: vars(v).copy()
                            for k, v in self.workers.items()},
                "work_stats": {
                    "active_work": len(self.active_work),
                    "completed_work": len(self.completed_work),
                    "total_work": self.total_work_items,
                    "completed_items": self.completed_items,
                    "error_items": self.error_items,
                    "discovered_pages": self.discovered_pages,
                },
                "uptime_s": self.clock() - self._started_at,
                "crawl_completed": self.crawl_completed,
            }

    def get_cluster(self) -> Dict[str, Any]:
        """The ``/cluster`` JSON body: the FleetView's per-worker fold
        (telemetry, rates, history, staleness) plus the orchestrator-side
        work summary — one page answering "what is the fleet doing".
        Registered via `utils.metrics.set_cluster_provider` by the CLI."""
        out = self.fleet.export()
        with self._mu:
            out["orchestrator"] = {
                "crawl_id": self.crawl_id,
                "is_running": self._running,
                "current_depth": self.current_depth,
                "active_work": len(self.active_work),
                "completed_items": self.completed_items,
                "error_items": self.error_items,
                "backpressure_active": (self._backpressure_active or self._circuit_backpressure),
                "uptime_s": self.clock() - self._started_at,
            }
        return out
