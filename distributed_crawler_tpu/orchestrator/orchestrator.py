"""The orchestrator: BFS work distribution, result fan-in, worker health.

Parity with the reference's `orchestrator/orchestrator.go` (633 LoC):
- work distributor ticking every 5 s over the current BFS depth (`:160-277`)
- work-item creation from `state.Page` (`:280-303`)
- result handling -> page status update + new-layer creation (`:315-416`)
- worker registry built from status messages (`:419-449`)
- health monitor: 5-min last-seen timeout -> offline -> republish that
  worker's items at high priority with retry counts (`:472-559`)
- progress logging + `get_status` snapshot (`:562-633`)

Tick methods (`distribute_work`, `check_worker_health`, `log_progress`) are
public and side-effect-complete so tests drive them deterministically without
timers; `start()` wires the same methods to background threads.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field, replace
from datetime import datetime
from typing import Any, Dict, List, Optional

from ..bus.messages import (
    PRIORITY_HIGH,
    PRIORITY_MEDIUM,
    STATUS_SUCCESS,
    TOPIC_RESULTS,
    TOPIC_WORK_QUEUE,
    TOPIC_WORKER_STATUS,
    WORKER_ACTIVE,
    WORKER_BUSY,
    WORKER_IDLE,
    WORKER_OFFLINE,
    ResultMessage,
    StatusMessage,
    WorkItem,
    WorkItemConfig,
    WorkQueueMessage,
    WorkResult,
)
from .fleet import FleetView
from ..config.crawler import CrawlerConfig
from ..utils import flight, trace
from ..state.datamodels import (
    PAGE_ERROR,
    PAGE_FETCHED,
    PAGE_PROCESSING,
    PAGE_UNFETCHED,
    Page,
    utcnow,
)

logger = logging.getLogger("dct.orchestrator")


@dataclass
class OrchestratorConfig:
    """Timing knobs (`orchestrator.go:163,477,498` + config/distributed.go)."""

    distribute_interval_s: float = 5.0
    health_interval_s: float = 30.0
    worker_timeout_s: float = 300.0  # 5 min (`orchestrator.go:498`)
    max_retries: int = 3
    work_ttl_s: int = 3600
    # Co-scheduling backpressure (north star: crawl + inference shards on
    # one slice): when the summed queue_length of live TPU workers crosses
    # the HIGH watermark, crawl work distribution pauses; it resumes once
    # the backlog drains below LOW (hysteresis so the valve doesn't
    # chatter).  high=0 disables the valve.
    inference_backpressure_high: int = 64
    inference_backpressure_low: int = 32


@dataclass
class WorkerInfo:
    """Tracked per-worker state (`orchestrator.go:46-56`)."""

    id: str = ""
    status: str = WORKER_IDLE
    worker_type: str = "crawl"  # "crawl" | "tpu" (StatusMessage.worker_type)
    last_seen: Optional[datetime] = None
    current_work: Optional[str] = None
    queue_length: int = 0  # TPU workers: pending inference batches
    tasks_total: int = 0
    tasks_success: int = 0
    tasks_error: int = 0
    metadata: Dict[str, Any] = field(default_factory=dict)


class Orchestrator:
    """Central coordinator (`orchestrator.go:26-106`)."""

    def __init__(self, crawl_id: str, config: CrawlerConfig, bus, sm,
                 ocfg: Optional[OrchestratorConfig] = None,
                 clock=time.monotonic):
        self.crawl_id = crawl_id
        self.config = config
        self.bus = bus
        self.sm = sm
        self.ocfg = ocfg or OrchestratorConfig()
        self.clock = clock

        self.workers: Dict[str, WorkerInfo] = {}
        self.active_work: Dict[str, WorkItem] = {}
        self.completed_work: Dict[str, WorkResult] = {}
        self.current_depth = 0
        self.total_work_items = 0
        self.completed_items = 0
        self.error_items = 0
        self.discovered_pages = 0
        self.crawl_completed = False
        self._retry_counts: Dict[str, int] = {}  # page id -> retries
        self._backpressure_active = False
        # Telemetry-rich per-worker fold behind /cluster; its staleness
        # rule tracks the same timeout check_worker_health enforces.
        self.fleet = FleetView(stale_after_s=self.ocfg.worker_timeout_s)

        self._mu = threading.RLock()
        self._running = False
        self._threads: List[threading.Thread] = []
        self._started_at = 0.0

    # -- lifecycle ---------------------------------------------------------
    def start(self, seed_urls: List[str], background: bool = True) -> None:
        """`orchestrator.go:106-137`."""
        with self._mu:
            if self._running:
                raise RuntimeError("orchestrator is already running")
            self._running = True
        self._started_at = self.clock()
        self.sm.initialize(seed_urls)
        self.bus.subscribe(TOPIC_RESULTS, self.handle_result_payload)
        self.bus.subscribe(TOPIC_WORKER_STATUS, self.handle_status_payload)
        if background:
            for target, interval, name in (
                    (self.distribute_work, self.ocfg.distribute_interval_s,
                     "orch-distribute"),
                    (self._health_tick, self.ocfg.health_interval_s,
                     "orch-health")):
                t = threading.Thread(target=self._loop,
                                     args=(target, interval), daemon=True,
                                     name=name)
                t.start()
                self._threads.append(t)
        logger.info("orchestrator started", extra={
            "crawl_id": self.crawl_id, "seed_count": len(seed_urls)})

    def stop(self) -> None:
        with self._mu:
            self._running = False
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()
        self.sm.close()
        logger.info("orchestrator stopped", extra={"crawl_id": self.crawl_id})

    @property
    def is_running(self) -> bool:
        with self._mu:
            return self._running

    def _loop(self, tick, interval_s: float) -> None:
        while self.is_running:
            deadline = self.clock() + interval_s
            # Coarse sleep in small slices so stop() is responsive.
            while self.is_running and self.clock() < deadline:
                time.sleep(0.05)
            if not self.is_running:
                return
            try:
                tick()
            except Exception as e:
                logger.error("orchestrator tick error: %s", e)

    def _health_tick(self) -> None:
        self.check_worker_health()
        self.fleet.refresh_staleness()  # keep the gauge live for /metrics
        self.requeue_stale_work()
        self.log_progress()

    # -- co-scheduling backpressure ----------------------------------------
    def inference_backlog(self, now: Optional[datetime] = None) -> int:
        """Summed queue_length of live TPU workers — the inference-side
        backlog the crawl must not outrun.  Offline workers AND workers
        whose heartbeat is older than worker_timeout_s are excluded: a
        stale queue_length (worker died between health sweeps) must not
        hold the valve shut."""
        now = now or utcnow()
        with self._mu:
            return sum(
                w.queue_length for w in self.workers.values()
                if w.worker_type == "tpu" and w.status != WORKER_OFFLINE
                and w.last_seen is not None
                and (now - w.last_seen).total_seconds()
                <= self.ocfg.worker_timeout_s)

    def _backpressure_engaged(self) -> bool:
        """Hysteresis valve: engage at HIGH, release below LOW.  A LOW at
        or above HIGH would invert the hysteresis into per-tick chatter,
        so it is clamped to HIGH (degenerating to a plain threshold)."""
        high = self.ocfg.inference_backpressure_high
        if high <= 0:
            return False
        low = min(self.ocfg.inference_backpressure_low, high)
        backlog = self.inference_backlog()
        if self._backpressure_active:
            if backlog < low:
                self._backpressure_active = False
                logger.info("inference backlog drained; resuming crawl "
                            "distribution", extra={"backlog": backlog})
        elif backlog >= high:
            self._backpressure_active = True
            logger.warning("inference backlog high; pausing crawl "
                           "distribution", extra={
                               "backlog": backlog, "high_watermark": high})
        return self._backpressure_active

    # -- work distribution (`orchestrator.go:182-277`) ---------------------
    def distribute_work(self) -> int:
        """One distribution pass; returns the number of items published.

        The reference only advanced depth on an *empty* layer
        (`orchestrator.go:189-210`), which stalls once a layer is fully
        fetched; here a layer with no pending and no in-flight pages also
        advances.  A backed-up inference stage (TPU worker queue_length
        over the high watermark) pauses PUBLISHING — crawl admission
        follows the slowest co-scheduled stage — but never
        completion/depth bookkeeping: a crawl whose pages are all fetched
        still completes while the valve is closed."""
        throttled = self._backpressure_engaged()
        if self.config.max_depth > 0 and \
                self.current_depth > self.config.max_depth:
            with self._mu:
                active = len(self.active_work)
            if active == 0 and not self.crawl_completed:
                logger.info("configured max depth reached",
                            extra={"max_depth": self.config.max_depth})
                self._mark_crawl_completed()
            return 0
        pages = self.sm.get_layer_by_depth(self.current_depth)
        pending = [p for p in pages
                   if p.status == PAGE_UNFETCHED
                   or (p.status == PAGE_ERROR and self._should_retry(p))]
        in_flight = any(p.status == PAGE_PROCESSING for p in pages)

        if not pending:
            if in_flight:
                return 0  # wait for results at this depth
            max_depth = self.sm.get_max_depth()
            if self.current_depth < max_depth:
                self.current_depth += 1
                logger.info("moving to next depth",
                            extra={"new_depth": self.current_depth})
                return 0
            with self._mu:
                active = len(self.active_work)
            if active == 0 and not self.crawl_completed:
                self._mark_crawl_completed()
            return 0
        if throttled:
            return 0  # pending work exists but inference must drain first
        published = 0
        for page in pending:
            item = self.create_work_item(page)
            with self._mu:
                self.active_work[item.id] = item
                self.total_work_items += 1
            page.status = PAGE_PROCESSING
            page.timestamp = utcnow()
            try:
                self.sm.update_page(page)
            except Exception as e:
                logger.error("failed to update page status", extra={
                    "page_url": page.url, "error": str(e)})
            try:
                # The root span of the work item's trace: everything
                # downstream (bus delivery, worker processing, the result
                # leg) shares item.trace_id, so /traces shows dispatch ->
                # crawl -> result as one timeline.
                with trace.span("orchestrator.dispatch",
                                trace_id=item.trace_id, work_item=item.id,
                                depth=item.depth, platform=item.platform):
                    self.bus.publish(TOPIC_WORK_QUEUE,
                                     WorkQueueMessage.new(
                                         item, PRIORITY_MEDIUM,
                                         self.ocfg.work_ttl_s))
                published += 1
                flight.record("dispatch", work_item=item.id, url=item.url,
                              depth=item.depth)
            except Exception as e:
                # Revert on publish failure (`orchestrator.go:255-268`).
                logger.error("failed to publish work item", extra={
                    "work_item_id": item.id, "error": str(e)})
                page.status = PAGE_UNFETCHED
                try:
                    self.sm.update_page(page)
                except Exception as revert_err:
                    logger.error("failed to revert page status", extra={
                        "page_url": page.url, "error": str(revert_err)})
                with self._mu:
                    self.active_work.pop(item.id, None)
                    self.total_work_items -= 1
        return published

    def create_work_item(self, page: Page) -> WorkItem:
        """`orchestrator.go:280-303`."""
        c = self.config
        cfg = WorkItemConfig(
            storage_root=c.storage_root, concurrency=c.concurrency,
            timeout=c.timeout, min_post_date=c.min_post_date,
            post_recency=c.post_recency, date_between_min=c.date_between_min,
            date_between_max=c.date_between_max, sample_size=c.sample_size,
            max_comments=c.max_comments, max_posts=c.max_posts,
            max_depth=c.max_depth, max_pages=c.max_pages,
            min_users=c.min_users, crawl_label=c.crawl_label,
            skip_media_download=c.skip_media_download,
            youtube_api_key=c.youtube_api_key,
            sampling_method=c.sampling_method,
            min_channel_videos=c.min_channel_videos)
        return WorkItem.new(page.url, page.depth, page.id, self.crawl_id,
                            c.platform, cfg)

    def _should_retry(self, page: Page) -> bool:
        """`orchestrator.go:306-312`, with real per-page retry tracking."""
        return self._retry_counts.get(page.id, 0) < self.ocfg.max_retries

    # -- result handling (`orchestrator.go:315-416`) -----------------------
    def handle_result_payload(self, payload: Dict[str, Any]) -> None:
        self.handle_result(ResultMessage.from_dict(payload))

    def handle_result(self, message: ResultMessage) -> None:
        result = message.work_result
        with self._mu:
            item = self.active_work.pop(result.work_item_id, None)
            if item is not None:
                self.completed_work[result.work_item_id] = result
                if result.status == STATUS_SUCCESS:
                    self.completed_items += 1
                else:
                    self.error_items += 1
        if item is None:
            logger.warning("result for unknown work item", extra={
                "work_item_id": result.work_item_id})
            return
        flight.record("result", work_item=result.work_item_id,
                      status=result.status, worker=result.worker_id,
                      error=result.error or None)
        with trace.span("orchestrator.handle_result",
                        trace_id=item.trace_id or message.trace_id,
                        work_item=result.work_item_id, status=result.status,
                        worker=result.worker_id):
            self._apply_result(item, message, result)

    def _apply_result(self, item: WorkItem, message: ResultMessage,
                      result: WorkResult) -> None:
        for page in self.sm.get_layer_by_depth(item.depth):
            if page.url != item.url:
                continue
            if result.status == STATUS_SUCCESS:
                page.status = PAGE_FETCHED
                self._retry_counts.pop(page.id, None)
            else:
                page.status = PAGE_ERROR
                page.error = result.error
                if result.retry_recommended:
                    self._retry_counts[page.id] = \
                        self._retry_counts.get(page.id, 0) + 1
                else:
                    # Worker classified the failure as permanent
                    # (`worker.go:436-456`): exhaust the retry budget.
                    self._retry_counts[page.id] = self.ocfg.max_retries
            page.timestamp = result.completed_at or utcnow()
            try:
                self.sm.update_page(page)
            except Exception as e:
                logger.error("failed to update page after result", extra={
                    "url": page.url, "error": str(e)})
            break

        discovered = message.discovered_pages or result.discovered_pages
        if discovered:
            try:
                self._process_discovered(discovered, item.depth)
                with self._mu:
                    self.discovered_pages += len(discovered)
            except Exception as e:
                logger.error("failed to process discovered pages",
                             extra={"error": str(e)})

    def _process_discovered(self, discovered, current_depth: int) -> None:
        """`orchestrator.go:386-416`."""
        from ..state.datamodels import new_id
        pages = [Page(id=new_id(), url=dp.url, depth=current_depth + 1,
                      status=PAGE_UNFETCHED, timestamp=utcnow(),
                      parent_id=dp.parent_id)
                 for dp in discovered]
        self.sm.add_layer(pages)
        logger.info("added discovered pages as new layer", extra={
            "count": len(pages), "new_depth": current_depth + 1})

    # -- worker registry (`orchestrator.go:419-449`) -----------------------
    def handle_status_payload(self, payload: Dict[str, Any]) -> None:
        self.handle_status(StatusMessage.from_dict(payload))

    def handle_status(self, message: StatusMessage) -> None:
        self.fleet.observe(message)
        with self._mu:
            worker = self.workers.get(message.worker_id)
            if worker is None:
                worker = WorkerInfo(id=message.worker_id)
                self.workers[message.worker_id] = worker
            worker.status = message.status
            worker.worker_type = message.worker_type or "crawl"
            worker.last_seen = message.timestamp or utcnow()
            worker.queue_length = message.queue_length
            worker.tasks_total = message.tasks_processed
            worker.tasks_success = message.tasks_success
            worker.tasks_error = message.tasks_error
            if message.current_work is not None:
                worker.current_work = message.current_work
                # Record the claim so failed-worker reassignment knows which
                # items this worker held (the busy heartbeat carries the
                # item id, `worker.go:255-263`).
                item = self.active_work.get(message.current_work)
                if item is not None:
                    item.assigned_to = message.worker_id
                    item.assigned_at = worker.last_seen

    # -- health monitoring (`orchestrator.go:472-559`) ---------------------
    def check_worker_health(self, now: Optional[datetime] = None) -> List[str]:
        """Mark silent workers offline and reassign their work; returns the
        failed worker IDs."""
        now = now or utcnow()
        failed: List[str] = []
        with self._mu:
            for worker_id, worker in self.workers.items():
                if worker.status == WORKER_OFFLINE or worker.last_seen is None:
                    continue
                silence = (now - worker.last_seen).total_seconds()
                if silence > self.ocfg.worker_timeout_s:
                    logger.warning("worker appears to have failed", extra={
                        "worker_id": worker_id,
                        "last_seen": str(worker.last_seen)})
                    worker.status = WORKER_OFFLINE
                    failed.append(worker_id)
                    flight.record("worker_offline", worker=worker_id,
                                  silence_s=round(silence, 1))
        if failed:
            self.reassign_work_from_failed_workers(failed)
        return failed

    def requeue_stale_work(self, now: Optional[datetime] = None) -> int:
        """Age out active work whose result never arrived within
        ``work_ttl_s`` even though its worker still heartbeats (lost frame,
        wedged handler): republish at high priority up to the retry budget,
        then drop the item and mark its page errored so the crawl can't
        stall forever on one in-flight entry."""
        now = now or utcnow()
        with self._mu:
            stale = [i for i in self.active_work.values()
                     if i.created_at is not None and
                     (now - i.created_at).total_seconds() >
                     self.ocfg.work_ttl_s]
        requeued = 0
        for item in stale:
            if item.retry_count >= self.ocfg.max_retries:
                logger.error("abandoning stale work item past retry budget",
                             extra={"work_item_id": item.id, "url": item.url})
                with self._mu:
                    self.active_work.pop(item.id, None)
                    self.error_items += 1
                for page in self.sm.get_layer_by_depth(item.depth):
                    if page.url == item.url:
                        page.status = PAGE_ERROR
                        page.error = "work item expired without result"
                        self._retry_counts[page.id] = self.ocfg.max_retries
                        try:
                            self.sm.update_page(page)
                        except Exception as e:
                            logger.error("failed to mark expired page: %s", e)
                        break
                continue
            # Rotate the item id on requeue (generation suffix) so a late
            # result from the stale attempt can't complete the fresh one —
            # and mutate under the lock so the result handler never sees a
            # half-updated entry still keyed in active_work.
            with self._mu:
                if item.id not in self.active_work:
                    continue  # result arrived between snapshot and requeue
                self.active_work.pop(item.id, None)
                fresh = replace(item,
                                id=(item.id.rsplit("#", 1)[0] +
                                    f"#{item.retry_count + 1}"),
                                retry_count=item.retry_count + 1,
                                assigned_to="", created_at=now)
                self.active_work[fresh.id] = fresh
            try:
                with trace.span("orchestrator.requeue",
                                trace_id=fresh.trace_id, work_item=fresh.id,
                                retry=fresh.retry_count):
                    self.bus.publish(TOPIC_WORK_QUEUE,
                                     WorkQueueMessage.new(
                                         fresh, PRIORITY_HIGH,
                                         self.ocfg.work_ttl_s))
                requeued += 1
                flight.record("requeue", work_item=fresh.id,
                              retry=fresh.retry_count)
                logger.warning("requeued stale work item", extra={
                    "work_item_id": fresh.id,
                    "retry_count": fresh.retry_count})
            except Exception as e:
                logger.error("failed to requeue stale work item", extra={
                    "work_item_id": fresh.id, "error": str(e)})
        return requeued

    def reassign_work_from_failed_workers(self, failed: List[str]) -> int:
        """`orchestrator.go:520-559`."""
        reassigned = 0
        with self._mu:
            items = [i for i in self.active_work.values()
                     if i.assigned_to in failed]
        for item in items:
            with self._mu:
                if item.id not in self.active_work:
                    continue  # result landed before the reassignment
                self.active_work.pop(item.id, None)
                fresh = replace(item,
                                id=(item.id.rsplit("#", 1)[0] +
                                    f"#{item.retry_count + 1}"),
                                retry_count=item.retry_count + 1,
                                assigned_to="", created_at=utcnow())
                self.active_work[fresh.id] = fresh
            try:
                with trace.span("orchestrator.reassign",
                                trace_id=fresh.trace_id, work_item=fresh.id,
                                retry=fresh.retry_count):
                    self.bus.publish(TOPIC_WORK_QUEUE,
                                     WorkQueueMessage.new(
                                         fresh, PRIORITY_HIGH,
                                         self.ocfg.work_ttl_s))
                reassigned += 1
                flight.record("reassign", work_item=fresh.id,
                              retry=fresh.retry_count)
                logger.info("reassigned work item from failed worker", extra={
                    "work_item_id": fresh.id, "retry_count": fresh.retry_count})
            except Exception as e:
                logger.error("failed to reassign work item", extra={
                    "work_item_id": fresh.id, "error": str(e)})
        return reassigned

    # -- progress / status (`orchestrator.go:562-633`) ---------------------
    def _mark_crawl_completed(self) -> None:
        self.crawl_completed = True
        metadata = {
            "status": "completed",
            "end_time": utcnow().isoformat(),
            "total_work_items": self.total_work_items,
            "completed_items": self.completed_items,
            "error_items": self.error_items,
            "discovered_pages": self.discovered_pages,
            "max_depth_reached": self.current_depth,
            "duration_s": self.clock() - self._started_at,
        }
        try:
            self.sm.update_crawl_metadata(self.crawl_id, metadata)
        except Exception as e:
            logger.error("failed to update crawl completion metadata",
                         extra={"error": str(e)})
        flight.record("crawl_completed", crawl_id=self.crawl_id,
                      completed=self.completed_items,
                      errors=self.error_items)
        logger.info("crawl marked as completed", extra={"stats": metadata})

    def log_progress(self) -> None:
        with self._mu:
            active_workers = sum(
                1 for w in self.workers.values()
                if w.status in (WORKER_ACTIVE, WORKER_BUSY, WORKER_IDLE))
            logger.info("crawl progress status", extra={
                "current_depth": self.current_depth,
                "active_work": len(self.active_work),
                "completed_work": self.completed_items,
                "error_work": self.error_items,
                "total_work": self.total_work_items,
                "total_workers": len(self.workers),
                "active_workers": active_workers,
                "discovered_pages": self.discovered_pages,
                "uptime_s": self.clock() - self._started_at})

    def get_status(self) -> Dict[str, Any]:
        """`orchestrator.go:596-633`."""
        backlog = self.inference_backlog()
        with self._mu:
            tpu = {k: w for k, w in self.workers.items()
                   if w.worker_type == "tpu"}
            return {
                "crawl_id": self.crawl_id,
                "is_running": self._running,
                "platform": self.config.platform,
                "current_depth": self.current_depth,
                "worker_count": len(self.workers),
                "crawl_worker_count": len(self.workers) - len(tpu),
                "tpu_worker_count": len(tpu),
                "inference_backlog": backlog,
                "backpressure_active": self._backpressure_active,
                "workers": {k: vars(v).copy()
                            for k, v in self.workers.items()},
                "work_stats": {
                    "active_work": len(self.active_work),
                    "completed_work": len(self.completed_work),
                    "total_work": self.total_work_items,
                    "completed_items": self.completed_items,
                    "error_items": self.error_items,
                    "discovered_pages": self.discovered_pages,
                },
                "uptime_s": self.clock() - self._started_at,
                "crawl_completed": self.crawl_completed,
            }

    def get_cluster(self) -> Dict[str, Any]:
        """The ``/cluster`` JSON body: the FleetView's per-worker fold
        (telemetry, rates, history, staleness) plus the orchestrator-side
        work summary — one page answering "what is the fleet doing".
        Registered via `utils.metrics.set_cluster_provider` by the CLI."""
        out = self.fleet.export()
        with self._mu:
            out["orchestrator"] = {
                "crawl_id": self.crawl_id,
                "is_running": self._running,
                "current_depth": self.current_depth,
                "active_work": len(self.active_work),
                "completed_items": self.completed_items,
                "error_items": self.error_items,
                "backpressure_active": self._backpressure_active,
                "uptime_s": self.clock() - self._started_at,
            }
        return out
