"""FleetView: fold heartbeats into one queryable picture of every worker.

The orchestrator's `WorkerInfo` registry (`orchestrator.py`) keeps exactly
what work distribution needs: status, last_seen, queue_length.  The fleet
questions that matter at TPU-serving scale — device-memory headroom per
worker, compile-cache churn, batch-outcome mix, per-stage latency, *was
this worker flapping before it died* — need the telemetry-rich heartbeats
(`utils/telemetry.py`) folded into per-worker state with history:

- last accepted heartbeat + the full ``resource_usage`` telemetry map,
- a bounded status-history ring of (timestamp, status, queue_length)
  transitions (flap detection, postmortem timelines),
- rates derived from task-counter deltas between consecutive heartbeats,
- an out-of-order guard: a heartbeat whose timestamp is older than the
  newest accepted one is counted (``stale_dropped``) but never regresses
  ``last_seen`` or the rates — gRPC redelivery and competing brokers can
  reorder frames,
- labeled fleet gauges (`fleet_worker_queue_length{worker_id=…}`,
  `fleet_worker_device_mem_bytes{worker_id=…,kind=…}`) so Prometheus sees
  per-worker series without scraping every worker individually,
- a staleness rollup mirroring `check_worker_health`'s timeout rule.

Served as JSON at the metrics server's ``/cluster`` endpoint through the
same late-bound provider seam ``/status`` uses (`utils/metrics.py`).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from datetime import datetime
from typing import Any, Deque, Dict, Optional, Tuple

from ..bus.messages import (
    MSG_WORKER_STOPPING,
    StatusMessage,
    WORKER_OFFLINE,
)
from ..state.datamodels import utcnow
from ..utils.metrics import REGISTRY, MetricsRegistry

DEFAULT_HISTORY = 64  # status transitions kept per worker


@dataclass
class WorkerTrack:
    """Everything FleetView knows about one worker."""

    worker_id: str
    worker_type: str = "crawl"
    status: str = ""
    first_seen: Optional[datetime] = None
    last_seen: Optional[datetime] = None
    current_work: Optional[str] = None
    queue_length: int = 0
    tasks_processed: int = 0
    tasks_success: int = 0
    tasks_error: int = 0
    uptime_s: float = 0.0
    heartbeats: int = 0
    stale_dropped: int = 0     # out-of-order heartbeats ignored
    telemetry: Dict[str, Any] = field(default_factory=dict)
    # Estimated sender-clock offset (receiver wall − heartbeat send wall,
    # seconds): the min-|sample| over recent beats, because bus transit
    # only ever inflates |recv − send| — the smallest-magnitude sample is
    # the closest to the true skew.  The TraceCollector adds this to a
    # worker's span walls to land them on the orchestrator's clock.
    clock_offset_s: float = 0.0
    offset_samples: Deque[float] = field(
        default_factory=lambda: deque(maxlen=16))
    # (iso_ts, status, queue_length) ring — appended on CHANGE, not on
    # every beat, so a stable worker's history is its life story, not noise.
    history: Deque[Tuple[str, str, int]] = field(
        default_factory=lambda: deque(maxlen=DEFAULT_HISTORY))
    # task-counter deltas between consecutive accepted heartbeats
    tasks_per_s: float = 0.0
    errors_per_s: float = 0.0


class FleetView:
    """Thread-safe heartbeat fold; the data behind ``/cluster``."""

    def __init__(self, stale_after_s: float = 300.0,
                 history: int = DEFAULT_HISTORY,
                 registry: MetricsRegistry = REGISTRY):
        self.stale_after_s = stale_after_s
        self.history = history
        self._mu = threading.Lock()
        self._workers: Dict[str, WorkerTrack] = {}
        self.m_queue = registry.gauge(
            "fleet_worker_queue_length",
            "per-worker queue length from the last heartbeat")
        self.m_devmem = registry.gauge(
            "fleet_worker_device_mem_bytes",
            "per-worker device memory (kind=in_use|limit|peak, summed "
            "over the worker's devices)")
        self.m_rss = registry.gauge(
            "fleet_worker_rss_bytes", "per-worker process RSS")
        self.m_mfu = registry.gauge(
            "fleet_worker_mfu",
            "per-worker rolling MFU from the last heartbeat's efficiency "
            "telemetry (utils/costmodel.py)")
        self.m_goodput = registry.gauge(
            "fleet_worker_goodput_tokens_per_s",
            "per-worker rolling real-token throughput from the last "
            "heartbeat")
        self.m_stale = registry.gauge(
            "fleet_stale_workers",
            "workers whose last heartbeat is older than the timeout")
        # Staleness is a function of NOW, not of the last health tick: a
        # stored gauge refreshed every health_interval_s would let a
        # /metrics (or /cluster) scrape between ticks report a worker
        # healthy after its heartbeat deadline had already lapsed.  The
        # fn-bound gauge recomputes at every read.
        self.m_stale.set_fn(self.stale_count)

    # -- folding -------------------------------------------------------------
    def observe(self, msg: StatusMessage,
                now: Optional[datetime] = None) -> bool:
        """Fold one heartbeat; returns False when it was dropped as
        out-of-order (older than the newest accepted beat)."""
        now = now or utcnow()
        ts = msg.timestamp or now
        with self._mu:
            track = self._workers.get(msg.worker_id)
            if track is None:
                track = WorkerTrack(worker_id=msg.worker_id, first_seen=ts)
                track.history = deque(maxlen=self.history)
                self._workers[msg.worker_id] = track
            if track.last_seen is not None and ts < track.last_seen:
                track.stale_dropped += 1
                return False
            status = (WORKER_OFFLINE
                      if msg.message_type == MSG_WORKER_STOPPING
                      else msg.status)
            prev_seen, prev_tasks, prev_errors = (
                track.last_seen, track.tasks_processed, track.tasks_error)
            if status != track.status or \
                    msg.queue_length != track.queue_length:
                track.history.append(
                    (ts.isoformat(), status, msg.queue_length))
            track.worker_type = msg.worker_type or track.worker_type
            track.status = status
            track.last_seen = ts
            track.current_work = msg.current_work
            track.queue_length = msg.queue_length
            track.tasks_processed = msg.tasks_processed
            track.tasks_success = msg.tasks_success
            track.tasks_error = msg.tasks_error
            track.uptime_s = msg.uptime_s
            track.heartbeats += 1
            if msg.timestamp is not None:
                # Clock-offset sample: this beat's receive − send wall.
                track.offset_samples.append(
                    (now - msg.timestamp).total_seconds())
                track.clock_offset_s = min(track.offset_samples, key=abs)
            if msg.resource_usage:
                track.telemetry = msg.resource_usage
            if prev_seen is not None:
                dt = (ts - prev_seen).total_seconds()
                if dt > 0:
                    d_tasks = msg.tasks_processed - prev_tasks
                    d_errors = msg.tasks_error - prev_errors
                    if d_tasks < 0 or d_errors < 0:
                        # Counter regression = the worker restarted under
                        # the same id; its fresh counts ARE the delta
                        # since restart (a raw difference would show a
                        # large negative rate until the next beat).
                        d_tasks, d_errors = (msg.tasks_processed,
                                             msg.tasks_error)
                    track.tasks_per_s = round(d_tasks / dt, 4)
                    track.errors_per_s = round(d_errors / dt, 4)
            # Gauges update inside the fold lock: two concurrently
            # delivered beats for one worker are serialized here, so the
            # gauge can never keep the older beat's values while the
            # JSON fold shows the newer (gauge locks nest fine — nothing
            # takes _mu while holding one).
            self._update_gauges(msg)
        return True

    def _update_gauges(self, msg: StatusMessage) -> None:
        wid = msg.worker_id
        self.m_queue.labels(worker_id=wid).set(float(msg.queue_length))
        usage = msg.resource_usage or {}
        rss = usage.get("rss_bytes")
        if isinstance(rss, (int, float)):
            self.m_rss.labels(worker_id=wid).set(float(rss))
        efficiency = usage.get("efficiency")
        if isinstance(efficiency, dict):
            mfu = efficiency.get("mfu")
            if isinstance(mfu, (int, float)):
                self.m_mfu.labels(worker_id=wid).set(float(mfu))
            goodput = efficiency.get("goodput_tokens_per_s")
            if isinstance(goodput, (int, float)):
                self.m_goodput.labels(worker_id=wid).set(float(goodput))
        devices = usage.get("device_memory")
        if isinstance(devices, list):
            sums = {"in_use": 0.0, "limit": 0.0, "peak": 0.0}
            for dev in devices:
                if not isinstance(dev, dict):
                    continue
                sums["in_use"] += float(dev.get("bytes_in_use") or 0)
                sums["limit"] += float(dev.get("bytes_limit") or 0)
                sums["peak"] += float(dev.get("peak_bytes_in_use") or 0)
            for kind, total in sums.items():
                self.m_devmem.labels(worker_id=wid, kind=kind).set(total)

    def clock_offsets(self) -> Dict[str, float]:
        """{worker_id: estimated clock offset in seconds} — what the
        TraceCollector adds to a worker's span walls (receiver − sender;
        only workers that have sent a timestamped beat appear)."""
        with self._mu:
            return {wid: t.clock_offset_s
                    for wid, t in self._workers.items()
                    if t.offset_samples}

    def _is_stale(self, t: WorkerTrack, now: datetime) -> bool:
        """The ONE staleness rule (mirrors check_worker_health): silent
        beyond ``stale_after_s`` and not cleanly offline."""
        return (t.status != WORKER_OFFLINE and t.last_seen is not None
                and (now - t.last_seen).total_seconds()
                > self.stale_after_s)

    def stale_count(self, now: Optional[datetime] = None) -> int:
        """Stale workers computed against ``now`` AT CALL TIME — the
        fn-bound ``fleet_stale_workers`` read, so every scrape (plain
        /metrics included) judges staleness live instead of replaying
        the last health tick's verdict."""
        now = now or utcnow()
        with self._mu:
            return sum(1 for t in self._workers.values()
                       if self._is_stale(t, now))

    def refresh_staleness(self, now: Optional[datetime] = None) -> int:
        """Evict long-gone workers and return the live stale count.
        Driven by the orchestrator's health tick; the gauge itself no
        longer depends on this tick (``stale_count`` recomputes at every
        read), so the tick's remaining job is the bounded-memory sweep.

        Eviction keeps the fleet view bounded for long-lived
        orchestrators whose workers restart under fresh ids (pod-name
        worker_ids): a track silent past ``10 * stale_after_s`` is
        dropped along with its per-worker gauge children — a worker that
        comes back simply re-registers on its next beat."""
        now = now or utcnow()
        stale = 0
        evicted = []
        with self._mu:
            for wid, t in list(self._workers.items()):
                if t.last_seen is None:
                    continue
                age = (now - t.last_seen).total_seconds()
                if age > 10 * self.stale_after_s:
                    del self._workers[wid]
                    evicted.append(wid)
                elif self._is_stale(t, now):
                    stale += 1
        for wid in evicted:
            for gauge in (self.m_queue, self.m_rss, self.m_mfu,
                          self.m_goodput):
                gauge.remove_labels(worker_id=wid)
            for kind in ("in_use", "limit", "peak"):
                self.m_devmem.remove_labels(worker_id=wid, kind=kind)
        return stale

    # -- export --------------------------------------------------------------
    def export(self, now: Optional[datetime] = None) -> Dict[str, Any]:
        """The ``/cluster`` JSON body: per-worker maps + a fleet rollup
        whose staleness rule mirrors `Orchestrator.check_worker_health`
        (silence beyond ``stale_after_s`` == presumed dead)."""
        now = now or utcnow()
        workers: Dict[str, Any] = {}
        stale = []
        counts = {"crawl": 0, "tpu": 0}
        # The whole walk stays under the lock: observe() mutates tracks
        # (and appends to each history deque) from bus threads, and a
        # deque iterated while appended-to raises mid-/cluster-request.
        # Building the plain-dict snapshot is cheap; JSON encoding happens
        # on the copy, outside.
        with self._mu:
            tracks = list(self._workers.values())
            for t in tracks:
                age = (now - t.last_seen).total_seconds() \
                    if t.last_seen is not None else None
                is_stale = self._is_stale(t, now)
                if is_stale:
                    stale.append(t.worker_id)
                counts[t.worker_type] = counts.get(t.worker_type, 0) + 1
                workers[t.worker_id] = {
                    "worker_type": t.worker_type,
                    "status": t.status,
                    "first_seen": t.first_seen.isoformat()
                    if t.first_seen else None,
                    "last_seen": t.last_seen.isoformat()
                    if t.last_seen else None,
                    "last_seen_age_s": round(age, 1) if age is not None
                    else None,
                    "stale": is_stale,
                    "current_work": t.current_work,
                    "queue_length": t.queue_length,
                    "tasks": {"processed": t.tasks_processed,
                              "success": t.tasks_success,
                              "error": t.tasks_error},
                    "rates": {"tasks_per_s": t.tasks_per_s,
                              "errors_per_s": t.errors_per_s},
                    "uptime_s": t.uptime_s,
                    "heartbeats": t.heartbeats,
                    "clock_offset_s": round(t.clock_offset_s, 6),
                    "stale_heartbeats_dropped": t.stale_dropped,
                    "telemetry": t.telemetry,
                    "history": list(t.history),
                }
        return {
            "workers": workers,
            "fleet": {
                "worker_count": len(workers),
                "crawl_workers": counts.get("crawl", 0),
                "tpu_workers": counts.get("tpu", 0),
                "stale_workers": stale,
                "stale_after_s": self.stale_after_s,
                "generated_at": now.isoformat(),
            },
        }
