"""TraceCollector: assemble ONE distributed trace from many span rings.

PR 2 gave every process a span ring and a ``/traces`` endpoint — but
each endpoint only shows the spans *that process* recorded, so the
question a distributed system actually asks ("where did this work item's
second go, across orchestrator → bus → worker?") required manually
joining N endpoints by trace id, each on its own wall clock.  The
reference got a cross-process view free from its Dapr sidecar; this is
our collector half:

- both serving workers periodically ship completed spans as typed
  `SpanBatchMessage`s on ``TOPIC_SPANS`` (`utils/trace.py:SpanExporter`
  — bounded, whole-trace-sampled);
- the orchestrator folds them here, keyed by ``trace_id``, with every
  remote span's ``start_wall`` corrected onto the COLLECTOR's clock by
  a per-worker offset.  The offset comes from heartbeat send/receive
  walls already flowing through `orchestrator/fleet.py:FleetView`
  (min over recent beats — transit time only ever inflates recv−send,
  so the minimum sample is the closest estimate of the true offset);
  workers that have not heartbeated yet fall back to the span batch's
  own ``sent_wall``;
- the collector's OWN process's spans (the orchestrator's dispatch /
  handle_result legs) merge in at export, deduped by span id, so one
  assembled trace spans every process that touched the work;
- served as JSON at the metrics server's ``/dtraces`` endpoint
  (`utils/metrics.py:set_dtraces_provider`) and embedded in
  flight-recorder postmortem bundles; rendered by
  ``tools/trace_dump.py --collector`` and judged by
  ``tools/critpath.py``.

Bounded everywhere: max traces (LRU by last update), max spans per
trace, and drop counters that make loss visible instead of silent.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

from ..bus.messages import SpanBatchMessage
from ..utils import trace as _trace
from ..utils.metrics import REGISTRY, MetricsRegistry

logger = logging.getLogger("dct.tracecollect")

DEFAULT_MAX_TRACES = 512
DEFAULT_MAX_SPANS_PER_TRACE = 512
# Heartbeat-offset samples kept per worker for the min estimator.
OFFSET_SAMPLES = 16


class _TraceBucket:
    """One assembled trace: spans keyed by span_id (dedup across bus
    redelivery AND the local-merge path in a single-process test rig)."""

    __slots__ = ("spans", "processes", "last_update", "dropped")

    def __init__(self):
        self.spans: Dict[str, Dict[str, Any]] = {}
        self.processes: set = set()
        self.last_update = 0.0
        self.dropped = 0


class TraceCollector:
    """Thread-safe fold of SpanBatchMessages into distributed traces."""

    def __init__(self,
                 offsets_fn: Optional[Callable[[], Dict[str, float]]] = None,
                 process: str = "orchestrator",
                 tracer: Optional[_trace.Tracer] = None,
                 max_traces: int = DEFAULT_MAX_TRACES,
                 max_spans_per_trace: int = DEFAULT_MAX_SPANS_PER_TRACE,
                 registry: MetricsRegistry = REGISTRY):
        """``offsets_fn`` returns {worker_id: clock_offset_s} — normally
        `FleetView.clock_offsets` (receiver − sender, seconds to ADD to a
        sender wall to land on the collector's clock).  ``process`` names
        this process's lane for locally-merged spans."""
        self.offsets_fn = offsets_fn
        self.process = process
        self.tracer = tracer or _trace.TRACER
        self.max_traces = max(1, int(max_traces))
        self.max_spans_per_trace = max(1, int(max_spans_per_trace))
        self._mu = threading.Lock()
        self._traces: "OrderedDict[str, _TraceBucket]" = OrderedDict()
        # Per-worker state: min-estimator offset from sent_wall (the
        # fallback when the fleet has no heartbeat offset yet) + export
        # accounting for the /dtraces "workers" map.
        self._workers: Dict[str, Dict[str, Any]] = {}
        self.m_spans = registry.counter(
            "dtrace_spans_total",
            "spans folded into the distributed-trace collector, by "
            "exporting worker")
        self.m_dropped = registry.counter(
            "dtrace_spans_dropped_total",
            "spans reported dropped by exporters plus spans the "
            "collector's own bounds rejected")
        self.m_traces = registry.gauge(
            "dtrace_assembled_traces",
            "distributed traces currently held by the collector")

    # -- offset estimation ---------------------------------------------------
    def _offset_for(self, worker_id: str, sent_wall: float,
                    now: float) -> float:
        """Seconds to add to this worker's walls.  Fleet heartbeat
        estimate wins; the span batch's own send/receive pair keeps a
        running min-estimator as fallback (same transit-bias argument)."""
        fleet = {}
        if self.offsets_fn is not None:
            try:
                fleet = self.offsets_fn() or {}
            except Exception as e:  # a wedged fleet view must not drop spans
                logger.warning("fleet clock-offset read failed: %s", e)
        state = self._workers.setdefault(worker_id, {
            "own_offset_s": None, "spans": 0, "batches": 0, "dropped": 0,
            "last_export_wall": 0.0})
        if sent_wall > 0:
            sample = now - sent_wall
            prev = state["own_offset_s"]
            # min by magnitude: transit time inflates |recv - send|
            # whichever side of zero the true offset is on.
            if prev is None or abs(sample) < abs(prev):
                state["own_offset_s"] = sample
        if worker_id in fleet:
            return float(fleet[worker_id])
        return float(state["own_offset_s"] or 0.0)

    # -- folding -------------------------------------------------------------
    def observe(self, msg: SpanBatchMessage,
                now: Optional[float] = None) -> int:
        """Fold one span batch; returns the number of spans accepted."""
        now = now if now is not None else time.time()
        accepted = 0
        with self._mu:
            offset = self._offset_for(msg.worker_id, msg.sent_wall, now)
            state = self._workers[msg.worker_id]
            state["batches"] += 1
            state["dropped"] += int(msg.dropped)
            state["last_export_wall"] = now
            state["applied_offset_s"] = round(offset, 6)
            for row in msg.spans:
                tid = row.get("trace_id")
                sid = row.get("span_id")
                if not tid or not sid:
                    continue
                bucket = self._traces.get(tid)
                if bucket is None:
                    bucket = self._traces[tid] = _TraceBucket()
                    while len(self._traces) > self.max_traces:
                        self._traces.popitem(last=False)  # LRU evict
                if len(bucket.spans) >= self.max_spans_per_trace \
                        and sid not in bucket.spans:
                    bucket.dropped += 1
                    self.m_dropped.inc()
                    continue
                corrected = dict(row)
                corrected["start_wall"] = \
                    float(row.get("start_wall") or 0.0) + offset
                corrected["process"] = msg.worker_id
                corrected["clock_offset_s"] = round(offset, 6)
                bucket.spans[sid] = corrected
                bucket.processes.add(msg.worker_id)
                bucket.last_update = now
                self._traces.move_to_end(tid)
                accepted += 1
            state["spans"] += accepted
        if accepted:
            self.m_spans.labels(worker=msg.worker_id).inc(accepted)
        if msg.dropped:
            self.m_dropped.inc(msg.dropped)
        with self._mu:
            self.m_traces.set(float(len(self._traces)))
        return accepted

    # -- export --------------------------------------------------------------
    def _local_spans_by_trace(self) -> Dict[str, List[Dict[str, Any]]]:
        out: Dict[str, List[Dict[str, Any]]] = {}
        for s in self.tracer.spans():
            row = s.to_dict()
            row["process"] = self.process
            row["clock_offset_s"] = 0.0
            out.setdefault(s.trace_id, []).append(row)
        return out

    def export(self, limit: int = 0) -> Dict[str, Any]:
        """The ``/dtraces`` JSON body: assembled traces (remote spans
        offset-corrected + this process's own spans merged in, deduped by
        span id), most recently updated first."""
        local = self._local_spans_by_trace()
        with self._mu:
            items = [(tid, b) for tid, b in self._traces.items()]
            workers = {w: dict(st) for w, st in self._workers.items()}
        traces = []
        for tid, bucket in reversed(items):  # newest update first
            spans = dict(bucket.spans)
            for row in local.get(tid, []):
                spans.setdefault(row["span_id"], row)
            rows = sorted(spans.values(),
                          key=lambda r: r.get("start_wall", 0.0))
            processes = sorted(bucket.processes
                               | ({self.process} if local.get(tid) else set()))
            start = min((r.get("start_wall", 0.0) for r in rows),
                        default=0.0)
            end = max((r.get("start_wall", 0.0)
                       + r.get("duration_ms", 0.0) / 1000.0 for r in rows),
                      default=0.0)
            traces.append({
                "trace_id": tid,
                "span_count": len(rows),
                "processes": processes,
                "duration_ms": round((end - start) * 1000.0, 3),
                "dropped_spans": bucket.dropped,
                "spans": rows,
            })
            if limit and len(traces) >= limit:
                break
        return {
            "traces": traces,
            "collector_process": self.process,
            "workers": workers,
            "max_traces": self.max_traces,
        }

    def reset(self) -> None:
        with self._mu:
            self._traces.clear()
            self._workers.clear()
        self.m_traces.set(0.0)
