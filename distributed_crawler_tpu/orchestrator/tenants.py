"""Per-tenant accounting + error-budget ledger: the /tenants surface.

The serving workers now attribute every chip-second, FLOP, and token to
the tenant that spent it (`utils/costmodel.py:TenantLedger`) and split
their SLO breach counters by tenant (`utils/slo.py`); the watchtower
folds both out of heartbeats into ``fleet_tenant_*`` series
(`orchestrator/watchtower.py:_observe`).  This module is the judgement
layer on top of those folds:

- **spend rows**: per-tenant chip-seconds / FLOPs / real tokens /
  batches summed across the fleet (latest cumulative value per worker),
  plus each tenant's share of total spend and worst queue-wait p95 —
  "which tenant spent which chip-seconds";
- **error-budget ledger**: for every configured ``(tenant, slo)``
  budget, the windowed breach *burn* (reset-aware
  ``TimeSeriesStore.increase`` over ``fleet_tenant_slo_breach_total``),
  the remaining budget, the current burn rate (least-squares slope of
  the cumulative counters), and an **exhaustion projection** — seconds
  until the budget runs out at the current rate;
- the ``/tenants`` JSON body (`utils.metrics.set_tenants_provider`),
  embedded in postmortem bundles (`utils/flight.py`) and rendered by
  tools/watch.py's tenants panel.

Budgets are declared in config (``observability.tenant_budgets``) or a
scenario's ``tenant_budgets`` block and validated LOUDLY by
:func:`budgets_from_config` — a typo'd tenant or SLO key raises instead
of silently never being enforced.  Tenants with spend but no budget
still appear in the view (attribution is unconditional; judgement is
opt-in), and the alert rule grammar can threshold any ``fleet_tenant_*``
series without new machinery.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..bus.messages import DEFAULT_TENANT
from ..utils.timeseries import STORE, TimeSeriesStore

# The spend series the watchtower folds (cumulative counters, one child
# per {worker, tenant}) and the row keys they aggregate into.
_SPEND_SERIES: Tuple[Tuple[str, str], ...] = (
    ("fleet_tenant_chip_seconds_total", "chip_seconds"),
    ("fleet_tenant_flops_total", "flops"),
    ("fleet_tenant_real_tokens_total", "real_tokens"),
    ("fleet_tenant_batches_total", "batches"),
)
_BREACH_SERIES = "fleet_tenant_slo_breach_total"
_QUEUE_WAIT_SERIES = "fleet_tenant_queue_wait_p95_seconds"

DEFAULT_BUDGET_WINDOW_S = 300.0


def budgets_from_config(block: Any) -> Tuple[Dict[str, Dict[str, float]],
                                             float]:
    """Validate a ``tenant_budgets`` block into ``({tenant: {slo:
    allowed_breaches}}, window_s)``.  Loud on malformed input: unknown
    top-level keys, non-dict budgets, non-numeric or negative counts all
    raise ValueError — a misspelled budget must fail the run, not
    silently never be enforced.  ``None``/``{}`` mean "no budgets"."""
    if block is None:
        return {}, DEFAULT_BUDGET_WINDOW_S
    if not isinstance(block, dict):
        raise ValueError(
            f"tenant_budgets must be a mapping, got {type(block).__name__}")
    unknown = set(block) - {"window_s", "budgets"}
    if unknown:
        raise ValueError(
            f"unknown tenant_budgets key(s): {sorted(unknown)} "
            "(expected: window_s, budgets)")
    window_s = block.get("window_s", DEFAULT_BUDGET_WINDOW_S)
    if not isinstance(window_s, (int, float)) or isinstance(window_s, bool) \
            or float(window_s) <= 0:
        raise ValueError(
            f"tenant_budgets.window_s must be a positive number, "
            f"got {window_s!r}")
    budgets_block = block.get("budgets", {})
    if not isinstance(budgets_block, dict):
        raise ValueError("tenant_budgets.budgets must be a mapping of "
                         "tenant -> {slo: allowed_breaches}")
    budgets: Dict[str, Dict[str, float]] = {}
    for tenant, slos in budgets_block.items():
        if not isinstance(tenant, str) or not tenant.strip():
            raise ValueError(
                f"tenant_budgets.budgets key must be a non-empty tenant "
                f"name, got {tenant!r}")
        if not isinstance(slos, dict) or not slos:
            raise ValueError(
                f"tenant_budgets.budgets[{tenant!r}] must be a non-empty "
                "mapping of slo -> allowed_breaches")
        per_slo: Dict[str, float] = {}
        for slo, allowed in slos.items():
            if not isinstance(slo, str) or not slo.strip():
                raise ValueError(
                    f"tenant_budgets.budgets[{tenant!r}] has a non-string "
                    f"SLO key: {slo!r}")
            if not isinstance(allowed, (int, float)) \
                    or isinstance(allowed, bool) or float(allowed) < 0:
                raise ValueError(
                    f"tenant_budgets.budgets[{tenant!r}][{slo!r}] must be "
                    f"a non-negative number, got {allowed!r}")
            per_slo[slo.strip()] = float(allowed)
        budgets[tenant.strip()] = per_slo
    return budgets, float(window_s)


class TenantBudgetLedger:
    """Fleet tenant spend + error-budget view over the time-series store."""

    def __init__(self, store: Optional[TimeSeriesStore] = None,
                 budgets: Optional[Dict[str, Dict[str, float]]] = None,
                 window_s: float = DEFAULT_BUDGET_WINDOW_S,
                 clock=time.time):
        self.store = store if store is not None else STORE
        self.clock = clock
        self._mu = threading.Lock()
        self._budgets: Dict[str, Dict[str, float]] = \
            {t: dict(s) for t, s in (budgets or {}).items()}
        self._window_s = float(window_s)

    def configure(self, budgets: Optional[Dict[str, Dict[str, float]]] = None,
                  window_s: Optional[float] = None) -> None:
        """Install validated budgets (`budgets_from_config`) — the CLI
        at startup, or the loadgen gate per scenario."""
        with self._mu:
            if budgets is not None:
                self._budgets = {t: dict(s) for t, s in budgets.items()}
            if window_s is not None and float(window_s) > 0:
                self._window_s = float(window_s)

    # -- aggregation over the fleet folds ------------------------------------
    def _spend_rows(self) -> Dict[str, Dict[str, float]]:
        """{tenant: {chip_seconds, flops, ...}} — latest cumulative value
        per {worker, tenant} child, summed across workers."""
        rows: Dict[str, Dict[str, float]] = {}
        for series, key in _SPEND_SERIES:
            for labels, samples in self.store.matching(series):
                tenant = labels.get("tenant")
                if not tenant or not samples:
                    continue
                row = rows.setdefault(tenant, {})
                row[key] = row.get(key, 0.0) + samples[-1][1]
        for labels, samples in self.store.matching(_QUEUE_WAIT_SERIES):
            tenant = labels.get("tenant")
            if not tenant or not samples:
                continue
            row = rows.setdefault(tenant, {})
            # Worst worker's p95 — a fleet mean would hide the one queue
            # a tenant is actually stuck in.
            row["queue_wait_p95_s"] = max(row.get("queue_wait_p95_s", 0.0),
                                          samples[-1][1])
        return rows

    def _burn(self, tenant: str, slo: str, window_s: float,
              now: float) -> Tuple[float, Optional[float]]:
        """(windowed breach increase, burn rate per second) for one
        (tenant, slo) across all workers.  The increase is reset-aware;
        the rate is the summed least-squares slope of each worker's
        cumulative counter over the window (clamped at zero — a counter
        reset's negative slope is not a refund)."""
        labels = {"tenant": tenant, "slo": slo}
        burned = self.store.increase(_BREACH_SERIES, labels,
                                     window_s=window_s, now=now)
        rate = 0.0
        seen = False
        since = now - window_s
        for _, samples in self.store.matching(_BREACH_SERIES, labels,
                                              since=since):
            s = TimeSeriesStore.slope(samples)
            if s is not None:
                seen = True
                rate += max(0.0, s)
        return burned, (rate if seen else None)

    def _observed_breach_pairs(self) -> List[Tuple[str, str]]:
        """Every (tenant, slo) with a breach series, budgeted or not."""
        pairs = set()
        for labels, _ in self.store.matching(_BREACH_SERIES):
            tenant, slo = labels.get("tenant"), labels.get("slo")
            if tenant and slo:
                pairs.add((tenant, slo))
        return sorted(pairs)

    # -- export --------------------------------------------------------------
    def view(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``/tenants`` JSON body."""
        now = self.clock() if now is None else now
        with self._mu:
            budgets = {t: dict(s) for t, s in self._budgets.items()}
            window_s = self._window_s
        rows = self._spend_rows()
        totals: Dict[str, float] = {}
        for row in rows.values():
            for _, key in _SPEND_SERIES:
                totals[key] = totals.get(key, 0.0) + row.get(key, 0.0)
        total_chip = totals.get("chip_seconds", 0.0)
        tenants: Dict[str, Dict[str, Any]] = {}
        names = set(rows) | set(budgets) | \
            {t for t, _ in self._observed_breach_pairs()}
        for tenant in sorted(names):
            row = rows.get(tenant, {})
            spend = {key: row.get(key, 0.0) for _, key in _SPEND_SERIES}
            spend["share"] = (spend["chip_seconds"] / total_chip) \
                if total_chip > 0 else 0.0
            entry: Dict[str, Any] = {"spend": spend}
            if "queue_wait_p95_s" in row:
                entry["queue_wait_p95_s"] = row["queue_wait_p95_s"]
            entry["budgets"] = {}
            tenants[tenant] = entry
        # Burn for every observed (tenant, slo) pair; budgeted pairs add
        # remaining + exhaustion even when they never breached.
        pairs = set(self._observed_breach_pairs())
        for tenant, slos in budgets.items():
            for slo in slos:
                pairs.add((tenant, slo))
        for tenant, slo in sorted(pairs):
            burned, rate = self._burn(tenant, slo, window_s, now)
            cell: Dict[str, Any] = {"burned": round(burned, 6)}
            if rate is not None:
                cell["burn_rate_per_s"] = round(rate, 9)
            allowed = budgets.get(tenant, {}).get(slo)
            if allowed is not None:
                remaining = allowed - burned
                cell["budget"] = allowed
                cell["remaining"] = round(remaining, 6)
                cell["exhausted"] = remaining <= 0
                if remaining <= 0:
                    cell["exhaustion_s"] = 0.0
                elif rate:
                    cell["exhaustion_s"] = round(remaining / rate, 3)
            tenants.setdefault(tenant, {"spend": {
                key: 0.0 for _, key in _SPEND_SERIES} | {"share": 0.0},
                "budgets": {}})
            tenants[tenant].setdefault("budgets", {})[slo] = cell
        unattributed = tenants.get(DEFAULT_TENANT, {}) \
            .get("spend", {}).get("share", 0.0)
        return {
            "generated_at": now,
            "window_s": window_s,
            "default_tenant": DEFAULT_TENANT,
            "tenants": tenants,
            "totals": {k: round(v, 9) for k, v in sorted(totals.items())},
            "unattributed_share": round(unattributed, 9),
        }
