"""The autoscaler: alert-actuated elastic fleet control.

PR 12 built the consuming half of the elastic-fleet story — the
watchtower keeps rolling history of every fleet series, evaluates
burn-rate/trend/threshold rules on the orchestrator tick, and publishes
firing/resolved `AlertMessage`s on ``TOPIC_ALERTS``.  This module is the
ACTUATION half: a policy engine that turns those alerts (plus direct
reads of the rolling store for trend anticipation) into per-pool
desired-size decisions, and drives a pluggable `WorkerSupervisor` that
spawns and retires real serving workers.

Policy shape (one `PoolPolicy` per worker pool):

- **scale-up** when any of ``scale_up_alerts`` is firing, or — trend
  anticipation — when ``trend_series`` is climbing faster than
  ``trend_slope_per_s`` (the store read, so the fleet can grow BEFORE a
  burn rule confirms);
- **scale-down** only when no scale-up pressure exists AND the
  ``headroom_series`` mean has stayed under ``headroom_below`` for a
  full ``stabilization_s`` window;
- **hysteresis everywhere**: separate per-direction cooldowns
  (``up_cooldown_s``/``down_cooldown_s``), the stabilization window, and
  hard ``min_workers``/``max_workers`` bounds, so a flapping alert can
  confirm at most one step per cooldown and can never thrash the fleet.

Every decision is flight-recorded (``autoscale`` events), counted
(``autoscaler_decisions_total{pool,direction}``), gauged
(``autoscaler_desired_workers{pool}`` vs ``autoscaler_actual_workers``),
written into the rolling store (so /timeseries carries fleet-size
history and the loadgen gate can judge ``min_fleet_size`` /
``max_fleet_size`` over time), kept in a bounded decision log, and
served at the new ``/autoscaler`` surface
(`utils.metrics.set_autoscaler_provider`).

Actuation is pluggable:

- `InProcessSupervisor` constructs/retires real `TPUWorker`/`ASRWorker`
  instances through per-pool factories (what the loadgen gate drives);
  retirement is ALWAYS a graceful drain through the existing stop path
  — never ``kill()`` — so un-acked frames requeue and the fleet loses
  nothing on the way down;
- `SubprocessSupervisor` spawns ``--mode tpu-worker`` children for
  `cli.py` deployments; retirement is SIGTERM (the `_serve_forever`
  graceful path) with a bounded escalation to SIGKILL.
"""

from __future__ import annotations

import logging
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional

from ..bus.messages import TOPIC_ALERTS
from ..utils import flight
from ..utils.metrics import REGISTRY, MetricsRegistry
from ..utils.timeseries import STORE, TimeSeriesStore

logger = logging.getLogger("dct.autoscaler")

SCALE_UP = "up"
SCALE_DOWN = "down"


@dataclass
class PoolPolicy:
    """Desired-size policy for one worker pool (docs/operations.md
    "Elastic fleet & autoscaling" knob table)."""

    pool: str
    min_workers: int = 1
    max_workers: int = 4
    scale_up_step: int = 1
    scale_down_step: int = 1
    # Per-direction cooldowns: at most one step per cooldown, each way.
    up_cooldown_s: float = 30.0
    down_cooldown_s: float = 60.0
    # Scale-up pressure: any of these watchtower rules firing.
    scale_up_alerts: List[str] = field(default_factory=lambda: [
        "queue_wait_burn", "batch_age_burn"])
    # Trend anticipation (optional): a positive slope threshold on a
    # rolling-store series lets the pool grow before the burn alert's
    # for_s confirms.  Empty series name = off.
    trend_series: str = ""
    trend_slope_per_s: float = 0.0
    trend_window_s: float = 30.0
    # Scale-down headroom: the series' windowed mean must stay below the
    # threshold for stabilization_s, with zero scale-up pressure.
    headroom_series: str = "fleet_queue_depth"
    headroom_below: float = 1.0
    stabilization_s: float = 30.0

    def validate(self) -> None:
        if not self.pool:
            raise ValueError("pool policy needs a pool name")
        if self.min_workers < 0:
            raise ValueError(f"pool {self.pool}: min_workers must be >= 0")
        if self.max_workers < max(1, self.min_workers):
            raise ValueError(
                f"pool {self.pool}: max_workers ({self.max_workers}) must "
                f"be >= min_workers ({self.min_workers}) and >= 1")
        if self.scale_up_step < 1 or self.scale_down_step < 1:
            raise ValueError(f"pool {self.pool}: scale steps must be >= 1")
        if self.up_cooldown_s < 0 or self.down_cooldown_s < 0 \
                or self.stabilization_s < 0:
            raise ValueError(
                f"pool {self.pool}: cooldowns/stabilization must be >= 0")
        if self.trend_series and self.trend_slope_per_s <= 0:
            raise ValueError(
                f"pool {self.pool}: trend_series needs a positive "
                f"trend_slope_per_s")

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PoolPolicy":
        known = set(cls.__dataclass_fields__)
        unknown = set(d) - known
        if unknown:
            # A typo'd knob must fail loudly at config time, not silently
            # run the default policy forever (the AlertRule discipline).
            raise ValueError(
                f"autoscaler pool {d.get('pool', '?')}: unknown key(s) "
                f"{', '.join(sorted(unknown))}")
        try:
            policy = cls(**d)
        except TypeError as e:
            raise ValueError(
                f"autoscaler pool {d.get('pool', '?')}: {e}") from e
        policy.scale_up_alerts = list(policy.scale_up_alerts or [])
        policy.validate()
        return policy


def pools_from_config(raw: Any) -> List[PoolPolicy]:
    """Build the pool-policy list from an ``autoscaler.pools`` config
    value (YAML list / scenario "autoscaler.pools" block / parsed
    ``--autoscaler-pools`` JSON).  Duplicate pool names are rejected."""
    if not raw:
        return []
    if not isinstance(raw, list):
        raise ValueError("autoscaler pools must be a list of pool objects")
    pools = [PoolPolicy.from_dict(dict(d)) for d in raw]
    seen = set()
    for p in pools:
        if p.pool in seen:
            raise ValueError(f"duplicate autoscaler pool {p.pool!r}")
        seen.add(p.pool)
    return pools


@dataclass
class _PoolState:
    desired: int = 0
    last_up_at: float = 0.0
    last_down_at: float = 0.0
    headroom_since: float = 0.0   # wall when headroom began holding; 0=not
    pressure: List[str] = field(default_factory=list)
    # Spawn-churn detection: spawns that "succeed" but whose workers die
    # before the next tick (a subprocess child crashing on a bad flag)
    # reopen the gap every pass — count the consecutive reopenings and
    # back off instead of crash-loop-forking forever.
    spawned_last: bool = False
    churn: int = 0
    backoff_until: float = 0.0


# Consecutive ticks the desired/actual gap may reopen after a spawn
# before actuation backs off (10x the eval interval, min 30 s).
SPAWN_CHURN_LIMIT = 5


class Autoscaler:
    """Alert-driven desired-size control loop over a `WorkerSupervisor`.

    Sources, in priority order: ``alerts_fn`` (the watchtower's
    `get_alerts` — authoritative when wired), and/or typed
    `AlertMessage`s observed on ``TOPIC_ALERTS`` via
    :meth:`observe_alert` (`attach_bus`), so the control plane works
    in-process beside the orchestrator AND as a remote subscriber."""

    def __init__(self, supervisor, pools: List[PoolPolicy],
                 store: Optional[TimeSeriesStore] = None,
                 registry: MetricsRegistry = REGISTRY,
                 clock=time.time,
                 eval_interval_s: float = 5.0,
                 alerts_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 log_capacity: int = 256):
        if not pools:
            raise ValueError("autoscaler needs at least one pool policy")
        for p in pools:
            p.validate()
        self.supervisor = supervisor
        self.pools = {p.pool: p for p in pools}
        if len(self.pools) != len(pools):
            raise ValueError("duplicate autoscaler pool names")
        self.store = store if store is not None else STORE
        self.clock = clock
        self.eval_interval_s = float(eval_interval_s)
        self.alerts_fn = alerts_fn
        self._mu = threading.Lock()
        self._states: Dict[str, _PoolState] = {
            name: _PoolState() for name in self.pools}
        self._firing: Dict[str, float] = {}   # rule -> fired wall (bus-fed)
        self._log: Deque[Dict[str, Any]] = deque(
            maxlen=max(1, log_capacity))
        self._last_eval = 0.0
        self._ticks = 0
        self._decisions = 0
        self._started_at = self.clock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.m_decisions = registry.counter(
            "autoscaler_decisions_total",
            "autoscaler scale decisions by pool and direction")
        self.m_desired = registry.gauge(
            "autoscaler_desired_workers",
            "the autoscaler's desired worker count per pool")
        self.m_actual = registry.gauge(
            "autoscaler_actual_workers",
            "live workers the supervisor reports per pool")

    # -- alert intake --------------------------------------------------------
    def attach_bus(self, bus) -> None:
        """Subscribe :meth:`observe_alert` to ``TOPIC_ALERTS`` — the
        remote-control-plane seam (fan-out: the orchestrator's own
        logging sink keeps its subscription too)."""
        bus.subscribe(TOPIC_ALERTS, self.observe_alert)

    def observe_alert(self, payload: Dict[str, Any]) -> None:
        """Fold one `AlertMessage` payload into the firing set; never
        raises into the bus."""
        try:
            rule = payload.get("rule", "")
            state = payload.get("state", "")
            if not rule:
                return
            with self._mu:
                if state == "firing":
                    self._firing[rule] = float(
                        payload.get("at_wall") or self.clock())
                elif state == "resolved":
                    self._firing.pop(rule, None)
        except Exception as e:
            logger.debug("undecodable alert announcement: %s", e)

    def _firing_now(self) -> Dict[str, float]:
        """The current firing set: the watchtower read when wired (it
        also reconciles a missed resolved-frame), else the bus-fed map."""
        if self.alerts_fn is not None:
            try:
                body = self.alerts_fn() or {}
                firing = {}
                for a in body.get("alerts", []):
                    if a.get("state") == "firing":
                        firing[a.get("rule", "")] = float(
                            a.get("fired_at") or 0.0)
                with self._mu:
                    self._firing = dict(firing)
                return firing
            except Exception as e:
                logger.warning("autoscaler alerts read failed: %s", e)
        with self._mu:
            return dict(self._firing)

    # -- signals -------------------------------------------------------------
    def _trend_pressure(self, policy: PoolPolicy, now: float) -> bool:
        if not policy.trend_series:
            return False
        since = now - policy.trend_window_s
        slopes = [s for s in (
            self.store.slope(samples)
            for _, samples in self.store.matching(policy.trend_series,
                                                  since=since))
            if s is not None]
        return bool(slopes) and sum(slopes) >= policy.trend_slope_per_s

    def _headroom_holds(self, policy: PoolPolicy, now: float) -> bool:
        """Windowed mean of the headroom series under the threshold.  An
        empty window (no samples yet) is NOT headroom — an unobserved
        fleet must never scale down on silence."""
        since = now - max(policy.stabilization_s, 1e-9)
        vals = [v for _, samples in
                self.store.matching(policy.headroom_series, since=since)
                for _, v in samples]
        if not vals:
            return False
        return (sum(vals) / len(vals)) < policy.headroom_below

    # -- the tick ------------------------------------------------------------
    def tick(self, now: Optional[float] = None,
             force: bool = False) -> List[Dict[str, Any]]:
        """One control pass over every pool; rate-limited to
        ``eval_interval_s`` (``force=True`` bypasses — deterministic
        tests and the gate's phase boundaries).  Returns the decisions
        this pass produced (empty most ticks)."""
        now = self.clock() if now is None else now
        with self._mu:
            if not force and now - self._last_eval < self.eval_interval_s:
                return []
            self._last_eval = now
            self._ticks += 1
        firing = self._firing_now()
        decisions: List[Dict[str, Any]] = []
        for name, policy in self.pools.items():
            try:
                decision = self._tick_pool(name, policy, firing, now)
            except Exception as e:
                logger.warning("autoscaler pool %s tick failed: %s",
                               name, e)
                continue
            if decision is not None:
                decisions.append(decision)
        return decisions

    def _tick_pool(self, name: str, policy: PoolPolicy,
                   firing: Dict[str, float],
                   now: float) -> Optional[Dict[str, Any]]:
        st = self._states[name]
        actual = int(self.supervisor.actual(name))
        if st.desired <= 0:
            # First sight of the pool: adopt what exists, floored at min
            # (an under-min fleet grows to min on this very tick).
            st.desired = max(policy.min_workers, actual)
        pressure = sorted(r for r in policy.scale_up_alerts if r in firing)
        trend = self._trend_pressure(policy, now)
        if trend:
            pressure.append(f"trend:{policy.trend_series}")
        st.pressure = pressure

        decision = None
        if pressure:
            st.headroom_since = 0.0
            if st.desired < policy.max_workers \
                    and now - st.last_up_at >= policy.up_cooldown_s:
                target = min(policy.max_workers,
                             st.desired + policy.scale_up_step)
                decision = self._decide(name, policy, st, SCALE_UP,
                                        st.desired, target, pressure[0],
                                        actual, now)
                st.last_up_at = now
        else:
            if self._headroom_holds(policy, now):
                if st.headroom_since <= 0.0:
                    st.headroom_since = now
            else:
                st.headroom_since = 0.0
            held = st.headroom_since > 0.0 \
                and now - st.headroom_since >= policy.stabilization_s
            if held and st.desired > policy.min_workers \
                    and now - st.last_down_at >= policy.down_cooldown_s:
                target = max(policy.min_workers,
                             st.desired - policy.scale_down_step)
                decision = self._decide(name, policy, st, SCALE_DOWN,
                                        st.desired, target, "headroom",
                                        actual, now)
                st.last_down_at = now
        self._actuate(name, policy, st, now)
        actual_now = int(self.supervisor.actual(name))
        self.m_desired.labels(pool=name).set(float(st.desired))
        self.m_actual.labels(pool=name).set(float(actual_now))
        self.store.add("autoscaler_desired_workers", float(st.desired),
                       {"pool": name}, wall=now)
        self.store.add("autoscaler_actual_workers", float(actual_now),
                       {"pool": name}, wall=now)
        if decision is not None:
            decision["actual_after"] = actual_now
        return decision

    def _decide(self, name: str, policy: PoolPolicy, st: _PoolState,
                direction: str, from_n: int, to_n: int, reason: str,
                actual: int, now: float) -> Dict[str, Any]:
        st.desired = to_n
        decision = {
            "at": now, "pool": name, "direction": direction,
            "from": from_n, "to": to_n, "reason": reason,
            "alert": reason if not reason.startswith("trend:")
            and reason != "headroom" else None,
            "actual_before": actual,
        }
        with self._mu:
            self._log.append(decision)
            self._decisions += 1
        self.m_decisions.labels(pool=name, direction=direction).inc()
        flight.record("autoscale", pool=name, direction=direction,
                      from_workers=from_n, to_workers=to_n, reason=reason)
        logger.warning(
            "autoscale %s: %s %d -> %d (%s)", name, direction, from_n,
            to_n, reason)
        return decision

    def _actuate(self, name: str, policy: PoolPolicy, st: _PoolState,
                 now: float) -> None:
        """Converge actual toward desired through the supervisor.  An
        actuation failure reverts desired to what actually exists
        (floored at min) so the gap is re-decided, not silently
        presumed closed.  A spawn that "succeeds" but whose worker dies
        before the next tick (a crash-looping subprocess child) reopens
        the gap every pass — after SPAWN_CHURN_LIMIT consecutive
        reopenings actuation backs off for 10x the eval interval
        instead of forking a spawn storm."""
        if now < st.backoff_until:
            return
        gap = st.desired - int(self.supervisor.actual(name))
        if gap > 0 and st.spawned_last:
            st.churn += 1
            if st.churn >= SPAWN_CHURN_LIMIT:
                backoff = max(30.0, 10.0 * self.eval_interval_s)
                st.backoff_until = now + backoff
                st.churn = 0
                st.spawned_last = False
                flight.record("autoscale_error", pool=name,
                              op="spawn_churn",
                              error=f"spawned workers keep dying; "
                                    f"backing off {backoff:.0f}s")
                logger.error(
                    "autoscaler pool %s: spawned workers keep dying "
                    "(%d consecutive reopened gaps); backing off %.0fs "
                    "— check the worker command line/environment",
                    name, SPAWN_CHURN_LIMIT, backoff)
                return
        elif gap <= 0:
            st.churn = 0
        st.spawned_last = False
        guard = policy.max_workers + policy.min_workers + 2
        while int(self.supervisor.actual(name)) < st.desired and guard > 0:
            guard -= 1
            try:
                wid = self.supervisor.spawn(name)
                st.spawned_last = True
                flight.record("autoscale_spawn", pool=name, worker=wid)
            except Exception as e:
                logger.error("autoscaler spawn failed for pool %s: %s",
                             name, e)
                flight.record("autoscale_error", pool=name, op="spawn",
                              error=str(e))
                st.desired = max(policy.min_workers,
                                 int(self.supervisor.actual(name)))
                return
        while int(self.supervisor.actual(name)) > st.desired and guard > 0:
            guard -= 1
            try:
                wid = self.supervisor.retire(name)
                if wid is None:
                    return  # nothing retirable right now; retry next tick
                flight.record("autoscale_retire", pool=name, worker=wid)
            except Exception as e:
                logger.error("autoscaler retire failed for pool %s: %s",
                             name, e)
                flight.record("autoscale_error", pool=name, op="retire",
                              error=str(e))
                st.desired = max(policy.min_workers,
                                 int(self.supervisor.actual(name)))
                return

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        """Background control loop (cli.py orchestrator mode); the
        loadgen gate drives :meth:`tick` inline instead."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dct-autoscaler")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # the loop must outlive a bad tick
                logger.error("autoscaler tick error: %s", e)
            self._stop.wait(min(1.0, max(0.05, self.eval_interval_s / 2)))

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    # -- export --------------------------------------------------------------
    def decisions(self) -> List[Dict[str, Any]]:
        with self._mu:
            return list(self._log)

    def snapshot(self) -> Dict[str, Any]:
        """The ``/autoscaler`` JSON body (registered via
        `utils.metrics.set_autoscaler_provider`); postmortem bundles
        embed it — "what the autoscaler did before the crash"."""
        now = self.clock()
        pools: Dict[str, Any] = {}
        with self._mu:
            states = {n: (st.desired, st.last_up_at, st.last_down_at,
                          st.headroom_since, list(st.pressure),
                          st.backoff_until)
                      for n, st in self._states.items()}
            log = list(self._log)
            ticks, decisions = self._ticks, self._decisions
        for name, policy in self.pools.items():
            desired, up_at, down_at, headroom_since, pressure, \
                backoff_until = states[name]
            try:
                actual = int(self.supervisor.actual(name))
            except Exception as e:
                logger.debug("supervisor actual(%s) read failed: %s",
                             name, e)
                actual = -1  # the snapshot still serves; -1 says "unknown"
            pools[name] = {
                "desired": max(desired, 0),
                "actual": actual,
                "min": policy.min_workers,
                "max": policy.max_workers,
                "pressure": pressure,
                "headroom_held_s": round(now - headroom_since, 3)
                if headroom_since > 0 else 0.0,
                "actuation_backoff_s": round(max(
                    0.0, backoff_until - now), 3),
                "cooldown": {
                    "up_remaining_s": round(max(
                        0.0, policy.up_cooldown_s - (now - up_at)), 3),
                    "down_remaining_s": round(max(
                        0.0, policy.down_cooldown_s - (now - down_at)), 3),
                },
                "policy": policy.to_dict(),
            }
        return {
            "generated_at": now,
            "uptime_s": round(now - self._started_at, 3),
            "eval_interval_s": self.eval_interval_s,
            "ticks": ticks,
            "decision_count": decisions,
            "pools": pools,
            "decisions": log,
        }


# --- supervisors -------------------------------------------------------------

class InProcessSupervisor:
    """Actuation over in-process worker handles.

    A *handle* is anything exposing ``.name`` and ``.worker`` where the
    worker has ``drain(timeout_s)`` / ``stop(timeout_s)`` — the loadgen
    gate's `WorkerHandle`/`ASRWorkerHandle`, or a bare worker wrapped in
    :class:`WorkerHandleAdapter`.  ``spawn_fn()`` builds AND starts a
    fresh handle.  Retirement is newest-first and always the graceful
    path: drain (un-acked frames requeue to the survivors), then stop —
    never ``kill()``.  ``on_change(pool, live_handles)`` fires after
    every spawn/retire so hosts can re-point process-global provider
    seams (/status, /costs) at a surviving worker."""

    def __init__(self, drain_timeout_s: float = 10.0,
                 on_change: Optional[Callable[[str, List[Any]], None]]
                 = None):
        self.drain_timeout_s = float(drain_timeout_s)
        self.on_change = on_change
        self._mu = threading.Lock()
        self._pools: Dict[str, Dict[str, Any]] = {}
        self.spawned: Dict[str, int] = {}
        self.retired: Dict[str, int] = {}

    def add_pool(self, pool: str, spawn_fn: Callable[[], Any]) -> None:
        with self._mu:
            if pool in self._pools:
                raise ValueError(f"pool {pool!r} already registered")
            self._pools[pool] = {"spawn": spawn_fn, "handles": []}

    def attach(self, pool: str, handle: Any) -> None:
        """A pre-existing (scenario-start) worker joins the pool."""
        with self._mu:
            self._pools[pool]["handles"].append(handle)

    @staticmethod
    def _alive(handle: Any) -> bool:
        return bool(getattr(handle, "alive", True)) \
            and getattr(handle, "worker", None) is not None

    def pools(self) -> List[str]:
        with self._mu:
            return sorted(self._pools)

    def handles(self, pool: Optional[str] = None) -> List[Any]:
        with self._mu:
            if pool is not None:
                return list(self._pools[pool]["handles"])
            return [h for p in self._pools.values()
                    for h in p["handles"]]

    def live(self, pool: Optional[str] = None) -> List[Any]:
        return [h for h in self.handles(pool) if self._alive(h)]

    def actual(self, pool: str) -> int:
        return len(self.live(pool))

    def spawn(self, pool: str) -> str:
        with self._mu:
            spawn_fn = self._pools[pool]["spawn"]
        handle = spawn_fn()
        with self._mu:
            self._pools[pool]["handles"].append(handle)
            self.spawned[pool] = self.spawned.get(pool, 0) + 1
        self._changed(pool)
        return getattr(handle, "name", repr(handle))

    def retire(self, pool: str) -> Optional[str]:
        with self._mu:
            live = [h for h in self._pools[pool]["handles"]
                    if self._alive(h)]
            if not live:
                return None
            handle = live[-1]  # newest-first: the scale-up's own spawns
            self._pools[pool]["handles"].remove(handle)
            self.retired[pool] = self.retired.get(pool, 0) + 1
        worker = getattr(handle, "worker", None)
        try:
            drain = getattr(worker, "drain", None)
            if callable(drain):
                drain(timeout_s=self.drain_timeout_s)
        except Exception as e:
            logger.warning("retire drain of %s failed: %s",
                           getattr(handle, "name", "?"), e)
        # The EXISTING graceful stop path — never kill(): the worker
        # announces worker_stopping, ships its span tail, flushes the
        # provider, and its pull stream teardown requeues whatever the
        # drain above didn't finish.
        stop = getattr(handle, "stop", None) or getattr(worker, "stop")
        stop()
        self._changed(pool)
        return getattr(handle, "name", repr(handle))

    def _changed(self, pool: str) -> None:
        if self.on_change is None:
            return
        try:
            self.on_change(pool, self.live(pool))
        except Exception as e:
            logger.warning("supervisor on_change failed: %s", e)

    def stop_all(self, pool: Optional[str] = None) -> None:
        """Teardown: gracefully stop every live handle (gate/test
        cleanup; retirement bookkeeping is not incremented)."""
        for handle in self.live(pool):
            try:
                stop = getattr(handle, "stop", None) \
                    or getattr(handle.worker, "stop")
                stop()
            except Exception as e:
                logger.warning("supervisor teardown stop failed: %s", e)


class WorkerHandleAdapter:
    """Wrap a bare worker (TPUWorker/ASRWorker) in the handle protocol
    `InProcessSupervisor` expects — hosts that construct workers
    directly (no loadgen WorkerHandle) still get supervised."""

    def __init__(self, name: str, worker, on_stop=None):
        self.name = name
        self.worker = worker
        self.alive = True
        self._on_stop = on_stop

    def stop(self) -> None:
        self.alive = False
        try:
            self.worker.stop()
        finally:
            if self._on_stop is not None:
                self._on_stop(self)


class SubprocessSupervisor:
    """Actuation over ``--mode tpu-worker`` child processes (cli.py).

    ``argv_template`` is the full child command line with
    ``{worker_id}`` placeholders (built by cli.py from the orchestrator's
    own bus address + ``autoscaler.worker_args``).  Retirement sends
    SIGTERM — the `_serve_forever` graceful path (drain, stopping
    status, postmortem hooks) — and escalates to SIGKILL only past
    ``term_timeout_s``."""

    def __init__(self, pool_argv: Dict[str, List[str]],
                 term_timeout_s: float = 30.0):
        self.pool_argv = {p: list(argv) for p, argv in pool_argv.items()}
        self.term_timeout_s = float(term_timeout_s)
        self._mu = threading.Lock()
        self._children: Dict[str, List] = {p: [] for p in pool_argv}
        self._seq: Dict[str, int] = {p: 0 for p in pool_argv}

    def pools(self) -> List[str]:
        return sorted(self.pool_argv)

    def _reap_locked(self, pool: str) -> None:
        self._children[pool] = [
            (wid, proc) for wid, proc in self._children[pool]
            if proc.poll() is None]

    def actual(self, pool: str) -> int:
        with self._mu:
            self._reap_locked(pool)
            return len(self._children[pool])

    def children(self, pool: str) -> List[str]:
        with self._mu:
            self._reap_locked(pool)
            return [wid for wid, _ in self._children[pool]]

    def spawn(self, pool: str) -> str:
        with self._mu:
            self._seq[pool] += 1
            wid = f"{pool}-auto-{self._seq[pool]}"
            argv = [a.replace("{worker_id}", wid)
                    for a in self.pool_argv[pool]]
        proc = subprocess.Popen(argv)
        logger.warning("autoscaler spawned worker %s (pid %d): %s",
                       wid, proc.pid, " ".join(argv))
        with self._mu:
            self._children[pool].append((wid, proc))
        return wid

    def retire(self, pool: str) -> Optional[str]:
        with self._mu:
            self._reap_locked(pool)
            if not self._children[pool]:
                return None
            wid, proc = self._children[pool].pop()  # newest-first
        proc.terminate()  # SIGTERM: the graceful _serve_forever path
        try:
            proc.wait(timeout=self.term_timeout_s)
        except subprocess.TimeoutExpired:
            logger.error("worker %s ignored SIGTERM for %.0fs; killing",
                         wid, self.term_timeout_s)
            proc.kill()
            proc.wait(timeout=5.0)
        logger.warning("autoscaler retired worker %s (rc=%s)",
                       wid, proc.returncode)
        return wid

    def stop_all(self) -> None:
        for pool in list(self.pool_argv):
            while self.retire(pool) is not None:
                pass


def default_subprocess_argv(pool: str, bus_address: str,
                            extra_args: Optional[List[str]] = None,
                            python: Optional[str] = None,
                            shard_addresses: Optional[List[str]] = None
                            ) -> List[str]:
    """The cli.py child command line for one pool: a ``tpu-worker``
    (or ``asr-worker`` for pool names starting with "asr") dialing the
    orchestrator's broker — or, on a partitioned control plane
    (``shard_addresses``), EVERY broker shard: a spawned worker that
    dialed only one shard would never pull the other shards' work
    queues.  ``{worker_id}`` is substituted per spawn."""
    mode = "asr-worker" if pool.startswith("asr") else "tpu-worker"
    if shard_addresses:
        bus_args = ["--bus-shard-addresses", ",".join(shard_addresses),
                    "--bus-shards", str(len(shard_addresses))]
    else:
        bus_args = ["--bus-address", bus_address]
    return [python or sys.executable, "-m", "distributed_crawler_tpu.cli",
            "--mode", mode, "--worker-id", "{worker_id}"] \
        + bus_args + list(extra_args or [])
