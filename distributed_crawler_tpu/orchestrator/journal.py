"""Crash-consistent crawl journal: the orchestrator's durable memory.

The reference coordinator survived restarts because its graph state lived
in PostgreSQL behind the Dapr state store — the process held no state
worth losing.  Our port keeps coordination state (`active_work`,
retry counts, current depth, applied-result ids) in process memory and
only persists `state.json` at initialize/close, so orchestrator death
used to lose the crawl.  This module adds the write-ahead record that
makes `Orchestrator.start()` resumable:

- **append** — one JSON line per coordination event (``dispatch``,
  ``result``, ``requeue``, ``reassign``, ``abandon``, ``depth``,
  ``layer``, ``completed``), flushed per event (optionally fsynced).
- **snapshot/compact** — an atomic (tmp + rename) full-state snapshot;
  the event log is truncated after a successful snapshot, bounding
  replay work.  The orchestrator saves the state manager *before*
  snapshotting so truncation never orphans page-status fixups.
- **replay** — snapshot + surviving events folded into a
  :class:`RecoveredCrawl`.  A torn final line (the crash happened
  mid-append) is skipped, not fatal: the corresponding in-flight action
  is re-derived from page state by the resume sweep.

The journal is deliberately backend-agnostic: it writes through plain
files under ``journal_dir`` (typically
``<dump-dir>/orch-journal/<crawl-id>`` or
``<storage_root>/<crawl_id>/orch-journal``) so it works identically
under every state-manager backend.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("dct.orchestrator.journal")

JOURNAL_FILE = "journal.jsonl"
SNAPSHOT_FILE = "snapshot.json"

DEFAULT_COMPACT_EVERY = 256


@dataclass
class RecoveredCrawl:
    """Everything `Orchestrator._resume` needs, folded from snapshot +
    events."""

    crawl_id: str = ""
    current_depth: int = 0
    total_work_items: int = 0
    completed_items: int = 0
    error_items: int = 0
    discovered_pages: int = 0
    crawl_completed: bool = False
    # work-item id -> serialized WorkItem (dispatched, no result yet)
    active_work: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    # page id -> retry count (non-terminal pages only)
    retry_counts: Dict[str, int] = field(default_factory=dict)
    # work-item ids whose results were already applied (idempotence set)
    applied_results: set = field(default_factory=set)
    # page id -> (status, error): the page's journaled terminal/interim
    # state, replayed over the (possibly stale) persisted state manager
    page_fixups: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    # [(depth, [page dicts])] discovered layers, in journal order
    layers: List[Tuple[int, List[Dict[str, Any]]]] = \
        field(default_factory=list)
    events_replayed: int = 0

    def to_debug_dict(self) -> Dict[str, Any]:
        return {
            "crawl_id": self.crawl_id,
            "current_depth": self.current_depth,
            "active_work": sorted(self.active_work),
            "applied_results": len(self.applied_results),
            "retry_counts": dict(self.retry_counts),
            "layers": [(d, len(p)) for d, p in self.layers],
            "crawl_completed": self.crawl_completed,
            "events_replayed": self.events_replayed,
        }


class CrawlJournal:
    """Append-only event log + atomic snapshot under one directory."""

    def __init__(self, journal_dir: str, fsync: bool = False,
                 compact_every: int = DEFAULT_COMPACT_EVERY):
        if not journal_dir:
            raise ValueError("journal_dir cannot be empty")
        self.journal_dir = journal_dir
        self.fsync = fsync
        self.compact_every = max(1, compact_every)
        self._lock = threading.Lock()
        self._fh = None
        self._since_snapshot = 0
        os.makedirs(journal_dir, exist_ok=True)

    # -- paths --------------------------------------------------------------
    @property
    def journal_path(self) -> str:
        return os.path.join(self.journal_dir, JOURNAL_FILE)

    @property
    def snapshot_path(self) -> str:
        return os.path.join(self.journal_dir, SNAPSHOT_FILE)

    # -- lifecycle ----------------------------------------------------------
    def exists(self) -> bool:
        """True if there is anything to resume from."""
        if os.path.exists(self.snapshot_path):
            return True
        try:
            return os.path.getsize(self.journal_path) > 0
        except OSError:
            return False

    def reset(self) -> None:
        """Discard snapshot + events (``--fresh``)."""
        with self._lock:
            self._close_locked()
            for path in (self.journal_path, self.snapshot_path):
                try:
                    os.unlink(path)
                except OSError:
                    pass
            self._since_snapshot = 0

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        # Caller holds _lock (the `_locked` suffix contract).
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None  # crawlint: disable=LCK001

    # -- writing ------------------------------------------------------------
    def append(self, kind: str, **fields: Any) -> None:
        """Write one event; flushed before returning so the record
        survives a process kill (an OS/disk crash additionally needs
        ``fsync=True``)."""
        event = {"ts": time.time(), "kind": kind, **fields}
        line = json.dumps(event, default=str)
        with self._lock:
            if self._fh is None:
                # WAL semantics: file I/O under the writer lock IS the
                # serialization point.
                self._fh = open(self.journal_path, "a",  # crawlint: disable=LCK002
                                encoding="utf-8")
            self._fh.write(line + "\n")
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
            self._since_snapshot += 1

    def should_compact(self) -> bool:
        with self._lock:
            return self._since_snapshot >= self.compact_every

    def snapshot(self, state: Dict[str, Any]) -> None:
        """Atomically replace the snapshot and truncate the event log.
        Callers must have made any co-durable state (the state manager's
        ``save_state``) durable FIRST — after truncation the events that
        described it are gone."""
        tmp = self.snapshot_path + ".tmp"
        payload = {"ts": time.time(), "state": state}
        with self._lock:
            # Snapshot + truncation must be atomic w.r.t. appends: the
            # lock-held I/O is the crash-consistency mechanism.
            with open(tmp, "w", encoding="utf-8") as f:  # crawlint: disable=LCK002
                json.dump(payload, f, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.snapshot_path)
            self._close_locked()
            # Truncate AFTER the snapshot is durable.
            open(self.journal_path, "w",  # crawlint: disable=LCK002
                 encoding="utf-8").close()
            self._since_snapshot = 0

    # -- reading ------------------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        """Surviving events in append order; a torn tail line is dropped
        (crash mid-append), a torn *interior* line is skipped with a
        warning (should not happen with line-buffered appends)."""
        try:
            with open(self.journal_path, "r", encoding="utf-8") as f:
                lines = f.read().splitlines()
        except OSError:
            return []
        out: List[Dict[str, Any]] = []
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                if i == len(lines) - 1:
                    logger.warning("journal: dropping torn tail line")
                else:
                    logger.warning("journal: skipping corrupt line %d", i + 1)
        return out

    def load_snapshot(self) -> Dict[str, Any]:
        try:
            with open(self.snapshot_path, "r", encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return {}
        state = payload.get("state")
        return state if isinstance(state, dict) else {}

    def recorded_crawl_id(self) -> str:
        """The crawl this journal belongs to (snapshot, else the ``begin``
        event) — the identity check that keeps a shared journal dir from
        silently resuming an unrelated crawl."""
        snap = self.load_snapshot()
        if snap.get("crawl_id"):
            return str(snap["crawl_id"])
        for event in self.events():
            if event.get("kind") == "begin" and event.get("crawl_id"):
                return str(event["crawl_id"])
        return ""

    def replay(self) -> RecoveredCrawl:
        """Fold snapshot + events into a :class:`RecoveredCrawl`.

        Pure function of the on-disk bytes: calling it twice returns the
        same recovery (asserted by tests — determinism is what makes the
        resume path debuggable)."""
        rec = RecoveredCrawl()
        snap = self.load_snapshot()
        if snap:
            rec.crawl_id = snap.get("crawl_id", "")
            rec.current_depth = int(snap.get("current_depth", 0))
            rec.total_work_items = int(snap.get("total_work_items", 0))
            rec.completed_items = int(snap.get("completed_items", 0))
            rec.error_items = int(snap.get("error_items", 0))
            rec.discovered_pages = int(snap.get("discovered_pages", 0))
            rec.crawl_completed = bool(snap.get("crawl_completed", False))
            rec.active_work = {str(k): dict(v) for k, v in
                               (snap.get("active_work") or {}).items()}
            rec.retry_counts = {str(k): int(v) for k, v in
                                (snap.get("retry_counts") or {}).items()}
            rec.applied_results = set(snap.get("applied_results") or [])
            # NOTE: snapshots deliberately carry no page fixups — the
            # compaction protocol saves the state manager FIRST, so page
            # statuses as of the snapshot live in the persisted sm state;
            # fixups come only from post-snapshot events.
        for event in self.events():
            self._fold(rec, event)
            rec.events_replayed += 1
        return rec

    @staticmethod
    def _fold(rec: RecoveredCrawl, event: Dict[str, Any]) -> None:
        # Folding is IDEMPOTENT per work-item id: a journal event may
        # describe state a concurrent compaction already baked into the
        # snapshot (the append can land just after truncation), so an
        # event whose item is already accounted for must be a no-op —
        # otherwise counters double-fold on replay.
        kind = event.get("kind")
        if kind == "begin":
            rec.crawl_id = event.get("crawl_id", rec.crawl_id)
        elif kind == "dispatch":
            item = event.get("item") or {}
            wid = str(item.get("id", ""))
            if wid and wid not in rec.active_work \
                    and wid not in rec.applied_results:
                rec.active_work[wid] = item
                rec.total_work_items += 1
        elif kind in ("requeue", "reassign"):
            rec.active_work.pop(str(event.get("old_id", "")), None)
            item = event.get("item") or {}
            wid = str(item.get("id", ""))
            if wid and wid not in rec.applied_results:
                rec.active_work[wid] = item
            page_id = event.get("page_id", "")
            if page_id and event.get("retries") is not None:
                rec.retry_counts[page_id] = int(event["retries"])
        elif kind == "result":
            wid = str(event.get("work_item_id", ""))
            if not wid:
                return
            already = wid in rec.applied_results
            rec.active_work.pop(wid, None)
            rec.applied_results.add(wid)
            if not already:
                # Counters fold once per id; the PAGE fixup below folds
                # unconditionally — it is idempotent (absolute status),
                # and a snapshot racing the result apply may have
                # persisted the page pre-transition while already
                # counting the id as applied.
                if event.get("status") == "success":
                    rec.completed_items += 1
                else:
                    rec.error_items += 1
                rec.discovered_pages += int(event.get("discovered", 0) or 0)
            page_id = event.get("page_id", "")
            if page_id:
                page_status = event.get("page_status", "")
                if page_status:
                    rec.page_fixups[page_id] = (page_status,
                                                event.get("error", "") or "")
                retries = event.get("retries")
                if retries:
                    rec.retry_counts[page_id] = int(retries)
                else:
                    rec.retry_counts.pop(page_id, None)
        elif kind == "abandon":
            wid = str(event.get("work_item_id", ""))
            if not wid:
                return
            already = wid in rec.applied_results
            rec.active_work.pop(wid, None)
            rec.applied_results.add(wid)
            if not already:
                rec.error_items += 1
            page_id = event.get("page_id", "")
            if page_id:
                rec.page_fixups[page_id] = (
                    event.get("page_status", "abandoned"),
                    event.get("error", "") or "")
                rec.retry_counts.pop(page_id, None)
        elif kind == "depth":
            rec.current_depth = int(event.get("depth", rec.current_depth))
        elif kind == "layer":
            pages = event.get("pages") or []
            rec.layers.append((int(event.get("depth", 0)), list(pages)))
        elif kind == "completed":
            rec.crawl_completed = True
        # Unknown kinds are ignored: journals must be forward-readable.
