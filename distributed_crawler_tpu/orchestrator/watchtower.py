"""The fleet watchtower: history + rules + alerts over the live fleet.

`FleetView` (fleet.py) folds every telemetry heartbeat into the *current*
picture of each worker; the watchtower is the part that remembers and
judges.  It sits beside the fleet view inside the orchestrator and:

- **feeds the rolling time-series store** (`utils/timeseries.py`) from
  every accepted heartbeat — time-weighted queue depth, MFU, per-chip
  goodput, device occupancy (busy/overlap/bubble), RSS, and the SLO
  breach counters the serving workers now carry in
  ``resource_usage["slo_breaches"]`` — one ``fleet_*`` series per worker
  (and per chip/SLO where labeled);
- **self-samples the orchestrator's own metrics registry** each tick
  through the shared exposition parser (`RegistrySampler`), which is how
  broker-side series (dead letters, outbox depth) and the fleet gauges
  gain history without bespoke plumbing, and derives
  ``watchtower_outbox_utilization{publisher}`` (depth/capacity) for the
  near-full rule;
- **evaluates the alert engine** (`utils/alerts.py`) on the
  orchestrator's tick cadence (rate-limited by ``eval_interval_s``),
  publishing every firing/resolved transition as a typed `AlertMessage`
  on ``TOPIC_ALERTS`` and serving the lifecycle state at ``/alerts``
  (`set_alerts_provider` in cli.py / the loadgen gate).

Worker processes keep their OWN history by self-sampling their registries
on the telemetry interval (`inference/worker.py`, `media/worker.py`), so
an orchestrator restart loses only the *fleet-wide* fold — each worker's
``/timeseries`` still carries its story, and the next orchestrator
generation re-folds from the first heartbeat.  No sidecar, no external
TSDB.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ..bus.messages import TOPIC_ALERTS, AlertMessage, StatusMessage
from ..utils.alerts import AlertEngine, AlertRule, default_rules
from ..utils.metrics import REGISTRY, MetricsRegistry
from ..utils.timeseries import STORE, RegistrySampler, TimeSeriesStore
from .tenants import TenantBudgetLedger

logger = logging.getLogger("dct.watchtower")


class Watchtower:
    """History + alerting beside one orchestrator's `FleetView`."""

    def __init__(self, fleet,
                 rules: Optional[List[AlertRule]] = None,
                 store: Optional[TimeSeriesStore] = None,
                 registry: MetricsRegistry = REGISTRY,
                 bus=None,
                 clock=time.time,
                 eval_interval_s: float = 5.0,
                 sample_registry: bool = True):
        self.fleet = fleet
        self.store = store if store is not None else STORE
        self.bus = bus
        self.clock = clock
        self.eval_interval_s = float(eval_interval_s)
        self.registry = registry
        self.engine = AlertEngine(
            rules if rules is not None else default_rules(),
            store=self.store, registry=registry, clock=clock,
            publish=self._publish_transition)
        self._sampler = RegistrySampler(registry, self.store) \
            if sample_registry else None
        # Per-tenant spend + error-budget view over the fleet folds
        # below (orchestrator/tenants.py); budgets are installed later
        # via ``tenants.configure`` (CLI config / scenario block).
        self.tenants = TenantBudgetLedger(store=self.store, clock=clock)
        self._mu = threading.Lock()
        self._last_eval = 0.0
        self._ticks = 0

    # -- heartbeat fold ------------------------------------------------------
    def observe_status(self, msg: StatusMessage,
                       wall: Optional[float] = None) -> None:
        """Fold one heartbeat's telemetry into per-worker series.  Called
        by `Orchestrator.handle_status` right after the FleetView fold;
        never raises (history must not break the registry path)."""
        try:
            self._observe(msg, wall)
        except Exception as e:
            logger.debug("watchtower heartbeat fold degraded: %s", e)

    def _observe(self, msg: StatusMessage, wall: Optional[float]) -> None:
        wall = self.clock() if wall is None else wall
        wid = msg.worker_id
        usage = msg.resource_usage or {}
        labels = {"worker": wid}
        queue = usage.get("queue") or {}
        depth = queue.get("depth_time_weighted", queue.get("depth"))
        if depth is None:
            depth = msg.queue_length
        self.store.add("fleet_queue_depth", float(depth), labels,
                       wall=wall)
        rss = usage.get("rss_bytes")
        if isinstance(rss, (int, float)):
            self.store.add("fleet_rss_bytes", float(rss), labels,
                           wall=wall)
        eff = usage.get("efficiency")
        if isinstance(eff, dict):
            for key, series in (("mfu", "fleet_mfu"),
                                ("goodput_tokens_per_s",
                                 "fleet_goodput_tokens_per_s")):
                value = eff.get(key)
                if isinstance(value, (int, float)):
                    self.store.add(series, float(value), labels, wall=wall)
            for chip in (eff.get("per_chip") or []):
                if not isinstance(chip, dict):
                    continue
                goodput = chip.get("goodput_tokens_per_s")
                if isinstance(goodput, (int, float)):
                    self.store.add(
                        "fleet_per_chip_goodput_tokens_per_s",
                        float(goodput),
                        {"worker": wid,
                         "device": str(chip.get("device", "?"))},
                        wall=wall)
        occ = usage.get("occupancy")
        if isinstance(occ, dict):
            for key, series in (
                    ("busy_fraction", "fleet_occupancy_busy"),
                    ("overlap_fraction", "fleet_occupancy_overlap"),
                    ("bubble_share", "fleet_occupancy_bubble_share")):
                value = occ.get(key)
                if isinstance(value, (int, float)):
                    self.store.add(series, float(value), labels, wall=wall)
        breaches = usage.get("slo_breaches")
        if isinstance(breaches, dict):
            # Cumulative per-SLO breach counts from the worker's own
            # watchdog: the series the default burn-rate rules read.
            # Counter resets across worker restarts are absorbed by the
            # store's reset-aware increase().
            for slo, count in breaches.items():
                if isinstance(count, (int, float)):
                    self.store.add("fleet_slo_breach_total", float(count),
                                   {"worker": wid, "slo": str(slo)},
                                   wall=wall)
        # Per-tenant spend + breach folds (ISSUE 17): the worker's
        # TenantLedger rows and the watchdog's tenant-labeled breach
        # split become fleet series — what /tenants and the error-budget
        # ledger read.  Cumulative counters, so restarts are absorbed by
        # increase() exactly like the aggregate breach fold above.
        tenants = usage.get("tenants")
        if isinstance(tenants, dict):
            for row in (tenants.get("rows") or []):
                if not isinstance(row, dict):
                    continue
                tenant = str(row.get("tenant") or "")
                if not tenant:
                    continue
                tlabels = {"worker": wid, "tenant": tenant}
                for key, series in (
                        ("chip_seconds", "fleet_tenant_chip_seconds_total"),
                        ("flops", "fleet_tenant_flops_total"),
                        ("real_tokens", "fleet_tenant_real_tokens_total"),
                        ("batches", "fleet_tenant_batches_total")):
                    value = row.get(key)
                    if isinstance(value, (int, float)):
                        self.store.add(series, float(value), tlabels,
                                       wall=wall)
                p95 = row.get("queue_wait_p95_s")
                if isinstance(p95, (int, float)):
                    self.store.add("fleet_tenant_queue_wait_p95_seconds",
                                   float(p95), tlabels, wall=wall)
        tenant_breaches = usage.get("tenant_slo_breaches")
        if isinstance(tenant_breaches, dict):
            for tenant, slos in tenant_breaches.items():
                if not isinstance(slos, dict):
                    continue
                for slo, count in slos.items():
                    if isinstance(count, (int, float)):
                        self.store.add(
                            "fleet_tenant_slo_breach_total", float(count),
                            {"worker": wid, "tenant": str(tenant),
                             "slo": str(slo)}, wall=wall)

    # -- the tick ------------------------------------------------------------
    def tick(self, now: Optional[float] = None,
             force: bool = False) -> List[Dict[str, Any]]:
        """One watchtower pass: orchestrator-side series + registry
        self-sample + alert evaluation.  Rate-limited to
        ``eval_interval_s`` (the orchestrator calls this from both its
        distribute and health ticks; the gate calls it from its drive
        loop at 50 Hz) — ``force=True`` bypasses the limiter for
        deterministic tests/phase boundaries.  Returns the alert
        transitions this pass produced."""
        now = self.clock() if now is None else now
        with self._mu:
            if not force and now - self._last_eval < self.eval_interval_s:
                return []
            self._last_eval = now
            self._ticks += 1
        try:
            if self._sampler is not None:
                # The registry sample captures fleet_stale_workers via
                # its fn-bound gauge — an explicit add here would write
                # the same series twice per tick.
                self._sampler.sample(now=now)
            else:
                self.store.add("fleet_stale_workers",
                               float(self.fleet.stale_count()), wall=now)
            self._derive_outbox_utilization(now)
        except Exception as e:
            logger.debug("watchtower sampling degraded: %s", e)
        return self.engine.evaluate(now=now)

    def _derive_outbox_utilization(self, now: float) -> None:
        """``watchtower_outbox_utilization{publisher}`` = depth/capacity
        from the outbox gauges (`bus/outbox.py`) — the ratio the
        near-full rule thresholds on (raw depth would need per-site
        bounds)."""
        depth = self.registry.gauge("bus_outbox_depth")
        capacity = self.registry.gauge("bus_outbox_capacity")
        caps = {tuple(sorted(labels.items())): value
                for labels, value in capacity.series() if labels}
        for labels, value in depth.series():
            if not labels:
                continue
            cap = caps.get(tuple(sorted(labels.items())), 0.0)
            if cap > 0:
                self.store.add("watchtower_outbox_utilization",
                               value / cap, labels, wall=now)

    # -- export --------------------------------------------------------------
    def get_alerts(self) -> Dict[str, Any]:
        """The ``/alerts`` JSON body (registered via
        `utils.metrics.set_alerts_provider`)."""
        body = self.engine.snapshot()
        with self._mu:
            body["watchtower"] = {
                "ticks": self._ticks,
                "eval_interval_s": self.eval_interval_s,
                "series_count": len(self.store.keys()),
            }
        return body

    def get_tenants(self) -> Dict[str, Any]:
        """The ``/tenants`` JSON body (registered via
        `utils.metrics.set_tenants_provider`)."""
        return self.tenants.view()

    def firing(self) -> List[str]:
        return self.engine.firing()

    # -- publish seam --------------------------------------------------------
    def _publish_transition(self, event: Dict[str, Any]) -> None:
        if self.bus is None:
            return
        msg = AlertMessage.new(
            rule=event["rule"], kind=event["kind"],
            series=event["series"], state=event["to"],
            prev_state=event["from"], severity=event["severity"],
            value=event["value"], detail=event.get("detail"),
            at_wall=event["at"])
        # Publish errors are caught by the engine's publish guard — the
        # bus must never break an evaluation pass.
        self.bus.publish(TOPIC_ALERTS, msg.to_dict())
