"""First-class metrics: counters, gauges, latency histograms, text endpoint.

The reference has no metrics endpoint — its health server returns Hello World
(`dapr/standalone.go:31-33,115-122`) and throughput is greppable log lines.
SURVEY.md §5.5 calls out the gap; the BASELINE north-star metrics
(posts/sec/chip, p50 batch latency) are first-class here: a tiny in-process
registry with Prometheus-style text exposition, no external deps.
"""

from __future__ import annotations

import bisect
import logging
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

# Latency buckets in seconds: 1 ms .. 60 s, roughly log-spaced.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

LabelKey = Tuple[Tuple[str, str], ...]


def _escape_help(help_: str) -> str:
    """Prometheus text-format HELP escaping: a literal backslash or newline
    in the help string would corrupt the exposition."""
    return help_.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_key(kv: Dict[str, object]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in kv.items()))


def _label_str(items: LabelKey,
               extra: Optional[Tuple[str, str]] = None) -> str:
    parts = [f'{k}="{_escape_label_value(v)}"' for k, v in items]
    if extra is not None:
        parts.append(f'{extra[0]}="{_escape_label_value(extra[1])}"')
    return "{" + ",".join(parts) + "}" if parts else ""


class _LabeledMixin:
    """`.labels(platform="telegram")`-style child metrics.

    The parent owns the name/help/TYPE header and an (always-exposed)
    unlabeled series; each distinct label set gets one child instance of
    the same class, exposed as additional `name{k="v"} value` series.
    Children are created once and cached, so hot paths can call
    ``labels(...)`` per observation without allocation churn.
    """

    _label_items: LabelKey = ()

    def labels(self, **kv: object):
        if self._label_items:
            raise ValueError(
                f"labels() on an already-labeled child of {self.name}")
        if not kv:
            return self
        key = _label_key(kv)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                child._label_items = key
                self._children[key] = child
        return child

    def _child_snapshot(self) -> list:
        """Children in deterministic (sorted label) order, snapshotted
        under the parent lock."""
        with self._lock:
            return [c for _, c in sorted(self._children.items())]

    def _read(self) -> float:
        with self._lock:
            return self._value

    def series(self) -> list:
        """[(labels_dict, value)] for the parent and every labeled child —
        the programmatic read the telemetry snapshots use (exposition is
        for scrapers; this is for heartbeats).  Value-bearing metrics only
        (Counter/Gauge)."""
        return [(dict(m._label_items), m._read())
                for m in [self] + self._child_snapshot()]

    def remove_labels(self, **kv: object) -> None:
        """Drop the child for this exact label set (no-op if absent) —
        eviction support so per-worker series don't accumulate forever
        as workers come and go."""
        with self._lock:
            self._children.pop(_label_key(kv), None)


class Counter(_LabeledMixin):
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._value = 0.0
        self._lock = threading.Lock()
        self._children: Dict[LabelKey, "Counter"] = {}

    def _make_child(self) -> "Counter":
        return Counter(self.name, self.help)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} counter"]
        for m in [self] + self._child_snapshot():
            with m._lock:
                value = m._value
            lines.append(f"{self.name}{_label_str(m._label_items)} {value}")
        return "\n".join(lines) + "\n"


class Gauge(_LabeledMixin):
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._value = 0.0
        self._fn = None
        self._lock = threading.Lock()
        self._children: Dict[LabelKey, "Gauge"] = {}

    def _make_child(self) -> "Gauge":
        return Gauge(self.name, self.help)

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value

    def set_fn(self, fn) -> None:
        """Bind a zero-arg callable: the gauge's value is computed at
        READ time (expose/value/series) instead of at the last set().
        For values that are a function of *now* — staleness counts,
        ages — a stored value is only as fresh as its last writer's
        tick, so a scrape between ticks reads stale truth; a callable
        gauge cannot.  Pass None to unbind.  The callable must not
        touch this gauge (it runs outside the lock; a set() from inside
        it would deadlock-free but be overwritten)."""
        self._fn = fn

    def _read(self) -> float:
        fn = self._fn
        if fn is not None:
            try:
                value = float(fn())
            except Exception as e:
                # Degrade to the last stored value — a scrape must not
                # 500 because one computed gauge's provider broke.
                logging.getLogger("dct.metrics").debug(
                    "gauge %s value fn failed: %s", self.name, e)
                with self._lock:
                    return self._value
            with self._lock:
                self._value = value
            return value
        with self._lock:
            return self._value

    def add(self, amount: float) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._read()

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} gauge"]
        for m in [self] + self._child_snapshot():
            # _read(), not the stored value: fn-bound gauges (set_fn)
            # compute at scrape time so /metrics is never staler than
            # its reader.
            lines.append(
                f"{self.name}{_label_str(m._label_items)} {m._read()}")
        return "\n".join(lines) + "\n"


class Histogram(_LabeledMixin):
    """Bucketed histogram with exact quantiles over a bounded sample window."""

    def __init__(self, name: str, help_: str = "",
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                 window: int = 4096):
        self.name, self.help = name, help_
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._n = 0
        self._window: List[float] = []
        self._window_cap = window
        self._lock = threading.Lock()
        self._children: Dict[LabelKey, "Histogram"] = {}

    def _make_child(self) -> "Histogram":
        return Histogram(self.name, self.help, self.buckets,
                         self._window_cap)

    def observe(self, value: float) -> None:
        with self._lock:
            i = bisect.bisect_left(self.buckets, value)
            self._counts[i] += 1
            self._sum += value
            self._n += 1
            self._window.append(value)
            if len(self._window) > self._window_cap:
                # Drop the oldest half to amortize the trim.
                self._window = self._window[self._window_cap // 2:]

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._window:
                return None
            s = sorted(self._window)
            idx = min(len(s) - 1, max(0, int(q * (len(s) - 1))))
            return s[idx]

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def _series_lines(self, items: LabelKey) -> List[str]:
        # Snapshot counts/sum/count ATOMICALLY under the lock: a concurrent
        # observe() between the bucket walk and the _count line would
        # otherwise expose cumulative buckets that disagree with _count.
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._n
        lines = []
        cum = 0
        for bound, c in zip(self.buckets, counts):
            cum += c
            lines.append(f"{self.name}_bucket"
                         f"{_label_str(items, ('le', str(bound)))} {cum}")
        cum += counts[-1]
        lines.append(f"{self.name}_bucket"
                     f"{_label_str(items, ('le', '+Inf'))} {cum}")
        lines.append(f"{self.name}_sum{_label_str(items)} {total}")
        lines.append(f"{self.name}_count{_label_str(items)} {n}")
        return lines

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} histogram"]
        for m in [self] + self._child_snapshot():
            lines.extend(m._series_lines(m._label_items))
        return "\n".join(lines) + "\n"


class MetricsRegistry:
    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_make(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_make(name, lambda: Gauge(name, help_), Gauge)

    def histogram(self, name: str, help_: str = "",
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_make(
            name, lambda: Histogram(name, help_, buckets), Histogram)

    def _get_or_make(self, name, factory, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif not isinstance(m, cls):
                raise ValueError(f"metric {name} already registered as "
                                 f"{type(m).__name__}")
            return m

    def expose(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        return "".join(m.expose() for m in metrics)


REGISTRY = MetricsRegistry()

# Late-bound /status provider: the metrics server starts before the
# orchestrator/worker exists, so the service registers its `get_status`
# here once constructed.
_status_provider = None


def set_status_provider(fn) -> None:
    """Register the zero-arg dict provider served at /status (pass None to
    clear)."""
    global _status_provider
    _status_provider = fn


def clear_status_provider(fn) -> None:
    """Unregister ``fn`` only if it is still the active provider — a
    component stopping must not yank a provider someone else registered
    after it (bound methods compare by (instance, function))."""
    global _status_provider
    if _status_provider == fn:
        _status_provider = None


# Late-bound /cluster provider: same seam as /status, but for the
# orchestrator's fleet view (`orchestrator/fleet.py`) — one JSON map of
# every worker's last heartbeat, telemetry, rates, and staleness.
_cluster_provider = None


def set_cluster_provider(fn) -> None:
    """Register the zero-arg dict provider served at /cluster (pass None
    to clear)."""
    global _cluster_provider
    _cluster_provider = fn


def clear_cluster_provider(fn) -> None:
    """Unregister ``fn`` only if it is still the active provider."""
    global _cluster_provider
    if _cluster_provider == fn:
        _cluster_provider = None


# Late-bound /costs provider: the engine's hardware-efficiency view
# (`utils/costmodel.py`) — per-bucket compiled FLOPs/bytes, rolling
# MFU/goodput, SLO budgets + breach counts.
_costs_provider = None


def set_costs_provider(fn) -> None:
    """Register the zero-arg dict provider served at /costs (pass None
    to clear)."""
    global _costs_provider
    _costs_provider = fn


def clear_costs_provider(fn) -> None:
    """Unregister ``fn`` only if it is still the active provider."""
    global _costs_provider
    if _costs_provider == fn:
        _costs_provider = None


# Late-bound /dlq provider: the broker's dead-letter view
# (`bus/grpc_bus.py:GrpcBusServer.dlq_snapshot`) — per-topic counts +
# entry metadata from the persisted dead-letter spool (`bus/spool.py`),
# full payload for an explicit ?topic=&id= lookup.
_dlq_provider = None


def set_dlq_provider(fn) -> None:
    """Register the dict provider served at /dlq (``fn(topic=..., id=...)``
    or zero-arg; pass None to clear)."""
    global _dlq_provider
    _dlq_provider = fn


def clear_dlq_provider(fn) -> None:
    """Unregister ``fn`` only if it is still the active provider."""
    global _dlq_provider
    if _dlq_provider == fn:
        _dlq_provider = None


# Late-bound /dtraces provider: the orchestrator's distributed-trace
# collector (`orchestrator/tracecollect.py`) — assembled cross-process
# traces with clock-offset-corrected span walls.
_dtraces_provider = None


def set_dtraces_provider(fn) -> None:
    """Register the dict provider served at /dtraces (``fn(limit=N)`` or
    zero-arg; pass None to clear)."""
    global _dtraces_provider
    _dtraces_provider = fn


def clear_dtraces_provider(fn) -> None:
    """Unregister ``fn`` only if it is still the active provider."""
    global _dtraces_provider
    if _dtraces_provider == fn:
        _dtraces_provider = None


def dtraces_snapshot():
    """The active /dtraces body, or None without a provider — the
    flight recorder calls this so postmortem bundles carry the
    assembled distributed traces a dead process can no longer serve."""
    fn = _dtraces_provider
    if fn is None:
        return None
    try:
        return fn()
    except Exception as e:
        return {"error": str(e)}


# Late-bound /alerts provider: the watchtower's alert-engine snapshot
# (`orchestrator/watchtower.py` over `utils/alerts.py`) — per-rule
# lifecycle state + the bounded transition log.
_alerts_provider = None


def set_alerts_provider(fn) -> None:
    """Register the zero-arg dict provider served at /alerts (pass None
    to clear)."""
    global _alerts_provider
    _alerts_provider = fn


def clear_alerts_provider(fn) -> None:
    """Unregister ``fn`` only if it is still the active provider."""
    global _alerts_provider
    if _alerts_provider == fn:
        _alerts_provider = None


def alerts_snapshot():
    """The active /alerts body, or None without a provider — the flight
    recorder calls this so postmortem bundles carry the alert history a
    dead process can no longer serve."""
    fn = _alerts_provider
    if fn is None:
        return None
    try:
        return fn()
    except Exception as e:
        return {"error": str(e)}


# Late-bound /clusters provider: the streaming clustering worker's
# centroid-state view (`cluster/worker.py`) — per-cluster sizes,
# centroid norms, inertia trend, assignment throughput, checkpoint +
# resume state.
_clusters_provider = None


def set_clusters_provider(fn) -> None:
    """Register the zero-arg dict provider served at /clusters (pass
    None to clear)."""
    global _clusters_provider
    _clusters_provider = fn


def clear_clusters_provider(fn) -> None:
    """Unregister ``fn`` only if it is still the active provider."""
    global _clusters_provider
    if _clusters_provider == fn:
        _clusters_provider = None


def clusters_snapshot():
    """The active /clusters body, or None without a provider — the
    flight recorder calls this so postmortem bundles carry the centroid
    state a dead cluster worker can no longer serve."""
    fn = _clusters_provider
    if fn is None:
        return None
    try:
        return fn()
    except Exception as e:
        return {"error": str(e)}


# Late-bound /shards provider: the partitioned bus's per-shard view
# (`bus/partition.py:PartitionedBus.snapshot`) — per-shard address,
# generation, queue depth, outbox depth/parked frames, and circuit-
# breaker state, plus the consistent-hash ring summary.
_shards_provider = None


def set_shards_provider(fn) -> None:
    """Register the zero-arg dict provider served at /shards (pass None
    to clear)."""
    global _shards_provider
    _shards_provider = fn


def clear_shards_provider(fn) -> None:
    """Unregister ``fn`` only if it is still the active provider."""
    global _shards_provider
    if _shards_provider == fn:
        _shards_provider = None


def shards_snapshot():
    """The active /shards body, or None without a provider — the flight
    recorder calls this so postmortem bundles carry the per-shard bus
    state ("which shard was parked/broken before the crash")."""
    fn = _shards_provider
    if fn is None:
        return None
    try:
        return fn()
    except Exception as e:
        return {"error": str(e)}


# Late-bound /autoscaler provider: the elastic-fleet control plane's
# snapshot (`orchestrator/autoscaler.py`) — per-pool desired vs actual,
# policy bounds, cooldown state, and the bounded decision log.
_autoscaler_provider = None


def set_autoscaler_provider(fn) -> None:
    """Register the zero-arg dict provider served at /autoscaler (pass
    None to clear)."""
    global _autoscaler_provider
    _autoscaler_provider = fn


def clear_autoscaler_provider(fn) -> None:
    """Unregister ``fn`` only if it is still the active provider."""
    global _autoscaler_provider
    if _autoscaler_provider == fn:
        _autoscaler_provider = None


def autoscaler_snapshot():
    """The active /autoscaler body, or None without a provider — the
    flight recorder calls this so postmortem bundles carry the decision
    log ("what the autoscaler did before the crash")."""
    fn = _autoscaler_provider
    if fn is None:
        return None
    try:
        return fn()
    except Exception as e:
        return {"error": str(e)}


# Late-bound /tenants provider: the watchtower's per-tenant accounting
# view (`orchestrator/tenants.py`) — fleet spend rows folded from worker
# heartbeats plus the error-budget ledger (windowed burn per tenant per
# SLO, remaining budget, exhaustion projection).
_tenants_provider = None


def set_tenants_provider(fn) -> None:
    """Register the zero-arg dict provider served at /tenants (pass
    None to clear)."""
    global _tenants_provider
    _tenants_provider = fn


def clear_tenants_provider(fn) -> None:
    """Unregister ``fn`` only if it is still the active provider."""
    global _tenants_provider
    if _tenants_provider == fn:
        _tenants_provider = None


def tenants_snapshot():
    """The active /tenants body, or None without a provider — the
    flight recorder calls this so postmortem bundles carry the tenant
    spend + error-budget state a dead process can no longer serve."""
    fn = _tenants_provider
    if fn is None:
        return None
    try:
        return fn()
    except Exception as e:
        return {"error": str(e)}


def logs_snapshot():
    """The /logs body (the structured-log ring from utils/structlog.py)
    — the flight recorder calls this so postmortem bundles carry the
    last WARNING+ records a dead process can no longer serve.  Returns
    None when the ring is empty so bundles stay byte-identical for
    processes that never warned."""
    from . import structlog as _structlog

    records = _structlog.ring_snapshot()
    if not records:
        return None
    return {"records": records}


class _Handler(BaseHTTPRequestHandler):
    registry: MetricsRegistry = REGISTRY

    def do_GET(self):  # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0].rstrip("/")
        code = 200
        if path in ("", "/health", "/healthz"):
            body = b"ok\n"
            ctype = "text/plain"
        elif path == "/metrics":
            body = self.registry.expose().encode("utf-8")
            ctype = "text/plain; version=0.0.4"
        elif path == "/traces":
            # Completed traces (spans grouped by trace_id, newest first)
            # from the process-wide tracer — the JSON export half of
            # utils/trace.py; ?limit=N caps the trace count.
            import json as _json
            from urllib.parse import parse_qs as _parse_qs

            from . import trace as _trace

            query = self.path.partition("?")[2]
            try:
                limit = int(_parse_qs(query).get("limit", ["0"])[0])
            except (ValueError, TypeError):
                limit = 0
            body = _json.dumps(_trace.TRACER.export(limit=limit),
                               default=str).encode("utf-8")
            ctype = "application/json"
        elif path == "/status" and _status_provider is not None:
            # The orchestrator/worker `get_status()` map
            # (`orchestrator.go:596`, `worker.go:459`) served as JSON.
            import json as _json

            try:
                body = _json.dumps(_status_provider(),
                                   default=str).encode("utf-8")
            except Exception as e:
                # Visible to status-code monitors, one response per
                # request (no retry loop server-side).
                code = 500
                body = _json.dumps({"error": str(e)}).encode("utf-8")
            ctype = "application/json"
        elif path == "/costs" and _costs_provider is not None:
            # The engine's cost/efficiency view (`utils/costmodel.py`):
            # per-bucket compiled FLOPs, rolling MFU/goodput, SLO state —
            # rendered by tools/perfreport.py.
            import json as _json

            try:
                body = _json.dumps(_costs_provider(),
                                   default=str).encode("utf-8")
            except Exception as e:
                code = 500
                body = _json.dumps({"error": str(e)}).encode("utf-8")
            ctype = "application/json"
        elif path == "/profile":
            # Guarded on-demand jax.profiler capture
            # (`utils/profiling.py`): blocks THIS request thread for the
            # bounded window, one capture at a time process-wide; the
            # trace bundle lands under --dump-dir.
            import json as _json
            from urllib.parse import parse_qs as _parse_qs

            from . import profiling as _profiling

            query = self.path.partition("?")[2]
            seconds = _parse_qs(query).get("seconds", ["1"])[0]
            result = _profiling.capture(seconds)
            code = int(result.pop("code", 200 if result.get("ok") else 500))
            body = _json.dumps(result).encode("utf-8")
            ctype = "application/json"
        elif path == "/dtraces" and _dtraces_provider is not None:
            # Assembled DISTRIBUTED traces (spans from every process that
            # exported on TOPIC_SPANS, clock-offset-corrected) from the
            # trace collector; ?limit=N caps the trace count.  Rendered
            # by tools/trace_dump.py --collector / tools/critpath.py.
            import json as _json
            from urllib.parse import parse_qs as _parse_qs

            query = self.path.partition("?")[2]
            try:
                limit = int(_parse_qs(query).get("limit", ["0"])[0])
            except (ValueError, TypeError):
                limit = 0
            try:
                try:
                    payload = _dtraces_provider(limit=limit)
                except TypeError:  # zero-arg providers are fine too
                    payload = _dtraces_provider()
                body = _json.dumps(payload, default=str).encode("utf-8")
            except Exception as e:
                code = 500
                body = _json.dumps({"error": str(e)}).encode("utf-8")
            ctype = "application/json"
        elif path == "/dlq" and _dlq_provider is not None:
            # The broker's dead-letter queue (`bus/spool.py`): per-topic
            # counts + newest entries; ?topic=&id= returns one entry's
            # full payload (base64).  Rendered/replayed by tools/dlq.py.
            import json as _json
            from urllib.parse import parse_qs as _parse_qs

            query = _parse_qs(self.path.partition("?")[2])
            topic = (query.get("topic") or [""])[0]
            entry_id = (query.get("id") or [""])[0]
            try:
                try:
                    payload = _dlq_provider(topic=topic or None,
                                            id=entry_id or None)
                except TypeError:  # zero-arg providers are fine too
                    payload = _dlq_provider()
                body = _json.dumps(payload, default=str).encode("utf-8")
            except Exception as e:
                code = 500
                body = _json.dumps({"error": str(e)}).encode("utf-8")
            ctype = "application/json"
        elif path == "/alerts" and _alerts_provider is not None:
            # The watchtower's alert surface (`utils/alerts.py` via
            # `orchestrator/watchtower.py`): per-rule lifecycle state
            # (inactive/pending/firing/resolved), evaluated values, and
            # the bounded transition log.  Rendered by tools/watch.py.
            import json as _json

            try:
                body = _json.dumps(_alerts_provider(),
                                   default=str).encode("utf-8")
            except Exception as e:
                code = 500
                body = _json.dumps({"error": str(e)}).encode("utf-8")
            ctype = "application/json"
        elif path == "/clusters" and _clusters_provider is not None:
            # The streaming clustering view (`cluster/worker.py`):
            # per-cluster sizes + centroid norms, inertia trend,
            # assignment throughput, and checkpoint/resume state.
            # Rendered by tools/watch.py's clusters panel.
            import json as _json

            try:
                body = _json.dumps(_clusters_provider(),
                                   default=str).encode("utf-8")
            except Exception as e:
                code = 500
                body = _json.dumps({"error": str(e)}).encode("utf-8")
            ctype = "application/json"
        elif path == "/shards" and _shards_provider is not None:
            # The partitioned bus's shard table (`bus/partition.py`):
            # per-shard address/generation/alive, queue + outbox depth,
            # breaker state, routed-frame counts, and the ring summary.
            # Rendered by tools/watch.py's shards panel.
            import json as _json

            try:
                body = _json.dumps(_shards_provider(),
                                   default=str).encode("utf-8")
            except Exception as e:
                code = 500
                body = _json.dumps({"error": str(e)}).encode("utf-8")
            ctype = "application/json"
        elif path == "/autoscaler" and _autoscaler_provider is not None:
            # The elastic-fleet control plane (`orchestrator/
            # autoscaler.py`): per-pool desired vs actual worker counts,
            # policy bounds + cooldowns, and the recent scale-decision
            # log.  Rendered by tools/watch.py's autoscaler panel.
            import json as _json

            try:
                body = _json.dumps(_autoscaler_provider(),
                                   default=str).encode("utf-8")
            except Exception as e:
                code = 500
                body = _json.dumps({"error": str(e)}).encode("utf-8")
            ctype = "application/json"
        elif path == "/tenants" and _tenants_provider is not None:
            # The watchtower's per-tenant accounting surface
            # (`orchestrator/tenants.py`): fleet spend rows by tenant +
            # the error-budget ledger (windowed burn per SLO, remaining
            # budget, exhaustion projection).  Rendered by
            # tools/watch.py's tenants panel and gated by loadgen.
            import json as _json

            try:
                body = _json.dumps(_tenants_provider(),
                                   default=str).encode("utf-8")
            except Exception as e:
                code = 500
                body = _json.dumps({"error": str(e)}).encode("utf-8")
            ctype = "application/json"
        elif path == "/logs":
            # The bounded structured-log ring (`utils/structlog.py`):
            # the last N WARNING+ records with trace_id correlation.
            # Served unconditionally (the /traces pattern): a process
            # that never warned answers with zero records, not a 404.
            # ?limit=N caps the record count (newest kept).
            import json as _json
            from urllib.parse import parse_qs as _parse_qs

            from . import structlog as _structlog

            query = self.path.partition("?")[2]
            try:
                limit = int(_parse_qs(query).get("limit", ["0"])[0])
            except (ValueError, TypeError):
                limit = 0
            try:
                records = _structlog.ring_snapshot(limit=limit)
                body = _json.dumps({"records": records},
                                   default=str).encode("utf-8")
            except Exception as e:
                code = 500
                body = _json.dumps({"error": str(e)}).encode("utf-8")
            ctype = "application/json"
        elif path == "/timeseries":
            # The process-local rolling time-series store
            # (`utils/timeseries.py:STORE`): worker self-samples and/or
            # the orchestrator's fleet folds.  ?series= filters by metric
            # name or exact series key, ?window= downsamples into
            # epoch-aligned buckets, ?since= bounds history in seconds.
            # Served unconditionally (the TRACER /traces pattern): an
            # empty store answers with zero series, not a 404.
            import json as _json
            from urllib.parse import parse_qs as _parse_qs

            from . import timeseries as _timeseries

            query = _parse_qs(self.path.partition("?")[2])

            def _qfloat(key: str) -> float:
                try:
                    return float((query.get(key) or ["0"])[0])
                except (ValueError, TypeError):
                    return 0.0

            try:
                body = _json.dumps(_timeseries.STORE.snapshot(
                    series=(query.get("series") or [""])[0] or None,
                    window_s=_qfloat("window"),
                    since_s=_qfloat("since")),
                    default=str).encode("utf-8")
            except Exception as e:
                code = 500
                body = _json.dumps({"error": str(e)}).encode("utf-8")
            ctype = "application/json"
        elif path == "/cluster" and _cluster_provider is not None:
            # The orchestrator's fleet view: per-worker last-seen, status
            # history, heartbeat telemetry, task rates, staleness rollup
            # (`orchestrator/fleet.py`; rendered by tools/postmortem.py).
            import json as _json

            try:
                body = _json.dumps(_cluster_provider(),
                                   default=str).encode("utf-8")
            except Exception as e:
                code = 500
                body = _json.dumps({"error": str(e)}).encode("utf-8")
            ctype = "application/json"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence request logging
        pass


def serve_metrics(port: int, registry: MetricsRegistry = REGISTRY
                  ) -> ThreadingHTTPServer:
    """Start the /metrics + /healthz (+ /status once a provider is
    registered via ``set_status_provider``) endpoint on a daemon thread.
    Returns the server (call .shutdown() to stop). Port 0 picks a free
    port (server.server_address[1])."""
    handler = type("Handler", (_Handler,), {"registry": registry})
    server = ThreadingHTTPServer(("127.0.0.1", port), handler)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="metrics-http")
    t.start()
    return server


@dataclass
class Timer:
    """Context manager observing elapsed seconds into a histogram."""

    histogram: Histogram
    _start: float = field(default=0.0, init=False)

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.histogram.observe(time.perf_counter() - self._start)
        return False
