"""Time/duration parsing for CLI parameters.

Parity with the reference's `main.go`:
- `parseTimeAgo` ("30d", "6h", "2w", "1m", "1y") -> cutoff datetime
  (`main.go:91-142`)
- date-between "YYYY-MM-DD,YYYY-MM-DD" parsing (`main.go:432-471`)
- Go-style duration strings for --max-crawl-duration ("2h45m", "90s")
"""

from __future__ import annotations

import re
from datetime import datetime, timedelta, timezone
from typing import Optional, Tuple

_UNITS_MSG = "must be a number followed by a unit (h,d,w,m,y)"


def _add_months(dt: datetime, months: int) -> datetime:
    """Calendar-aware month arithmetic (Go time.AddDate semantics, normalized)."""
    month_index = dt.month - 1 + months
    year = dt.year + month_index // 12
    month = month_index % 12 + 1
    # Go normalizes overflow days (Jan 31 - 1 month -> Dec 31; Mar 31 -1m -> "Mar 3"),
    # we clamp instead: the cutoff is a filter boundary, not a calendar identity.
    day = min(dt.day, [31, 29 if year % 4 == 0 and (year % 100 != 0 or year % 400 == 0) else 28,
                       31, 30, 31, 30, 31, 31, 30, 31, 30, 31][month - 1])
    return dt.replace(year=year, month=month, day=day)


def parse_time_ago(time_ago: str, now: Optional[datetime] = None) -> Optional[datetime]:
    """Parse "<N><unit>" into a cutoff datetime (`main.go:91-142`).

    Empty string -> None (no cutoff).
    """
    if not time_ago:
        return None
    unit = time_ago[-1]
    value_str = time_ago[:-1]
    m = re.match(r"^\s*(\d+)", value_str)
    if not m:
        raise ValueError(f"invalid time-ago format, {_UNITS_MSG}: {time_ago!r}")
    value = int(m.group(1))
    now = now or datetime.now(timezone.utc)
    if unit == "h":
        return now - timedelta(hours=value)
    if unit == "d":
        return now - timedelta(days=value)
    if unit == "w":
        return now - timedelta(weeks=value)
    if unit == "m":
        return _add_months(now, -value)
    if unit == "y":
        return _add_months(now, -12 * value)
    raise ValueError(
        f"invalid time unit '{unit}', must be h (hours), d (days), w (weeks), "
        "m (months), or y (years)"
    )


def parse_date_between(spec: str) -> Tuple[datetime, datetime]:
    """Parse "YYYY-MM-DD,YYYY-MM-DD" into (min, max) (`main.go:432-471`)."""
    dates = spec.split(",")
    if len(dates) != 2:
        raise ValueError("invalid date-between format, must be 'YYYY-MM-DD,YYYY-MM-DD'")
    try:
        min_date = datetime.strptime(dates[0].strip(), "%Y-%m-%d").replace(tzinfo=timezone.utc)
    except ValueError as e:
        raise ValueError(f"invalid min date in date-between format, must be YYYY-MM-DD: {e}")
    try:
        max_date = datetime.strptime(dates[1].strip(), "%Y-%m-%d").replace(tzinfo=timezone.utc)
    except ValueError as e:
        raise ValueError(f"invalid max date in date-between format, must be YYYY-MM-DD: {e}")
    if min_date > max_date:
        raise ValueError("min date must be before max date in date-between")
    return min_date, max_date


_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ms|h|m|s)")


def parse_duration(spec: str) -> float:
    """Go-style duration string ("2h45m", "90s", "500ms") -> seconds."""
    if not spec:
        return 0.0
    matches = _DURATION_RE.findall(spec)
    if not matches or "".join(f"{n}{u}" for n, u in matches) != spec.replace(" ", ""):
        raise ValueError(f"invalid duration: {spec!r}")
    mult = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 0.001}
    return sum(float(n) * mult[u] for n, u in matches)
