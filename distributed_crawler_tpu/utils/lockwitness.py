"""Runtime lock-order witness — the race-detector half of crawlint.

Static LCK checks (tools/analyze) see one class at a time; they cannot
see that worker thread A takes the spool lock then the metrics lock
while watchtower thread B takes them in the other order.  This module is
the dynamic complement, shaped after the kernel's lockdep and the Go
race detector's happens-before witness:

- **Creation-site interposition.**  :func:`install` replaces
  ``threading.Lock/RLock/Condition`` with factories that inspect the
  *caller's* frame: locks created by files under ``distributed_crawler_tpu/``
  come back wrapped in a witness proxy; everything else (stdlib, jax,
  tests) gets the original object.  Nothing is patched until install()
  runs, so the off path is exactly zero overhead.
- **Lock-order graph.**  Each proxy is keyed by its creation site
  (``file.py:line``).  On every acquire the witness records an edge
  held-site → acquired-site for each lock the thread already holds,
  with both witness stacks captured on the edge's first occurrence.  A
  new edge that closes a directed cycle is a potential deadlock
  (LKW001): two threads already demonstrated they take the same locks
  in opposite orders, even if the fatal interleaving never fired.
- **Blocking-under-lock.**  ``time.sleep``, ``Thread.join``,
  ``subprocess.Popen.wait``, ``queue.Queue.get``, ``socket.recv/accept``
  and ``Condition.wait`` on a *different* lock are patched to record a
  finding (LKW002) when called with an instrumented lock held — the
  dynamic analog of static LCK002, with wall-clock durations.
- **Hold-time accounting.**  Per-site count/total/max hold times; a
  budget (``CRAWLINT_LOCKWITNESS_BUDGET_MS``) turns outliers into
  LKW003 breaches.  All three series surface as ``lockwitness_*``
  metrics via :mod:`utils.metrics` compute-at-read gauges.

Enable with ``CRAWLINT_LOCKWITNESS=1`` (tests/conftest.py installs it
before any package module is imported), ``pytest --lockwitness``, or the
``forbid_lock_cycles`` gate key (loadgen/gate.py).  Findings dump as
JSON (:meth:`LockWitness.dump`, env ``CRAWLINT_LOCKWITNESS_OUT``) and
render through the crawlint Finding pipeline with
``python -m tools.analyze --lock-report <file>``.

Witness internals deliberately use raw ``_thread.allocate_lock()`` plus
a thread-local reentrancy guard: the metrics registry's own locks are
instrumented too, and the witness must never recurse through itself
while recording them.

Selfcheck (used by ``tools/_smoke.py``)::

    python -m distributed_crawler_tpu.utils.lockwitness --selfcheck
"""

from __future__ import annotations

import _thread
import json
import logging
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("dct.lockwitness")

REPORT_SCHEMA_VERSION = 1

# Package root: locks created by files under this directory get witnessed.
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_DIR = os.path.dirname(_PKG_DIR)

_STACK_LIMIT = 12           # frames kept per witness stack
_MAX_FINDINGS = 200         # bound per finding list (blocking/breaches)


def _site_of(frame) -> str:
    """repo-relative ``file.py:line`` for a creation/acquire frame."""
    fn = frame.f_code.co_filename
    try:
        rel = os.path.relpath(fn, _REPO_DIR)
    except ValueError:      # different drive (windows) — keep absolute
        rel = fn
    return f"{rel.replace(os.sep, '/')}:{frame.f_lineno}"


def _in_package(frame) -> bool:
    fn = frame.f_code.co_filename
    return fn.startswith(_PKG_DIR + os.sep) or fn == __file__


def _stack_of(frame) -> List[str]:
    """Formatted witness stack (innermost last), bounded."""
    summary = traceback.extract_stack(frame, limit=_STACK_LIMIT)
    return [ln.rstrip() for ln in traceback.format_list(summary)]


class _Held:
    """One (lock, thread) hold: identity, site, reentry count, frame."""

    __slots__ = ("ident", "site", "count", "t0", "frame")

    def __init__(self, ident: int, site: str, frame) -> None:
        self.ident = ident
        self.site = site
        self.count = 1
        self.t0 = time.monotonic()
        self.frame = frame      # acquire frame, for lazy stack capture


class LockWitness:
    """Global lock-order graph + blocking/hold-time findings."""

    def __init__(self) -> None:
        self._mu = _thread.allocate_lock()   # NEVER an instrumented lock
        self._tl = threading.local()
        self._enabled = False
        self._originals: Dict[str, Any] = {}
        self._budget_s: Optional[float] = None
        self._sites: Dict[str, int] = {}     # creation site -> locks made
        # (held_site, acquired_site) -> witness record
        self._edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._adj: Dict[str, set] = {}       # site -> successor sites
        self._cycles: List[Dict[str, Any]] = []
        self._cycle_keys: set = set()
        self._blocking: List[Dict[str, Any]] = []
        self._breaches: List[Dict[str, Any]] = []
        self._hold: Dict[str, List[float]] = {}  # site -> [n, total, max]
        self._acquisitions = 0

    # -- state -------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def cycle_count(self) -> int:
        return len(self._cycles)

    def blocking_count(self) -> int:
        return len(self._blocking)

    def breach_count(self) -> int:
        return len(self._breaches)

    def _held_list(self) -> List[_Held]:
        held = getattr(self._tl, "held", None)
        if held is None:
            held = self._tl.held = []
        return held

    def _guarded(self) -> bool:
        """True while this thread is already inside witness bookkeeping
        (or bookkeeping is being entered now) — nested acquires by the
        witness itself (e.g. the metrics histogram's own instrumented
        lock) must pass through unrecorded."""
        return getattr(self._tl, "busy", False)

    # -- install / uninstall ----------------------------------------------

    def install(self, budget_s: Optional[float] = None) -> None:
        """Patch the threading constructors + blocking calls.  Idempotent;
        safe to call from conftest, the gate runner, and the selfcheck in
        the same process."""
        if self._enabled:
            if budget_s is not None:
                self._budget_s = budget_s
            return
        if budget_s is not None:
            self._budget_s = budget_s
        elif self._budget_s is None:
            ms = os.environ.get("CRAWLINT_LOCKWITNESS_BUDGET_MS", "")
            try:
                self._budget_s = float(ms) / 1000.0 if ms else None
            except ValueError:
                self._budget_s = None
        self._originals = {
            "Lock": threading.Lock,
            "RLock": threading.RLock,
            "Condition": threading.Condition,
            "time.sleep": time.sleep,
            "Thread.join": threading.Thread.join,
        }
        threading.Lock = self._lock_factory(self._originals["Lock"], "Lock")
        threading.RLock = self._lock_factory(self._originals["RLock"],
                                             "RLock")
        threading.Condition = self._condition_factory(
            self._originals["Condition"])
        time.sleep = self._blocking_wrapper(self._originals["time.sleep"],
                                            "time.sleep")
        threading.Thread.join = self._blocking_method(
            self._originals["Thread.join"], "Thread.join")
        try:
            import queue
            self._originals["Queue.get"] = queue.Queue.get
            queue.Queue.get = self._blocking_method(queue.Queue.get,
                                                    "Queue.get")
        except Exception as e:
            logger.debug("lockwitness: queue.Queue.get not patched: %s", e)
        try:
            import subprocess
            self._originals["Popen.wait"] = subprocess.Popen.wait
            subprocess.Popen.wait = self._blocking_method(
                subprocess.Popen.wait, "Popen.wait")
        except Exception as e:
            logger.debug("lockwitness: Popen.wait not patched: %s", e)
        try:
            import socket
            self._originals["socket.recv"] = socket.socket.recv
            self._originals["socket.accept"] = socket.socket.accept
            socket.socket.recv = self._blocking_method(socket.socket.recv,
                                                       "socket.recv")
            socket.socket.accept = self._blocking_method(
                socket.socket.accept, "socket.accept")
        except Exception as e:
            logger.debug("lockwitness: socket waits not patched: %s", e)
        self._enabled = True
        self._register_metrics()

    def uninstall(self) -> None:
        """Restore every patched callable.  Existing proxies keep working
        (they delegate) but stop recording."""
        if not self._enabled:
            return
        self._enabled = False
        o = self._originals
        threading.Lock = o["Lock"]
        threading.RLock = o["RLock"]
        threading.Condition = o["Condition"]
        time.sleep = o["time.sleep"]
        threading.Thread.join = o["Thread.join"]
        if "Queue.get" in o:
            import queue
            queue.Queue.get = o["Queue.get"]
        if "Popen.wait" in o:
            import subprocess
            subprocess.Popen.wait = o["Popen.wait"]
        if "socket.recv" in o:
            import socket
            socket.socket.recv = o["socket.recv"]
            socket.socket.accept = o["socket.accept"]
        self._originals = {}

    def _register_metrics(self) -> None:
        """Expose counts as lockwitness_* compute-at-read gauges.  Late
        import: metrics' own module-level locks must already exist (they
        are created at metrics import, possibly pre-install, which is
        fine — only locks created AFTER install are witnessed)."""
        try:
            from .metrics import REGISTRY
            REGISTRY.gauge(
                "lockwitness_cycles",
                "lock-order cycles (potential deadlocks) witnessed by the "
                "runtime lock witness").set_fn(self.cycle_count)
            REGISTRY.gauge(
                "lockwitness_blocking_under_lock",
                "blocking calls observed while holding an instrumented "
                "lock").set_fn(self.blocking_count)
            REGISTRY.gauge(
                "lockwitness_hold_budget_breaches",
                "lock holds exceeding CRAWLINT_LOCKWITNESS_BUDGET_MS"
            ).set_fn(self.breach_count)
            REGISTRY.gauge(
                "lockwitness_instrumented_sites",
                "distinct lock creation sites under witness"
            ).set_fn(lambda: len(self._sites))
        except Exception as e:
            # Metrics unavailable: the witness still records.
            logger.debug("lockwitness: metrics gauges not registered: %s",
                         e)

    # -- factories ---------------------------------------------------------

    def _lock_factory(self, ctor, kind: str):
        witness = self

        def factory(*args, **kwargs):
            inner = ctor(*args, **kwargs)
            frame = sys._getframe(1)
            if not witness._enabled or not _in_package(frame):
                return inner
            site = _site_of(frame)
            with witness._mu:
                witness._sites[site] = witness._sites.get(site, 0) + 1
            return _WitnessLock(inner, site, witness)

        factory.__name__ = kind
        return factory

    def _condition_factory(self, ctor):
        witness = self

        def factory(lock=None):
            frame = sys._getframe(1)
            if lock is not None and isinstance(lock, _WitnessLock):
                # Share the wrapped lock's witness identity: `with lock:`
                # and `with cond:` are the same underlying mutex and must
                # be one graph node, not an artificial AB pair.
                inner = ctor(lock._inner)
                return _WitnessCondition(inner, lock._site, witness,
                                         id(lock._inner))
            inner = ctor(lock)
            if not witness._enabled or not _in_package(frame):
                return inner
            site = _site_of(frame)
            with witness._mu:
                witness._sites[site] = witness._sites.get(site, 0) + 1
            return _WitnessCondition(inner, site, witness,
                                     id(getattr(inner, "_lock", inner)))

        factory.__name__ = "Condition"
        return factory

    def _blocking_wrapper(self, fn, label: str):
        witness = self

        def wrapper(*args, **kwargs):
            witness._note_blocking(label)
            return fn(*args, **kwargs)

        wrapper.__name__ = getattr(fn, "__name__", label)
        wrapper.__wrapped__ = fn
        return wrapper

    def _blocking_method(self, fn, label: str):
        # Same shape; kept separate for clarity at the patch sites (bound
        # through the class, `self` arrives in *args).
        return self._blocking_wrapper(fn, label)

    # -- bookkeeping -------------------------------------------------------

    def _on_acquire(self, ident: int, site: str, frame) -> None:
        if not self._enabled or self._guarded():
            return
        self._tl.busy = True
        try:
            held = self._held_list()
            for h in held:
                if h.ident == ident:
                    h.count += 1        # RLock reentry: no new edge
                    return
            if held:
                self._record_edges(held, site, frame)
            entry = _Held(ident, site, frame)
            # Unlocked: a GIL race can drop a count, which is fine for a
            # diagnostic — taking the global mutex HERE would serialize
            # every lock acquisition in the process through one lock and
            # measurably perturb the SLO-gated scenarios the witness is
            # meant to observe.
            self._acquisitions += 1
            held.append(entry)
        finally:
            self._tl.busy = False

    def _on_release(self, ident: int) -> None:
        if self._guarded():
            return
        self._tl.busy = True
        try:
            held = self._held_list()
            for i in range(len(held) - 1, -1, -1):
                h = held[i]
                if h.ident != ident:
                    continue
                h.count -= 1
                if h.count > 0:
                    return
                held.pop(i)
                h.frame = None
                dur = time.monotonic() - h.t0
                # Aggregate updates are unlocked on purpose (see
                # _on_acquire): GIL races can lose a sample, never
                # corrupt the [count, total, max] shape.  Only the
                # first-seen-site insert and the (rare) breach append
                # take the mutex.
                agg = self._hold.get(h.site)
                if agg is None:
                    with self._mu:
                        agg = self._hold.setdefault(h.site,
                                                    [0, 0.0, 0.0])
                agg[0] += 1
                agg[1] += dur
                if dur > agg[2]:
                    agg[2] = dur
                if self._budget_s is not None and dur > self._budget_s:
                    with self._mu:
                        if len(self._breaches) < _MAX_FINDINGS:
                            self._breaches.append({
                                "site": h.site,
                                "held_s": round(dur, 6),
                                "budget_s": self._budget_s,
                                "thread":
                                    threading.current_thread().name,
                            })
                return
        finally:
            self._tl.busy = False

    def _record_edges(self, held: List[_Held], site: str, frame) -> None:
        """Add held→acquired edges; a new edge closing a directed cycle
        is a potential deadlock.  Caller already holds the reentrancy
        guard; the graph mutates under the raw mutex."""
        thread = threading.current_thread().name
        for h in held:
            if h.site == site:
                # Same creation site (reentry is filtered earlier, so
                # this is a different instance — e.g. two shard locks
                # from one constructor line).  Ordering within one
                # site is invisible to a site-keyed graph; skip
                # rather than fabricate a self-cycle.
                continue
            key = (h.site, site)
            # Fast path unlocked: after warm-up every nested acquire is
            # a known edge, and a GIL-raced count bump only loses a
            # diagnostic tick.  Graph MUTATION stays under the mutex.
            rec = self._edges.get(key)
            if rec is not None:
                rec["count"] += 1
                continue
            with self._mu:
                rec = self._edges.get(key)
                if rec is not None:
                    rec["count"] += 1
                    continue
                self._edges[key] = {
                    "held_site": h.site,
                    "acquire_site": site,
                    "thread": thread,
                    "count": 1,
                    "held_stack": _stack_of(h.frame) if h.frame else [],
                    "acquire_stack": _stack_of(frame),
                }
                self._adj.setdefault(h.site, set()).add(site)
                self._check_cycle(h.site, site)

    def _check_cycle(self, a: str, b: str) -> None:
        """After adding a→b: a path b→…→a in the existing graph closes a
        cycle.  BFS under self._mu (edge count is small)."""
        if a == b:
            return
        prev: Dict[str, str] = {b: b}
        queue = [b]
        while queue:
            cur = queue.pop(0)
            if cur == a:
                break
            for nxt in self._adj.get(cur, ()):
                if nxt not in prev:
                    prev[nxt] = cur
                    queue.append(nxt)
        if a not in prev:
            return
        # Reconstruct b → … → a, then close with the new edge a → b.
        path = [a]
        while path[-1] != b:
            path.append(prev[path[-1]])
        path.reverse()                       # [b, …, a]
        sites = [a] + path                   # a → b → … → a
        key = frozenset(sites)
        if key in self._cycle_keys:
            return
        self._cycle_keys.add(key)
        edges = []
        for x, y in zip(sites, sites[1:]):
            rec = self._edges.get((x, y))
            if rec:
                edges.append(dict(rec))
        self._cycles.append({
            "sites": sites,
            "threads": sorted({e["thread"] for e in edges}),
            "edges": edges,
        })

    def _note_blocking(self, label: str) -> None:
        if not self._enabled or self._guarded():
            return
        held = getattr(self._tl, "held", None)
        if not held:
            return
        self._tl.busy = True
        try:
            try:
                # 0=_note_blocking, 1=wrapper/wait, 2=the blocking caller.
                frame = sys._getframe(2)
            except ValueError:
                frame = sys._getframe(1)
            with self._mu:
                if len(self._blocking) >= _MAX_FINDINGS:
                    return
                self._blocking.append({
                    "call": label,
                    "held_sites": [h.site for h in held],
                    "held_s": round(time.monotonic() - held[0].t0, 6),
                    "thread": threading.current_thread().name,
                    "stack": _stack_of(frame),
                })
        finally:
            self._tl.busy = False

    # -- reporting ---------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        """JSON-ready snapshot (`tools/analyze --lock-report` input)."""
        with self._mu:
            hold = {
                site: {"count": int(agg[0]),
                       "total_s": round(agg[1], 6),
                       "max_s": round(agg[2], 6)}
                for site, agg in sorted(self._hold.items())
            }
            return {
                "schema_version": REPORT_SCHEMA_VERSION,
                "enabled": self._enabled,
                "budget_s": self._budget_s,
                "instrumented_sites": len(self._sites),
                "acquisitions": self._acquisitions,
                "edge_count": len(self._edges),
                "cycle_count": len(self._cycles),
                "blocking_count": len(self._blocking),
                "breach_count": len(self._breaches),
                "cycles": [dict(c) for c in self._cycles],
                "blocking": [dict(b) for b in self._blocking],
                "breaches": [dict(b) for b in self._breaches],
                "hold": hold,
            }

    def dump(self, path: str) -> None:
        """Atomic JSON dump (tmp + fsync + rename — the ATM discipline)."""
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.report(), f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def summary_line(self) -> str:
        return (f"lockwitness: {len(self._sites)} sites, "
                f"{self._acquisitions} acquisitions, "
                f"{len(self._edges)} edges, "
                f"{len(self._cycles)} cycle(s), "
                f"{len(self._blocking)} blocking-under-lock, "
                f"{len(self._breaches)} budget breach(es)")


class _WitnessLock:
    """Proxy around a real Lock/RLock: records acquire/release into the
    witness, delegates everything else."""

    __slots__ = ("_inner", "_site", "_witness")

    def __init__(self, inner, site: str, witness: LockWitness) -> None:
        self._inner = inner
        self._site = site
        self._witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._witness._on_acquire(id(self._inner), self._site,
                                      sys._getframe(1))
        return ok

    def release(self):
        self._witness._on_release(id(self._inner))
        return self._inner.release()

    def __enter__(self):
        self._inner.acquire()
        self._witness._on_acquire(id(self._inner), self._site,
                                  sys._getframe(1))
        return self

    def __exit__(self, exc_type, exc, tb):
        self._witness._on_release(id(self._inner))
        self._inner.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __repr__(self):
        return f"<WitnessLock {self._site} {self._inner!r}>"


class _WitnessCondition:
    """Proxy around a real Condition sharing the witness identity of its
    underlying mutex.  ``wait`` keeps the held marker (lock order is
    about program structure: code after wait still runs under the lock)
    and records blocking when OTHER witnessed locks are held."""

    __slots__ = ("_cond", "_site", "_witness", "_ident")

    def __init__(self, cond, site: str, witness: LockWitness,
                 ident: int) -> None:
        self._cond = cond
        self._site = site
        self._witness = witness
        self._ident = ident

    def acquire(self, *args, **kwargs):
        ok = self._cond.acquire(*args, **kwargs)
        if ok:
            self._witness._on_acquire(self._ident, self._site,
                                      sys._getframe(1))
        return ok

    def release(self):
        self._witness._on_release(self._ident)
        return self._cond.release()

    def __enter__(self):
        self._cond.acquire()
        self._witness._on_acquire(self._ident, self._site,
                                  sys._getframe(1))
        return self

    def __exit__(self, exc_type, exc, tb):
        self._witness._on_release(self._ident)
        return self._cond.__exit__(exc_type, exc, tb)

    def wait(self, timeout: Optional[float] = None):
        w = self._witness
        held = getattr(w._tl, "held", None) or []
        if w._enabled and any(h.ident != self._ident for h in held):
            w._note_blocking(f"Condition.wait[{self._site}]")
        return self._cond.wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        w = self._witness
        held = getattr(w._tl, "held", None) or []
        if w._enabled and any(h.ident != self._ident for h in held):
            w._note_blocking(f"Condition.wait_for[{self._site}]")
        return self._cond.wait_for(predicate, timeout)

    def notify(self, n: int = 1):
        return self._cond.notify(n)

    def notify_all(self):
        return self._cond.notify_all()

    def __getattr__(self, name):
        return getattr(self._cond, name)

    def __repr__(self):
        return f"<WitnessCondition {self._site} {self._cond!r}>"


#: Process-wide witness.  conftest, the gate runner, and the selfcheck
#: all install into the same instance — one graph per process.
WITNESS = LockWitness()


def install(budget_s: Optional[float] = None) -> None:
    WITNESS.install(budget_s=budget_s)


def uninstall() -> None:
    WITNESS.uninstall()


def enabled() -> bool:
    return WITNESS.enabled


def env_enabled() -> bool:
    return os.environ.get("CRAWLINT_LOCKWITNESS", "") == "1"


# -- witnessed-lock fabrication seams ---------------------------------------
# The factories only wrap locks CREATED inside the package tree; test
# code and `python -c` probes live outside it, so these helpers exist to
# mint witnessed locks on their behalf (the selfcheck uses them too).
# Pass a distinct ``label`` per lock: the graph is keyed by creation
# site, and every call through one helper shares this file's line, so
# unlabeled fabricated locks would collapse into a single node (and
# same-site edges are deliberately skipped).

def _relabel(obj, label: Optional[str]):
    if label is None \
            or not isinstance(obj, (_WitnessLock, _WitnessCondition)):
        return obj
    w = obj._witness
    with w._mu:
        old = obj._site
        n = w._sites.get(old, 0) - 1
        if n > 0:
            w._sites[old] = n
        else:
            w._sites.pop(old, None)
        w._sites[label] = w._sites.get(label, 0) + 1
    obj._site = label
    return obj


def make_lock(label: Optional[str] = None):
    return _relabel(threading.Lock(), label)


def make_rlock(label: Optional[str] = None):
    return _relabel(threading.RLock(), label)


def make_condition(lock=None, label: Optional[str] = None):
    return _relabel(threading.Condition(lock), label)


# ---------------------------------------------------------------------------
# selfcheck
# ---------------------------------------------------------------------------

def _selfcheck() -> int:
    """Prove the detector fires: a two-thread AB/BA inversion must yield
    exactly one cycle with both witness stacks, a sleep under lock must
    yield a blocking finding, and a consistently-ordered nested pair must
    add no cycle.  Exit 0 on pass."""
    install()
    # make_lock creations happen inside the package (this file), so the
    # factories wrap them; labels keep the four locks distinct graph
    # nodes (one shared helper line would otherwise be one site).
    lock_a = make_lock("selfcheck:a")
    lock_b = make_lock("selfcheck:b")
    lock_c = make_lock("selfcheck:c")
    lock_d = make_lock("selfcheck:d")
    assert isinstance(lock_a, _WitnessLock), \
        "factory did not wrap a package-created lock"

    def ordered(first, second):
        with first:
            with second:
                pass

    t1 = threading.Thread(target=ordered, args=(lock_a, lock_b),
                          name="witness-ab")
    t1.start()
    t1.join()
    t2 = threading.Thread(target=ordered, args=(lock_b, lock_a),
                          name="witness-ba")
    t2.start()
    t2.join()
    rep = WITNESS.report()
    ok = True
    if rep["cycle_count"] != 1:
        print(f"selfcheck FAILED: expected 1 cycle, got "
              f"{rep['cycle_count']}", file=sys.stderr)
        ok = False
    else:
        cyc = rep["cycles"][0]
        if not all(e["held_stack"] and e["acquire_stack"]
                   for e in cyc["edges"]):
            print("selfcheck FAILED: cycle edges missing witness stacks",
                  file=sys.stderr)
            ok = False
    before_blocking = WITNESS.blocking_count()
    with lock_c:
        time.sleep(0.01)
    if WITNESS.blocking_count() != before_blocking + 1:
        print("selfcheck FAILED: sleep-under-lock not recorded",
              file=sys.stderr)
        ok = False
    before_cycles = WITNESS.cycle_count()
    for _ in range(2):
        ordered(lock_c, lock_d)     # consistent order: never a cycle
    if WITNESS.cycle_count() != before_cycles:
        print("selfcheck FAILED: consistent nesting produced a cycle",
              file=sys.stderr)
        ok = False
    out = os.environ.get("CRAWLINT_LOCKWITNESS_OUT", "")
    if out:
        WITNESS.dump(out)
    print(WITNESS.summary_line() + (" [selfcheck OK]" if ok else ""))
    return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--selfcheck" in argv:
        return _selfcheck()
    print(__doc__)
    return 0


if __name__ == "__main__":
    sys.exit(main())
