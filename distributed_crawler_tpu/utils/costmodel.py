# crawlint: disable-file=TRC — every jax touch in this module is a
# HOST-SIDE compile-time hook by design: it lowers/inspects programs
# (`Lowered.cost_analysis()`), it never runs inside a traced region.
"""Hardware-efficiency cost accounting: what a batch costs vs what the
chip could do.

The north star says "as fast as the hardware allows", but until now the
only process that knew a batch's FLOPs was `bench.py` — and only while a
bench was running.  This module makes cost a first-class serving signal:

- :func:`encoder_forward_flops` — the analytic forward-FLOP count for one
  embed+classify batch, promoted out of `bench.py` so the bench and every
  running worker share ONE formula.
- :class:`CostModel` — per-(bucket, path) compiled cost captured at the
  engine's first dispatch of each program (`inference/engine.py`
  `_step`/`_packed_step` call sites): XLA's own numbers via
  ``lowered.cost_analysis()`` (tracing-cheap — no second XLA compile;
  ``lowered.compile().cost_analysis()`` is tried only as a fallback,
  where jax's executable caches make it near-free because the dispatch
  that triggered the capture just paid the compile) with the analytic
  count as the final fallback.  Exposed as ``tpu_engine_bucket_flops``
  gauges and the ``/costs`` endpoint (`utils/metrics.py`).
- :func:`peak_flops` — the per-device dense-bf16 peak table (promoted
  from `bench.py`), with a conservative CPU estimate so the MFU pipeline
  stays exercised end to end in CPU tests and deployments.
- :class:`EfficiencyMeter` — rolling-window goodput/MFU accounting over
  dispatched batches: real vs pad tokens, achieved FLOP/s vs peak,
  exported as ``tpu_engine_mfu`` / ``tpu_engine_goodput_tokens_per_s`` /
  ``tpu_engine_padding_density`` gauges and carried in telemetry
  heartbeats so the orchestrator's `/cluster` view shows per-worker
  efficiency.

Everything here is guarded: a backend without cost analysis, a missing
jax, or a wedged chip degrades to analytic numbers — never to a raise in
the serving path.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .metrics import REGISTRY, MetricsRegistry

logger = logging.getLogger("dct.costmodel")

# Dense bf16 peak per chip, by jax device_kind substring — ONE table for
# bench.py and the serving meters (it used to live in bench.py where no
# running worker could see it).
PEAK_BF16_FLOPS: List[Tuple[str, float]] = [
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
    ("v6 lite", 918e12), ("v6e", 918e12), ("v4", 275e12), ("v3", 123e12),
]

# Conservative per-host CPU peak (a few AVX cores' worth of f32 FMA).
# Deliberately low-precision: its job is to keep the MFU path exercised
# (and roughly comparable run-to-run) on CPU backends, clearly labelled
# ``peak_source: "cpu_estimate"`` — never to claim a real utilisation.
CPU_PEAK_FLOPS_ESTIMATE = 5e11


def encoder_forward_flops(cfg, batch: int, seq: int) -> float:
    """Analytic forward FLOPs for one embed+classify batch.

    Per token per layer: QKV+out projections (8·d²), attention score+value
    matmuls (4·seq·d), MLP up+down (4·d·ff); multiply-accumulate counted as
    2 FLOPs.  Embedding lookup and the d×n_labels head are negligible.
    """
    d, ff, L = cfg.hidden, cfg.mlp_dim, cfg.n_layers
    per_token = L * (8 * d * d + 4 * seq * d + 4 * d * ff)
    return float(batch * seq * per_token)


def whisper_forward_flops(cfg, batch: int, decode_len: int) -> float:
    """Analytic forward FLOPs for one greedy ASR batch — the Whisper row
    of the cost table, so `/costs` and the MFU/goodput gauges stay honest
    for ASR programs whose backend has no ``cost_analysis()``.

    Encoder (per 30 s window): the two stem convs (3-tap, stride 1 then
    2) plus ``n_audio_layer`` transformer layers over ``n_audio_ctx``
    positions — QKV+out projections (8·d²), score+value matmuls
    (4·ctx·d), MLP up+down (8·d²  since ff = 4d) per position.  Decoder:
    ``decode_len - 1`` single-token steps (the SOT token is free), each
    paying self-attention projections + a growing-cache score/value read
    (bounded by n_text_ctx; we charge the full cache — a <2% overcount
    that keeps the formula shape-static like the compiled program), the
    cross-attention read against ``n_audio_ctx`` cached K/V, the MLP,
    and the tied-embedding logits GEMM (d·n_vocab).  Multiply-accumulate
    counted as 2 FLOPs throughout, matching `encoder_forward_flops`.

    ``cfg`` is a `models.whisper.WhisperConfig`; this module must stay
    importable without jax, so the config is duck-typed.
    """
    da, dt = cfg.n_audio_state, cfg.n_text_state
    ctx_a, ctx_t = cfg.n_audio_ctx, cfg.n_text_ctx
    mel_frames = ctx_a * 2
    # Stem convs: [frames, n_mels] -> [frames, d] then stride-2 [ctx, d].
    conv = 2 * (mel_frames * 3 * cfg.n_mels * da
                + ctx_a * 3 * da * da)
    enc_layer = ctx_a * (8 * da * da + 4 * ctx_a * da + 16 * da * da)
    encoder = conv + cfg.n_audio_layer * enc_layer
    # Cross K/V projection, once per utterance per layer.
    cross_kv = cfg.n_text_layer * 2 * (2 * ctx_a * dt * dt)
    steps = max(1, int(decode_len) - 1)
    dec_step_layer = (8 * dt * dt            # self q/k/v/out projections
                      + 4 * ctx_t * dt       # self score+value vs cache
                      + 4 * dt * dt          # cross q + out projections
                      + 4 * ctx_a * dt       # cross score+value vs audio
                      + 16 * dt * dt)        # MLP (ff = 4d)
    logits = 2 * dt * cfg.n_vocab
    decoder = steps * (cfg.n_text_layer * dec_step_layer + logits)
    return float(batch) * (encoder + cross_kv + decoder)


def kmeans_step_flops(k: int, dim: int, rows: int) -> float:
    """Analytic FLOPs for one online mini-batch k-means step — the
    ``path="cluster"`` row of the cost table (`cluster/engine.py`), so
    `/costs` MFU/goodput stay honest for the clustering programs too.

    Assignment: one ``[rows, dim] x [dim, k]`` matmul (2·R·D·K) plus the
    ``||c||²`` bias row (2·K·D).  Update: the one-hot segment-sum matmul
    ``[k, rows] x [rows, dim]`` (2·R·D·K) plus the running-mean fold and
    spherical renormalization over the centroid table (~6·K·D).
    Normalizing the incoming rows costs ~3·R·D.  Multiply-accumulate
    counted as 2 FLOPs, matching `encoder_forward_flops`.
    """
    r, d, kk = float(rows), float(dim), float(k)
    return 4.0 * r * d * kk + 3.0 * r * d + 8.0 * kk * d


def peak_flops(device_kind: str = "", platform: str = "",
               n_devices: int = 1) -> Tuple[float, str]:
    """(aggregate peak FLOP/s over ``n_devices``, source tag).

    TPU kinds resolve through :data:`PEAK_BF16_FLOPS`; CPU gets the
    conservative estimate; anything else returns (0, "unknown") so MFU is
    omitted rather than invented.

    The aggregate ALWAYS scales with ``n_devices`` — CPU included — so an
    engine serving over an N-chip mesh divides its achieved FLOP/s by N×
    the single-chip peak.  Without this, MFU silently reads N× too high
    the moment a mesh appears (same work, same peak denominator).  Virtual
    CPU devices share host cores, so the scaled CPU figure is even more
    conservative than the single-device one — acceptable, because its job
    is keeping the MFU pipeline exercised and mesh-consistent, never
    claiming a real utilisation (``peak_source: "cpu_estimate"``).
    """
    kind = (device_kind or "").lower()
    n = max(1, int(n_devices))
    if platform == "tpu":
        for sub, peak in PEAK_BF16_FLOPS:
            if sub in kind:
                return peak * n, f"tpu:{sub}"
        return 0.0, "unknown"
    if platform == "cpu":
        return CPU_PEAK_FLOPS_ESTIMATE * n, "cpu_estimate"
    return 0.0, "unknown"


def default_peak_flops(n_devices: Optional[int] = None) -> Tuple[float, str]:
    """Peak for the ALREADY-IMPORTED jax's default backend; (0, "unknown")
    when jax isn't loaded — same never-import rule as
    `utils/telemetry.py:device_memory_stats` (a crawl worker's heartbeat
    must not pay the jax import).

    ``n_devices`` is the count the CALLER actually dispatches over (the
    engine's mesh size, 1 for a single-device engine); ``None`` keeps the
    historical all-visible-devices behavior for callers with no mesh
    notion.  An engine on one chip of an 8-chip host must NOT divide by
    8× peak (MFU would read 1/8 too low), and an 8-chip mesh must not
    divide by one chip's (N× too high)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return 0.0, "unknown"
    try:
        devices = jax.devices()
        n = len(devices) if n_devices is None else max(1, int(n_devices))
        return peak_flops(devices[0].device_kind, jax.default_backend(), n)
    except Exception as e:  # a wedged backend must not kill telemetry
        logger.debug("peak-FLOPs resolution failed: %s", e)
        return 0.0, "unknown"


def _analysis_dict(analysis: Any) -> Optional[Dict[str, Any]]:
    """`cost_analysis()` has returned both a dict and a 1-element list of
    dicts across jax versions; normalize to the dict (or None)."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    return analysis if isinstance(analysis, dict) else None


class CostModel:
    """Per-(bucket, path) compiled cost, captured once at first dispatch.

    ``capture()`` is called from the engine's dispatch loop right after
    the program's first call (which paid the XLA compile); it is
    idempotent, thread-safe, and never raises into serving.
    """

    def __init__(self, registry: MetricsRegistry = REGISTRY):
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.m_bucket_flops = registry.gauge(
            "tpu_engine_bucket_flops",
            "forward FLOPs of one compiled (bucket, path) batch program "
            "(XLA cost_analysis when available, analytic fallback)")

    def has(self, bucket: int, path: str) -> bool:
        with self._lock:
            return (str(bucket), path) in self._entries

    def capture(self, bucket: int, path: str, lower_fn,
                fallback_flops: float, batch: int = 0,
                seq: int = 0) -> Dict[str, Any]:
        """Record the (bucket, path) program's cost.

        ``lower_fn`` is a zero-arg callable returning the program's
        ``jax.stages.Lowered`` (e.g. ``lambda: fn.lower(params, *args)``
        — tracing only, the compile was already paid by the dispatch that
        triggered this capture).  Any failure anywhere degrades to the
        analytic ``fallback_flops``.
        """
        key = (str(bucket), path)
        with self._lock:
            got = self._entries.get(key)
            if got is not None:
                return got
        entry: Dict[str, Any] = {
            "bucket": int(bucket), "path": path,
            "batch": int(batch), "seq": int(seq or bucket),
            "flops": float(fallback_flops), "bytes_accessed": None,
            "source": "analytic", "captured_at": time.time(),
        }
        try:
            lowered = lower_fn()
            analysis = _analysis_dict(lowered.cost_analysis())
            if analysis is None:
                # Unoptimized-HLO analysis unavailable on this backend;
                # the executable variant hits jax's compile caches (the
                # live program just compiled) so this is near-free.
                analysis = _analysis_dict(lowered.compile().cost_analysis())
            if analysis is not None:
                flops = analysis.get("flops")
                if isinstance(flops, (int, float)) and flops > 0:
                    entry["flops"] = float(flops)
                    entry["source"] = "xla"
                ba = analysis.get("bytes accessed")
                if isinstance(ba, (int, float)) and ba > 0:
                    entry["bytes_accessed"] = float(ba)
        except Exception as e:
            logger.debug("cost_analysis unavailable for bucket=%s path=%s: "
                         "%s (using analytic count)", bucket, path, e)
        with self._lock:
            entry = self._entries.setdefault(key, entry)
        self.m_bucket_flops.labels(bucket=str(bucket),
                                   path=path).set(entry["flops"])
        return entry

    def flops_for(self, bucket: int, path: str,
                  default: float = 0.0) -> float:
        with self._lock:
            entry = self._entries.get((str(bucket), path))
        return float(entry["flops"]) if entry else default

    def snapshot(self) -> List[Dict[str, Any]]:
        """Entries sorted by (path, bucket) — the /costs body's core."""
        with self._lock:
            entries = list(self._entries.values())
        return sorted((dict(e) for e in entries),
                      key=lambda e: (e["path"], e["bucket"]))


class TenantLedger:
    """Per-tenant spend attribution (ISSUE 17): which workload consumed
    which chip-seconds/FLOPs/tokens, plus a rolling queue-wait read per
    tenant.

    The ledger keeps its OWN cumulative rows (registry counters with the
    same name are shared across every meter in a process, so exposition
    counters alone cannot answer "this engine's split").  ``totals`` are
    accumulated independently of the per-tenant rows under the same
    lock, so the conservation property the gate asserts — per-tenant
    rows sum to the fleet total — is checkable against this snapshot.

    Charging is proportional: one device batch's duration/FLOPs/tokens
    split by the caller-supplied weights (the worker weighs by real
    token counts per tenant in the coalesced group).  Warmup and other
    unweighted dispatches charge nothing — they predate any tenant, so
    they must not show up as "unattributed spend"."""

    _QUEUE_WINDOW = 512  # rolling queue-wait samples kept per tenant

    def __init__(self, registry: MetricsRegistry = REGISTRY):
        self._lock = threading.Lock()
        self._rows: Dict[str, Dict[str, float]] = {}
        self._totals = {"chip_seconds": 0.0, "flops": 0.0,
                        "real_tokens": 0.0, "batches": 0.0}
        self._queue_waits: Dict[str, "deque[float]"] = {}
        self.m_chip_seconds = registry.counter(
            "tenant_chip_seconds_total",
            "cumulative device-batch seconds attributed to one tenant "
            "(proportional split of each dispatch by real-token weight)")
        self.m_flops = registry.counter(
            "tenant_flops_total",
            "cumulative forward FLOPs attributed to one tenant")
        self.m_tokens = registry.counter(
            "tenant_real_tokens_total",
            "cumulative REAL (non-pad) tokens attributed to one tenant")
        self.m_queue_wait = registry.gauge(
            "tenant_queue_wait_p95_seconds",
            "p95 queue wait over the last samples observed for one tenant")

    def charge(self, weights: Dict[str, float], duration_s: float,
               flops: float, real_tokens: float) -> None:
        """Attribute one dispatch across ``weights`` proportionally."""
        total_w = sum(w for w in weights.values() if w > 0)
        if total_w <= 0:
            return
        with self._lock:
            self._totals["chip_seconds"] += float(duration_s)
            self._totals["flops"] += float(flops)
            self._totals["real_tokens"] += float(real_tokens)
            self._totals["batches"] += 1.0
            for tenant, w in weights.items():
                if w <= 0:
                    continue
                frac = w / total_w
                row = self._rows.setdefault(tenant, {
                    "chip_seconds": 0.0, "flops": 0.0,
                    "real_tokens": 0.0, "batches": 0.0})
                row["chip_seconds"] += duration_s * frac
                row["flops"] += flops * frac
                row["real_tokens"] += real_tokens * frac
                row["batches"] += frac
                self.m_chip_seconds.labels(tenant=tenant).inc(
                    duration_s * frac)
                self.m_flops.labels(tenant=tenant).inc(flops * frac)
                self.m_tokens.labels(tenant=tenant).inc(real_tokens * frac)

    def observe_queue_wait(self, tenant: str, seconds: float) -> None:
        """Feed one batch's queue wait into the tenant's rolling window."""
        with self._lock:
            dq = self._queue_waits.setdefault(
                tenant, deque(maxlen=self._QUEUE_WINDOW))
            dq.append(float(seconds))
            samples = sorted(dq)
        # Nearest-rank p95, same convention as utils/slo.py.
        p95 = samples[max(0, -(-len(samples) * 95 // 100) - 1)]
        self.m_queue_wait.labels(tenant=tenant).set(round(p95, 6))

    def snapshot(self) -> Dict[str, Any]:
        """{"rows": [...], "totals": {...}} — the /costs "tenants" map.
        Row ``share`` is the tenant's chip-second fraction of the total
        (the gate's ``max_unattributed_share`` reads the DEFAULT_TENANT
        row's share)."""
        with self._lock:
            totals = dict(self._totals)
            rows = {t: dict(r) for t, r in self._rows.items()}
            waits = {t: sorted(dq) for t, dq in self._queue_waits.items()
                     if dq}
        out_rows = []
        denom = totals["chip_seconds"]
        for tenant in sorted(rows):
            row = rows[tenant]
            entry: Dict[str, Any] = {
                "tenant": tenant,
                "chip_seconds": round(row["chip_seconds"], 6),
                "flops": round(row["flops"], 1),
                "real_tokens": round(row["real_tokens"], 1),
                "batches": round(row["batches"], 4),
                "share": round(row["chip_seconds"] / denom, 6)
                if denom > 0 else 0.0,
            }
            samples = waits.get(tenant)
            if samples:
                entry["queue_wait_p95_s"] = round(
                    samples[max(0, -(-len(samples) * 95 // 100) - 1)], 6)
                entry["queue_wait_samples"] = len(samples)
            out_rows.append(entry)
        # Tenants that only ever waited (no spend yet) still get a row.
        for tenant in sorted(set(waits) - set(rows)):
            samples = waits[tenant]
            out_rows.append({
                "tenant": tenant, "chip_seconds": 0.0, "flops": 0.0,
                "real_tokens": 0.0, "batches": 0.0, "share": 0.0,
                "queue_wait_p95_s": round(
                    samples[max(0, -(-len(samples) * 95 // 100) - 1)], 6),
                "queue_wait_samples": len(samples),
            })
        return {
            "rows": out_rows,
            "totals": {
                "chip_seconds": round(totals["chip_seconds"], 6),
                "flops": round(totals["flops"], 1),
                "real_tokens": round(totals["real_tokens"], 1),
                "batches": round(totals["batches"], 4),
            },
        }


class EfficiencyMeter:
    """Rolling-window goodput/MFU over dispatched batches.

    One record per device batch: wall time, dispatch→host duration, the
    program's FLOPs, and the real-vs-slot token split.  The window is
    time-bounded (``window_s``) so the gauges answer "how efficient is
    serving NOW", not "since process start".

    MFU here is *achieved FLOP/s over the wall window* vs peak — it
    includes idle gaps between batches, which is the serving-utilisation
    number an operator wants (a chip that computes at 60% MFU for 1 s
    out of every 10 is a 6% chip).  ``mfu_busy`` (over summed batch
    durations only) is also reported for kernel-efficiency reads.

    Mesh-aware: ``n_devices`` is how many chips one recorded dispatch
    covers (the engine's mesh size; 1 single-device).  Peak resolves as
    the N-chip aggregate — same achieved FLOPs over N× the denominator —
    and ``per_device_real_tokens`` (one real-token count per chip's data
    shard, from the host-side mask before device_put) feeds a per-chip
    goodput split: a feed whose padded rows starve the high shards shows
    those chips' goodput collapsing while the aggregate still looks
    healthy.  Under SPMD every chip runs the identical program, so
    per-chip MFU equals the aggregate MFU; goodput is where per-chip
    truth lives.
    """

    def __init__(self, registry: MetricsRegistry = REGISTRY,
                 window_s: float = 60.0, max_records: int = 1024,
                 peak: Optional[float] = None, peak_source: str = "",
                 n_devices: int = 1,
                 device_labels: Optional[List[str]] = None,
                 path: str = ""):
        self.window_s = window_s
        self._records: "deque[Tuple[float, float, float, int, int, Any]]" \
            = deque(maxlen=max_records)
        self._ever_recorded = False
        self._lock = threading.Lock()
        # Per-tenant attribution (ISSUE 17): the worker sets the pending
        # tenant weights before handing the engine a group; every record()
        # while weights are in force charges the ledger proportionally.
        # No weights (warmup, organic unlabeled runs) → nothing charged.
        self.tenants = TenantLedger(registry)
        self._tenant_weights: Dict[str, float] = {}
        # Peak injected for tests; resolved lazily from the live backend
        # otherwise (the engine imports jax long before the first batch).
        self._peak = peak
        self._peak_source = peak_source
        self._n_devices = max(1, int(n_devices))
        self.device_labels = list(device_labels) if device_labels else [
            str(i) for i in range(self._n_devices)]
        self.m_mfu = registry.gauge(
            "tpu_engine_mfu",
            "rolling-window achieved FLOP/s over the MESH-AGGREGATE peak "
            "(n_devices x one chip; wall-clock window incl. idle; 0 when "
            "peak is unknown)")
        self.m_goodput = registry.gauge(
            "tpu_engine_goodput_tokens_per_s",
            "rolling-window REAL (non-pad) tokens per second")
        self.m_density = registry.gauge(
            "tpu_engine_padding_density",
            "rolling-window real tokens / dispatched slot tokens")
        self.m_chip_goodput = registry.gauge(
            "tpu_engine_per_chip_goodput_tokens_per_s",
            "rolling-window REAL tokens/s attributed to one chip's data "
            "shard (uniform split when per-shard masks weren't recorded)")
        if path:
            # A second engine kind in the same process (the cluster
            # engine next to the text engine in one gate registry) must
            # not clobber the default meter's gauges: a ``path`` scopes
            # this meter's mfu/goodput/density series to labeled
            # children.  The per-chip gauge stays shared (its device
            # label already splits series, and labels() on a labeled
            # child would raise).
            self.m_mfu = self.m_mfu.labels(path=path)
            self.m_goodput = self.m_goodput.labels(path=path)
            self.m_density = self.m_density.labels(path=path)

    def _resolve_peak(self) -> Tuple[float, str]:
        if self._peak is None:
            self._peak, self._peak_source = \
                default_peak_flops(self._n_devices)
        return self._peak, self._peak_source

    def set_tenants(self, weights: Dict[str, float]) -> None:
        """Declare which tenants (by positive weight, e.g. real-token
        counts) the NEXT recorded dispatches belong to.  Weights persist
        until the next call, so one coalesced group's multiple device
        batches all charge the same split."""
        with self._lock:
            self._tenant_weights = {
                t: float(w) for t, w in (weights or {}).items() if w > 0}

    def record(self, duration_s: float, flops: float,
               real_tokens: int, slot_tokens: int,
               per_device_real_tokens: Optional[List[int]] = None) -> None:
        """Account one device batch; updates the gauges.

        ``per_device_real_tokens`` — real (non-pad) tokens per chip's data
        shard, length ``n_devices`` — lets the per-chip goodput split be
        exact; omitted, the batch's real tokens attribute uniformly."""
        now = time.monotonic()
        per_dev = None
        if per_device_real_tokens is not None \
                and len(per_device_real_tokens) == self._n_devices:
            per_dev = tuple(int(v) for v in per_device_real_tokens)
        with self._lock:
            self._ever_recorded = True
            self._records.append((now, float(duration_s), float(flops),
                                  int(real_tokens), int(slot_tokens),
                                  per_dev))
            self._prune(now)
            weights = dict(self._tenant_weights)
        if weights:
            self.tenants.charge(weights, float(duration_s), float(flops),
                                float(real_tokens))
        self.snapshot()  # refreshes the gauges as a side effect

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._records and self._records[0][0] < cutoff:
            self._records.popleft()

    def _window_totals(self) -> Tuple[int, float, float, float, int, int,
                                      List[float]]:
        """(batches, span_s, busy_s, flops, real, slot, per_device_real)
        under the lock."""
        now = time.monotonic()
        with self._lock:
            self._prune(now)
            records = list(self._records)
        if not records:
            return 0, 0.0, 0.0, 0.0, 0, 0, [0.0] * self._n_devices
        busy = sum(r[1] for r in records)
        flops = sum(r[2] for r in records)
        real = sum(r[3] for r in records)
        slot = sum(r[4] for r in records)
        per_dev = [0.0] * self._n_devices
        for r in records:
            if r[5] is not None:
                for i, v in enumerate(r[5]):
                    per_dev[i] += v
            else:  # no shard detail: uniform attribution
                share = r[3] / self._n_devices
                for i in range(self._n_devices):
                    per_dev[i] += share
        # Window span: oldest dispatch start to now, floored by busy time
        # (a single just-landed batch must not divide by ~0 wall).
        span = max(now - (records[0][0] - records[0][1]), busy, 1e-9)
        return len(records), span, busy, flops, real, slot, per_dev

    def snapshot(self) -> Dict[str, Any]:
        """The telemetry-heartbeat / /costs ``efficiency`` map, refreshing
        the gauges as a side effect (heartbeats call this every beat, so
        the gauges DECAY to 0 when the batch stream stops instead of
        freezing at the last busy window's value).  {} until the first
        batch ever lands, so never-fed workers don't report fantasy 0s —
        but a worker that went idle genuinely IS at MFU 0."""
        n, span, busy, flops, real, slot, per_dev = self._window_totals()
        with self._lock:
            ever = self._ever_recorded
        if n == 0:
            if not ever:
                return {}
            idle = {
                "window_s": self.window_s, "batches": 0,
                "achieved_flops_per_s": 0.0,
                "goodput_tokens_per_s": 0.0,
                "real_tokens": 0, "slot_tokens": 0,
                "padding_density": None,
                "mfu": 0.0 if self._resolve_peak()[0] else None,
                "mfu_busy": None,
                "peak_flops_per_s": self._resolve_peak()[0] or None,
                "peak_source": self._resolve_peak()[1],
                "n_devices": self._n_devices,
            }
            if self._n_devices > 1:
                # mfu mirrors the aggregate: 0.0 when idle-but-measured,
                # None when peak is unknown (0.0 would read as a DEAD
                # chip on a backend where MFU is simply unmeasurable).
                idle["per_chip"] = self._per_chip(
                    [0.0] * self._n_devices, 1.0, idle["mfu"])
            self._set_gauges(idle)
            return idle
        peak, source = self._resolve_peak()
        achieved = flops / span
        out: Dict[str, Any] = {
            "window_s": round(span, 3),
            "batches": n,
            "achieved_flops_per_s": round(achieved, 1),
            "goodput_tokens_per_s": round(real / span, 1),
            "real_tokens": real,
            "slot_tokens": slot,
            "padding_density": round(real / slot, 4) if slot else None,
            "peak_flops_per_s": peak or None,
            "peak_source": source,
            # 9 decimals: a tiny-model CPU window has a REAL mfu of ~1e-5
            # — and the k-means path's ~1e-7 — which must not round to a
            # dead-chip-looking 0.0.
            "mfu": round(achieved / peak, 9) if peak else None,
            "mfu_busy": round(flops / busy / peak, 9)
            if peak and busy > 0 else None,
            "n_devices": self._n_devices,
        }
        if self._n_devices > 1:
            # Per-chip rows: goodput from each chip's REAL data shard;
            # MFU is the aggregate number on every row (SPMD — one
            # program, identical per-chip FLOPs, shared wall window).
            out["per_chip"] = self._per_chip(per_dev, span,
                                             out.get("mfu"))
        self._set_gauges(out)
        return out

    def _per_chip(self, per_dev: List[float], span: float,
                  mfu) -> List[Dict[str, Any]]:
        rows = []
        for i, label in enumerate(self.device_labels):
            goodput = round(per_dev[i] / span, 1)
            self.m_chip_goodput.labels(device=label).set(goodput)
            rows.append({"device": label,
                         "goodput_tokens_per_s": goodput,
                         "real_tokens": int(per_dev[i]),
                         "mfu": mfu})
        return rows

    def _set_gauges(self, snap: Dict[str, Any]) -> None:
        self.m_mfu.set(snap.get("mfu") or 0.0)
        self.m_goodput.set(snap.get("goodput_tokens_per_s") or 0.0)
        self.m_density.set(snap.get("padding_density") or 0.0)
