"""On-demand jax.profiler capture + guarded trace-server startup.

Two complementary profiler surfaces, one module so their interplay is in
one place:

- **Trace server** (``--profiler-port``): the long-lived
  ``jax.profiler.start_server`` that TensorBoard / remote
  ``jax.profiler.trace`` clients ATTACH to — the reference's always-on
  ``:6060`` pprof analog.  :func:`start_profiler_server` wraps it so an
  unavailable or already-started profiler logs a WARNING instead of
  crashing worker startup (jax keeps one module-global server; a second
  start in the same process raises).
- **On-demand capture** (``/profile?seconds=N`` on the metrics port, and
  ``--profile-on-slow-ms`` auto-capture): :class:`ProfileCapture` runs
  ``jax.profiler.start_trace``/``stop_trace`` around a bounded sleep and
  writes the trace bundle under ``--dump-dir`` — no TensorBoard client
  needed, the bundle lands on disk next to the postmortem bundles.

The two share jax's single profiler session: a ``/profile`` capture while
a remote trace-server client is mid-capture (or vice versa) fails with
jax's "Only one profile may be run at a time" — surfaced here as a clear
409/error instead of an exception in the serving path.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("dct.profiling")

DEFAULT_MAX_SECONDS = 60.0   # bound on one /profile capture
DEFAULT_SECONDS = 3.0        # auto-capture window for --profile-on-slow-ms
DEFAULT_MAX_KEEP = 8         # trace bundles retained under dump_dir


class ProfileCapture:
    """Guarded one-at-a-time jax.profiler trace capture to a dump dir."""

    def __init__(self, dump_dir: str = "",
                 max_seconds: float = DEFAULT_MAX_SECONDS,
                 max_keep: int = DEFAULT_MAX_KEEP):
        self._lock = threading.Lock()
        self._active = False
        self.dump_dir = dump_dir
        self.max_seconds = max_seconds
        self.max_keep = max_keep
        self.captures = 0          # completed captures (for /costs + tests)
        self.last_path = ""

    def configure(self, dump_dir: Optional[str] = None,
                  max_seconds: Optional[float] = None) -> None:
        with self._lock:
            if dump_dir is not None:
                self.dump_dir = dump_dir
            if max_seconds is not None:
                self.max_seconds = max_seconds

    @property
    def active(self) -> bool:
        with self._lock:
            return self._active

    def capture(self, seconds: float) -> Dict[str, Any]:
        """Run one bounded capture; returns a JSON-safe result map with an
        HTTP-shaped ``code`` (200 ok / 400 bad request / 409 already
        running / 503 profiler unavailable).  Never raises."""
        try:
            seconds = float(seconds)
        except (TypeError, ValueError):
            return {"ok": False, "code": 400,
                    "error": "seconds must be a number"}
        if not seconds > 0:  # also rejects NaN
            return {"ok": False, "code": 400,
                    "error": "seconds must be > 0"}
        seconds = min(seconds, self.max_seconds)
        if not self.dump_dir:
            return {"ok": False, "code": 503,
                    "error": "no --dump-dir configured (profile bundles "
                             "need somewhere to land)"}
        with self._lock:
            if self._active:
                return {"ok": False, "code": 409,
                        "error": "a profiler capture is already running "
                                 "(one at a time)"}
            self._active = True
        path = os.path.join(
            self.dump_dir,
            f"profile_{time.strftime('%Y%m%d%H%M%S', time.gmtime())}"
            f"_{os.getpid()}")
        started = False
        try:
            import jax.profiler

            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
            started = True
            time.sleep(seconds)
        except Exception as e:
            # Covers: no jax, a backend that can't profile, AND jax's
            # module-global "Only one profile may be run at a time" when a
            # remote trace-server client holds the session.
            return {"ok": False, "code": 503,
                    "error": f"profiler capture failed to start: {e}"}
        finally:
            if started:
                try:
                    import jax.profiler

                    jax.profiler.stop_trace()
                except Exception as e:  # half-open session: report, move on
                    logger.warning("profiler stop_trace failed: %s", e)
            with self._lock:
                self._active = False
        with self._lock:
            self.captures += 1
            self.last_path = path
        self._prune_old()
        logger.info("profiler capture written", extra={
            "path": path, "seconds": seconds})
        from . import flight

        flight.record("profile_capture", path=path, seconds=seconds)
        return {"ok": True, "code": 200, "path": path, "seconds": seconds}

    def capture_async(self, seconds: float = DEFAULT_SECONDS,
                      reason: str = "") -> bool:
        """Fire-and-forget capture (the ``--profile-on-slow-ms`` path);
        returns False without spawning when one is already running — a
        stream of slow batches must produce one bundle, not a thread
        storm — or when no dump dir is configured (a capture that can
        never land must not report 'started' to the slow-batch log and
        flight events, nor spawn a doomed thread per slow batch)."""
        with self._lock:
            if self._active or not self.dump_dir:
                return False
        def run():
            result = self.capture(seconds)
            if not result.get("ok"):
                logger.warning("auto profiler capture (%s) failed: %s",
                               reason or "slow batch", result.get("error"))
        threading.Thread(target=run, daemon=True,
                         name="profile-capture").start()
        return True

    def _prune_old(self) -> None:
        """Keep only the newest ``max_keep`` trace bundles: /profile is
        side-effectful, and a dashboard probing it every scrape would
        otherwise fill the dump dir (shared with the crash postmortems)
        with multi-MB bundles until the host degrades.  Best-effort."""
        if self.max_keep <= 0 or not self.dump_dir:
            return
        try:
            import shutil

            bundles = sorted(
                e for e in os.listdir(self.dump_dir)
                if e.startswith("profile_")
                and os.path.isdir(os.path.join(self.dump_dir, e)))
            for stale in bundles[:-self.max_keep]:
                shutil.rmtree(os.path.join(self.dump_dir, stale),
                              ignore_errors=True)
        except OSError as e:
            logger.debug("profile-bundle pruning skipped: %s", e)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"active": self._active, "captures": self.captures,
                    "last_path": self.last_path,
                    "dump_dir": self.dump_dir,
                    "max_seconds": self.max_seconds,
                    "max_keep": self.max_keep}


PROFILER = ProfileCapture()


# Module-level conveniences delegating to the process-wide capture guard
# at CALL time (not bound at import), so tests can swap PROFILER.
def configure(dump_dir: Optional[str] = None,
              max_seconds: Optional[float] = None) -> None:
    PROFILER.configure(dump_dir=dump_dir, max_seconds=max_seconds)


def capture(seconds: float) -> Dict[str, Any]:
    return PROFILER.capture(seconds)


def capture_async(seconds: float = DEFAULT_SECONDS,
                  reason: str = "") -> bool:
    return PROFILER.capture_async(seconds, reason=reason)


_server_lock = threading.Lock()
_server_port: Optional[int] = None


def start_profiler_server(port: int) -> bool:
    """Start the long-lived jax.profiler trace server; best-effort.

    Guards the two startup hazards that must never kill a worker: jax (or
    its profiler) being unavailable, and a DUPLICATE start — jax keeps one
    module-global server, so a second ``start_server`` in the same
    process raises.  Both log a WARNING and return False.
    """
    global _server_port
    with _server_lock:
        if _server_port is not None:
            logger.warning(
                "profiler server already running on port %d; ignoring "
                "second start on port %d (jax keeps one per process)",
                _server_port, port)
            return False
        try:
            import jax.profiler

            jax.profiler.start_server(port)
        except Exception as e:
            logger.warning("profiler server failed to start: %s", e)
            return False
        _server_port = port
    logger.info("jax profiler serving", extra={"port": port})
    return True


def stop_profiler_server() -> None:
    """Stop the trace server if this process started one; best-effort."""
    global _server_port
    with _server_lock:
        if _server_port is None:
            return
        try:
            import jax.profiler

            jax.profiler.stop_server()
        except Exception as e:  # jax keeps a module-global server
            logger.warning("profiler server stop failed: %s", e)
        _server_port = None
