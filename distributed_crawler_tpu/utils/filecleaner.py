"""Background janitor deleting aged client media files.

Parity with the reference's `telegramhelper/filecleaner.go` (240 LoC): scan
`conn_*` connection directories under a base dir, delete files older than a
threshold from the configured subpaths (default media caches), on an
interval; started in job mode (`dapr/job.go:616-632`).
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from typing import List, Optional

logger = logging.getLogger("dct.filecleaner")

DEFAULT_SUBPATHS = [".tdlib/files/videos"]  # `filecleaner.go:33`
CONN_FOLDER_RE = re.compile(r"^conn_\d+")


class FileCleaner:
    """`filecleaner.go:30-240`."""

    def __init__(self, base_dir: str,
                 target_subpaths: Optional[List[str]] = None,
                 cleanup_interval_minutes: float = 30.0,
                 file_age_threshold_minutes: float = 60.0):
        self.base_dir = base_dir
        self.target_subpaths = list(target_subpaths or DEFAULT_SUBPATHS)
        self.cleanup_interval_s = cleanup_interval_minutes * 60.0
        self.file_age_threshold_s = file_age_threshold_minutes * 60.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.files_removed = 0

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                raise RuntimeError("file cleaner is already running")
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="dct-filecleaner")
            self._thread.start()
        logger.info("file cleaner started", extra={
            "base_dir": self.base_dir,
            "path_patterns": [os.path.join("conn_*", p)
                              for p in self.target_subpaths],
            "file_age_threshold_min": self.file_age_threshold_s / 60.0})

    def stop(self) -> None:
        with self._lock:
            if self._thread is None:
                return
            self._stop.set()
            self._thread.join(timeout=5.0)
            self._thread = None
        logger.info("file cleaner stopped")

    def _loop(self) -> None:
        # Run immediately on start, then on the interval (`:95-110`).
        self.clean_old_files()
        while not self._stop.wait(self.cleanup_interval_s):
            self.clean_old_files()

    def clean_old_files(self, now: Optional[float] = None) -> int:
        """One sweep; returns files removed (`filecleaner.go:113-240`)."""
        now = now if now is not None else time.time()
        cutoff = now - self.file_age_threshold_s
        removed = 0
        if not os.path.isdir(self.base_dir):
            return 0
        try:
            entries = os.listdir(self.base_dir)
        except OSError as e:
            logger.warning("cannot list base dir %s: %s", self.base_dir, e)
            return 0
        for entry in entries:
            if not CONN_FOLDER_RE.match(entry):
                continue
            for sub in self.target_subpaths:
                target = os.path.join(self.base_dir, entry, sub)
                if not os.path.isdir(target):
                    continue
                removed += self._clean_dir(target, cutoff)
        if removed:
            logger.info("file cleanup complete",
                        extra={"files_removed": removed})
        self.files_removed += removed
        return removed

    def _clean_dir(self, directory: str, cutoff: float) -> int:
        removed = 0
        try:
            names = os.listdir(directory)
        except OSError:
            return 0
        for name in names:
            path = os.path.join(directory, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            if not os.path.isfile(path):
                continue
            if st.st_mtime < cutoff:
                try:
                    os.remove(path)
                    removed += 1
                except OSError as e:
                    logger.warning("failed to remove %s: %s", path, e)
        return removed
