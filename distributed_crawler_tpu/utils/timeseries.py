"""Rolling time-series store: the history every gauge was missing.

Every observability surface so far answers about the *current instant* —
`/metrics` gauges, `/costs` rolling windows, `/cluster` last-heartbeat
folds — and the flight recorder keeps events, not values.  Nothing in the
system could answer "is queue wait trending up?" or "how fast are we
burning the error budget?".  This module is the missing half: a bounded,
O(1)-append ring of ``(wall, value)`` samples per labeled series, local
to the process (no sidecar, no external TSDB), with:

- **aligned downsampling**: reads can bucket samples into epoch-aligned
  windows (mean + count per bucket), so two scrapers asking for the same
  ``window`` see the same bucket boundaries;
- **counter-reset-aware ``increase()``**: the rate read burn-rate alert
  rules (`utils/alerts.py`) are built on — a worker restart's counter
  regression counts the fresh value, not a huge negative delta;
- **least-squares ``slope()``**: the trend read (`dlq_growth`-style
  rules);
- a ``snapshot()`` JSON body served at the metrics server's
  ``/timeseries`` endpoint (`utils/metrics.py`; ``?series=&window=``).

Feeds: the orchestrator's `Watchtower` (`orchestrator/watchtower.py`)
writes fleet-wide series from every telemetry heartbeat, and each worker
process *self-samples* its own metrics registry once per telemetry
interval (`RegistrySampler`, built on the shared exposition parser in
`loadgen/exposition.py`) so a worker's history survives orchestrator
restarts — the orchestrator re-folds what heartbeats carry, the worker
keeps its own ring regardless.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from .exposition import parse_exposition

logger = logging.getLogger("dct.timeseries")

DEFAULT_MAX_SAMPLES = 512   # samples kept per series
DEFAULT_WINDOW_S = 900.0    # reads ignore samples older than this
DEFAULT_MAX_SERIES = 4096   # distinct labeled series kept


def series_key(name: str, labels: Optional[Dict[str, str]] = None) -> str:
    """Canonical series identity: ``name{k=v,...}`` with sorted labels
    (bare ``name`` when unlabeled) — the ``?series=`` query value."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


@dataclass
class _Series:
    name: str
    labels: Dict[str, str]
    samples: Deque[Tuple[float, float]] = field(default_factory=deque)


class TimeSeriesStore:
    """Thread-safe bounded store of labeled (wall, value) rings."""

    def __init__(self, max_samples: int = DEFAULT_MAX_SAMPLES,
                 window_s: float = DEFAULT_WINDOW_S,
                 max_series: int = DEFAULT_MAX_SERIES,
                 clock=time.time):
        self.max_samples = max(2, int(max_samples))
        self.window_s = float(window_s)
        self.max_series = max(1, int(max_series))
        self.clock = clock
        self._mu = threading.Lock()
        self._series: Dict[str, _Series] = {}
        self._dropped_series = 0
        self._warned_full = False

    def configure(self, max_samples: Optional[int] = None,
                  window_s: Optional[float] = None,
                  max_series: Optional[int] = None) -> None:
        """Resize the rings / retention (CLI flags reconfigure the
        process-global STORE before serving starts; existing series are
        re-bounded in place)."""
        with self._mu:
            if max_samples is not None:
                self.max_samples = max(2, int(max_samples))
                for s in self._series.values():
                    s.samples = deque(s.samples, maxlen=self.max_samples)
            if window_s is not None:
                self.window_s = float(window_s)
            if max_series is not None:
                self.max_series = max(1, int(max_series))

    # -- writes --------------------------------------------------------------
    def add(self, name: str, value: float,
            labels: Optional[Dict[str, str]] = None,
            wall: Optional[float] = None) -> bool:
        """Append one sample; O(1).  Returns False when the series-count
        bound rejected a NEW series (existing series always accept)."""
        key = series_key(name, labels)
        wall = self.clock() if wall is None else float(wall)
        with self._mu:
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.max_series:
                    self._dropped_series += 1
                    if not self._warned_full:
                        self._warned_full = True
                        logger.warning(
                            "time-series store full (%d series); new "
                            "series are dropped — raise "
                            "timeseries_max_samples/max_series or reduce "
                            "label cardinality", self.max_series)
                    return False
                s = _Series(name=name, labels=dict(labels or {}),
                            samples=deque(maxlen=self.max_samples))
                self._series[key] = s
            s.samples.append((wall, float(value)))
        return True

    # -- reads ---------------------------------------------------------------
    def keys(self) -> List[str]:
        with self._mu:
            return sorted(self._series)

    def matching(self, name: str,
                 labels: Optional[Dict[str, str]] = None,
                 since: float = 0.0
                 ) -> List[Tuple[Dict[str, str],
                                 List[Tuple[float, float]]]]:
        """Every series of ``name`` whose labels are a superset of
        ``labels``, as [(labels, [(wall, value), ...])] snapshots —
        evaluation-safe: the lists are copies, so concurrent appends and
        ring evictions cannot corrupt a walk in progress."""
        want = labels or {}
        out = []
        with self._mu:
            for s in self._series.values():
                if s.name != name:
                    continue
                if any(s.labels.get(k) != v for k, v in want.items()):
                    continue
                samples = [p for p in s.samples if p[0] >= since] \
                    if since else list(s.samples)
                out.append((dict(s.labels), samples))
        return out

    def samples(self, name: str,
                labels: Optional[Dict[str, str]] = None,
                since: float = 0.0) -> List[Tuple[float, float]]:
        """One exact series' samples (empty when absent)."""
        key = series_key(name, labels)
        with self._mu:
            s = self._series.get(key)
            if s is None:
                return []
            return [p for p in s.samples if p[0] >= since] \
                if since else list(s.samples)

    def latest(self, name: str,
               labels: Optional[Dict[str, str]] = None
               ) -> Optional[float]:
        key = series_key(name, labels)
        with self._mu:
            s = self._series.get(key)
            return s.samples[-1][1] if s is not None and s.samples else None

    def increase(self, name: str,
                 labels: Optional[Dict[str, str]] = None,
                 window_s: float = 300.0,
                 now: Optional[float] = None) -> float:
        """Counter increase over the trailing window, summed across every
        matching labeled child, reset-aware: a negative step (the counter
        restarted from zero) contributes the NEW value, mirroring the
        FleetView's task-rate fold.  The sample immediately preceding the
        window anchors the first in-window delta, so sparse sampling
        never undercounts."""
        now = self.clock() if now is None else now
        start = now - float(window_s)
        total = 0.0
        for _, samples in self.matching(name, labels):
            prev = None
            for wall, value in samples:
                if wall < start:
                    prev = value
                    continue
                if prev is not None:
                    delta = value - prev
                    total += delta if delta >= 0 else value
                prev = value
        return total

    @staticmethod
    def slope(samples: List[Tuple[float, float]],
              min_samples: int = 2) -> Optional[float]:
        """Least-squares slope in value-units per second, or None when
        the series can't support one (fewer than ``min_samples`` points,
        or zero time spread — a single sample has no slope)."""
        n = len(samples)
        if n < max(2, min_samples):
            return None
        mean_t = sum(p[0] for p in samples) / n
        mean_v = sum(p[1] for p in samples) / n
        var_t = sum((p[0] - mean_t) ** 2 for p in samples)
        if var_t <= 0.0:
            return None
        cov = sum((p[0] - mean_t) * (p[1] - mean_v) for p in samples)
        return cov / var_t

    @staticmethod
    def downsample(samples: List[Tuple[float, float]], bucket_s: float
                   ) -> List[Tuple[float, float, int]]:
        """Epoch-aligned buckets: [(bucket_start, mean, count)].
        Alignment is absolute (floor(wall / bucket) * bucket), so every
        reader asking for the same bucket width sees the same
        boundaries."""
        bucket_s = float(bucket_s)
        if bucket_s <= 0 or not samples:
            return [(w, v, 1) for w, v in samples]
        acc: Dict[float, Tuple[float, int]] = {}
        for wall, value in samples:
            b = (wall // bucket_s) * bucket_s
            total, n = acc.get(b, (0.0, 0))
            acc[b] = (total + value, n + 1)
        return [(b, total / n, n)
                for b, (total, n) in sorted(acc.items())]

    # -- export --------------------------------------------------------------
    def snapshot(self, series: Optional[str] = None,
                 window_s: float = 0.0,
                 since_s: float = 0.0,
                 now: Optional[float] = None) -> Dict[str, Any]:
        """The ``/timeseries`` JSON body.  ``series`` filters by metric
        name OR exact series key; ``window_s`` > 0 downsamples into
        aligned buckets; ``since_s`` bounds history (default: the store's
        retention window)."""
        now = self.clock() if now is None else now
        horizon = now - (since_s if since_s > 0 else self.window_s)
        with self._mu:
            picked = []
            for key, s in self._series.items():
                if series and series not in (s.name, key):
                    continue
                picked.append((key, s.name, dict(s.labels),
                               [p for p in s.samples if p[0] >= horizon]))
            dropped = self._dropped_series
        body: Dict[str, Any] = {
            "generated_at": now,
            "window_s": self.window_s,
            "max_samples": self.max_samples,
            "series_count": len(picked),
            "dropped_series": dropped,
            "series": {},
        }
        for key, name, labels, samples in sorted(picked):
            if window_s > 0:
                points = [[round(b, 3), round(mean, 6), n]
                          for b, mean, n in self.downsample(samples,
                                                            window_s)]
            else:
                points = [[round(w, 3), v] for w, v in samples]
            body["series"][key] = {"name": name, "labels": labels,
                                   "samples": points}
        return body

    def reset(self) -> None:
        with self._mu:
            self._series.clear()
            self._dropped_series = 0
            self._warned_full = False


class RegistrySampler:
    """Self-sampling: one process's metrics registry → its own store.

    Each :meth:`sample` parses the registry's exposition through the ONE
    shared parser (`utils/exposition.py:parse_exposition`) and appends
    every sample as a time-series point.  Histogram
    ``_bucket`` children are skipped (per-le cardinality would crowd out
    real series; ``_sum``/``_count`` survive and carry the same story).
    Never raises — sampling telemetry must not take a heartbeat down.
    """

    def __init__(self, registry, store: Optional[TimeSeriesStore] = None,
                 include_prefixes: Tuple[str, ...] = (),
                 exclude_suffixes: Tuple[str, ...] = ("_bucket",)):
        self.registry = registry
        self.store = store if store is not None else STORE
        self.include_prefixes = tuple(include_prefixes)
        self.exclude_suffixes = tuple(exclude_suffixes)

    def sample(self, now: Optional[float] = None) -> int:
        """One self-sampling tick; returns the samples appended."""
        try:
            text = self.registry.expose()
        except Exception as e:
            logger.debug("registry self-sample degraded: %s", e)
            return 0
        added = 0
        wall = self.store.clock() if now is None else now
        for s in parse_exposition(text):
            if self.exclude_suffixes and \
                    s.name.endswith(self.exclude_suffixes):
                continue
            if self.include_prefixes and \
                    not s.name.startswith(self.include_prefixes):
                continue
            if self.store.add(s.name, s.value, s.labels or None,
                              wall=wall):
                added += 1
        return added


# The process-global store: workers self-sample into it, the orchestrator's
# watchtower folds heartbeats into it, and the metrics server serves it at
# /timeseries (the TRACER/RECORDER pattern).
STORE = TimeSeriesStore()
configure = STORE.configure
