"""The ONE Prometheus text-exposition parser.

Three consumers used to carry their own ad-hoc line parsers — the
perf-report renderer (`tools/perfreport.py:_metric_samples`), the
postmortem renderer (`tools/postmortem.py:_moving_metrics`), and now the
watchtower's registry self-sampler (`utils/timeseries.py`), which turns
every sample of a process's own `/metrics` body into time-series points
each telemetry tick.  Divergent parsers drift (one handled escaped label
values, one didn't), so this module is the single shared implementation;
the tools import it (via its `loadgen.exposition` re-export, next to the
gate that scrapes /metrics) and their local copies are gone.

Deliberately stdlib-only and import-light: the self-sampler runs it on
every worker heartbeat, so nothing here may pull jax, numpy, the engine
stack — or the loadgen package (whose __init__ drags the whole gate in).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

# name{labels} value — histogram/summary suffixes parse like any sample.
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)\s*$")
# One k="v" pair inside a label block; values may carry escaped quotes.
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(value: str) -> str:
    return (value.replace('\\"', '"').replace("\\n", "\n")
            .replace("\\\\", "\\"))


@dataclass
class Sample:
    """One parsed exposition sample."""

    name: str
    value: float
    labels: Dict[str, str] = field(default_factory=dict)
    labels_str: str = ""     # the raw "{k=\"v\",...}" block ("" when bare)
    line: str = ""           # the raw line (postmortem renders these)


def parse_exposition(text: str) -> List[Sample]:
    """Every sample in a Prometheus text exposition, in document order.

    Comment/HELP/TYPE lines and unparseable lines are skipped (a torn
    scrape must degrade to fewer samples, never raise)."""
    out: List[Sample] = []
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        m = _SAMPLE_RE.match(stripped)
        if m is None:
            continue
        try:
            value = float(m.group(3))
        except ValueError:
            continue
        labels_str = m.group(2) or ""
        labels = {k: _unescape(v)
                  for k, v in _LABEL_RE.findall(labels_str)}
        out.append(Sample(name=m.group(1), value=value, labels=labels,
                          labels_str=labels_str, line=stripped))
    return out


def metric_samples(text: str, name: str) -> List[Tuple[str, float]]:
    """[(labels_str, value)] for every sample of exactly ``name`` —
    the shape `tools/perfreport.py` renders."""
    return [(s.labels_str, s.value) for s in parse_exposition(text)
            if s.name == name]


def moving_samples(text: str) -> List[str]:
    """Raw sample lines whose value is non-zero — the "metrics that
    moved" digest `tools/postmortem.py` prints from a bundle."""
    return [s.line for s in parse_exposition(text) if s.value != 0.0]
