"""Declarative alert engine: rules over rolling time series.

The reference's orchestrator *judges* worker health continuously
(`check_worker_health`) but every judgement here so far is either a
point-in-time gauge or an offline gate assertion.  This module closes
the loop the ROADMAP's elastic-fleet item names: declarative rules
evaluated on the orchestrator tick over the rolling store
(`utils/timeseries.py`), with Prometheus-style alert lifecycles.

Three rule kinds:

- ``threshold`` — an aggregate (``last``/``mean``/``min``/``max`` over
  ``window_s``) of the matching series, grouped across labeled children
  (``sum``/``min``/``max``), compared with ``op`` against ``value``;
- ``trend`` — least-squares slope over ``window_s`` (value-units per
  second, summed across children), compared with ``op`` against
  ``slope_per_s``; a series with fewer than ``min_samples`` points (or
  no time spread) has NO slope and the rule stays inactive;
- ``burn_rate`` — multi-window SLO burn rate in the SRE-workbook style:
  the counter's increase-rate over a FAST and a SLOW window, each
  divided by the budget rate (``budget`` events per
  ``budget_window_s``), must BOTH exceed ``factor``.  The fast window
  makes the alert prompt, the slow window keeps one spike from paging.
  A zero/absent budget means zero tolerance: any increase burns at
  infinite rate and the factor check degenerates to "did it breach".

Lifecycle per rule: ``inactive → pending →(held for_s) firing →(clear
held clear_for_s) resolved``; a resolved alert must re-confirm through
``pending`` for ``for_s`` again before re-firing (flap suppression), and
a pending alert whose condition clears never fires at all.  Every
transition is flight-recorded, counted
(``alert_transitions_total{rule,to}``), kept in a bounded log, and —
through the publish seam the watchtower wires — announced as a typed
`AlertMessage` on ``TOPIC_ALERTS``.  `snapshot()` is the ``/alerts``
body.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional

from . import flight
from .metrics import REGISTRY, MetricsRegistry
from .timeseries import STORE, TimeSeriesStore

logger = logging.getLogger("dct.alerts")

ALERT_INACTIVE = "inactive"
ALERT_PENDING = "pending"
ALERT_FIRING = "firing"
ALERT_RESOLVED = "resolved"

RULE_KINDS = ("threshold", "trend", "burn_rate")
_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}
_AGGS = ("last", "mean", "min", "max")
_GROUPS = ("sum", "min", "max")

# JSON clamp for infinite burn rates (zero budget + any breach): strict
# JSON has no Infinity, and the /alerts body must stay parseable.
_BURN_CLAMP = 1e9


@dataclass
class AlertRule:
    """One declared rule (docs/operations.md "Watchtower" rule grammar)."""

    name: str
    kind: str                                   # one of RULE_KINDS
    series: str                                 # metric name in the store
    labels: Dict[str, str] = field(default_factory=dict)
    # threshold
    op: str = ">"
    value: float = 0.0
    agg: str = "last"
    window_s: float = 60.0                      # threshold/trend window
    # across matching labeled children (threshold only; trends sum)
    group: str = "sum"
    # trend
    slope_per_s: float = 0.0
    min_samples: int = 3
    # burn_rate
    budget: float = 0.0                         # events per budget window
    budget_window_s: float = 3600.0
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    factor: float = 6.0
    # lifecycle
    for_s: float = 0.0                          # pending must hold this long
    clear_for_s: float = 0.0                    # clear must hold this long
    severity: str = "page"
    description: str = ""

    def validate(self) -> None:
        if not self.name:
            raise ValueError("alert rule name cannot be empty")
        if self.kind not in RULE_KINDS:
            raise ValueError(f"alert rule {self.name}: unknown kind "
                             f"{self.kind!r} (want {'|'.join(RULE_KINDS)})")
        if not self.series:
            raise ValueError(f"alert rule {self.name}: series required")
        if self.op not in _OPS:
            raise ValueError(f"alert rule {self.name}: op must be one of "
                             f"{', '.join(_OPS)}")
        if self.agg not in _AGGS:
            raise ValueError(f"alert rule {self.name}: agg must be one of "
                             f"{', '.join(_AGGS)}")
        if self.group not in _GROUPS:
            raise ValueError(f"alert rule {self.name}: group must be one "
                             f"of {', '.join(_GROUPS)}")
        if self.kind == "burn_rate" and self.fast_window_s <= 0:
            raise ValueError(f"alert rule {self.name}: fast_window_s must "
                             "be positive")
        if self.kind == "burn_rate" and \
                self.slow_window_s < self.fast_window_s:
            raise ValueError(f"alert rule {self.name}: slow_window_s must "
                             "be >= fast_window_s")

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AlertRule":
        known = {f for f in cls.__dataclass_fields__}  # noqa: C416
        unknown = set(d) - known
        if unknown:
            # A typo'd rule key must fail loudly at config time, not
            # silently evaluate a default forever.
            raise ValueError(
                f"alert rule {d.get('name', '?')}: unknown key(s) "
                f"{', '.join(sorted(unknown))}")
        try:
            rule = cls(**{k: v for k, v in d.items()})
        except TypeError as e:
            # Missing required keys raise TypeError from __init__; the
            # config-error contract (cli exit 2, scenario setup error)
            # catches ValueError — keep the promise.
            raise ValueError(
                f"alert rule {d.get('name', '?')}: {e}") from e
        rule.labels = dict(rule.labels or {})
        rule.validate()
        return rule


@dataclass
class _AlertState:
    state: str = ALERT_INACTIVE
    since: float = 0.0            # when the current state was entered
    pending_since: float = 0.0
    clear_since: float = 0.0      # condition-false streak while firing
    fired_at: float = 0.0
    resolved_at: float = 0.0
    fired_count: int = 0
    value: Optional[float] = None
    detail: Dict[str, Any] = field(default_factory=dict)


class AlertEngine:
    """Evaluate rules over a store; own the lifecycles; feed the surfaces."""

    def __init__(self, rules: List[AlertRule],
                 store: Optional[TimeSeriesStore] = None,
                 registry: MetricsRegistry = REGISTRY,
                 clock=time.time,
                 publish: Optional[Callable[[Dict[str, Any]], None]] = None,
                 log_capacity: int = 256):
        self.rules = list(rules)
        for r in self.rules:
            r.validate()
        seen = set()
        for r in self.rules:
            if r.name in seen:
                raise ValueError(f"duplicate alert rule name {r.name!r}")
            seen.add(r.name)
        self.store = store if store is not None else STORE
        self.clock = clock
        self.publish = publish
        self._mu = threading.Lock()
        self._states: Dict[str, _AlertState] = {
            r.name: _AlertState() for r in self.rules}
        self._log: Deque = deque(maxlen=max(1, log_capacity))
        self.m_firing = registry.gauge(
            "alerts_firing", "alert rules currently in the firing state")
        self.m_transitions = registry.counter(
            "alert_transitions_total",
            "alert lifecycle transitions by rule and target state")

    # -- condition evaluation ------------------------------------------------
    def _eval_threshold(self, rule: AlertRule, now: float
                        ) -> "tuple[bool, Optional[float], Dict[str, Any]]":
        since = now - rule.window_s if rule.window_s > 0 else 0.0
        children = self.store.matching(rule.series, rule.labels or None,
                                       since=since)
        per_child: List[float] = []
        for _, samples in children:
            if not samples:
                continue
            vals = [v for _, v in samples]
            if rule.agg == "last":
                per_child.append(vals[-1])
            elif rule.agg == "mean":
                per_child.append(sum(vals) / len(vals))
            elif rule.agg == "min":
                per_child.append(min(vals))
            else:
                per_child.append(max(vals))
        if not per_child:
            return False, None, {"series": 0}  # empty series: inactive
        if rule.group == "sum":
            value = sum(per_child)
        elif rule.group == "min":
            value = min(per_child)
        else:
            value = max(per_child)
        return (_OPS[rule.op](value, rule.value), value,
                {"series": len(per_child), "op": rule.op,
                 "threshold": rule.value})

    def _eval_trend(self, rule: AlertRule, now: float
                    ) -> "tuple[bool, Optional[float], Dict[str, Any]]":
        since = now - rule.window_s if rule.window_s > 0 else 0.0
        children = self.store.matching(rule.series, rule.labels or None,
                                       since=since)
        slopes = [s for s in
                  (self.store.slope(samples, rule.min_samples)
                   for _, samples in children)
                  if s is not None]
        if not slopes:
            # Too few samples for ANY slope (single-sample series
            # included): no judgement, not a breach.
            return False, None, {"series": 0}
        value = sum(slopes)  # fleet trend = summed per-child slopes
        return (_OPS[rule.op](value, rule.slope_per_s), value,
                {"series": len(slopes), "op": rule.op,
                 "slope_per_s": rule.slope_per_s})

    def _eval_burn(self, rule: AlertRule, now: float
                   ) -> "tuple[bool, Optional[float], Dict[str, Any]]":
        budget_rate = (rule.budget / rule.budget_window_s
                       if rule.budget > 0 and rule.budget_window_s > 0
                       else 0.0)

        def burn(window_s: float) -> float:
            rate = self.store.increase(rule.series, rule.labels or None,
                                       window_s=window_s,
                                       now=now) / window_s
            if budget_rate <= 0.0:
                # Zero budget = zero tolerance: any increase is an
                # infinite burn; no increase burns nothing.
                return math.inf if rate > 0 else 0.0
            return rate / budget_rate

        fast = burn(rule.fast_window_s)
        slow = burn(rule.slow_window_s)
        cond = fast >= rule.factor and slow >= rule.factor
        value = min(fast, _BURN_CLAMP)
        return cond, value, {
            "burn_fast": round(min(fast, _BURN_CLAMP), 3),
            "burn_slow": round(min(slow, _BURN_CLAMP), 3),
            "factor": rule.factor, "budget": rule.budget,
            "budget_window_s": rule.budget_window_s,
        }

    # -- lifecycle -----------------------------------------------------------
    def _transition(self, rule: AlertRule, st: _AlertState, to: str,
                    now: float) -> Dict[str, Any]:
        event = {
            "rule": rule.name, "kind": rule.kind, "series": rule.series,
            "from": st.state, "to": to, "at": now,
            "value": st.value if st.value is None
            else round(st.value, 6),
            "detail": dict(st.detail), "severity": rule.severity,
        }
        st.state = to
        st.since = now
        if to == ALERT_FIRING:
            st.fired_at = now
            st.fired_count += 1
        elif to == ALERT_RESOLVED:
            st.resolved_at = now
        self.m_transitions.labels(rule=rule.name, to=to).inc()
        flight.record("alert", rule=rule.name, rule_kind=rule.kind,
                      series=rule.series, prev=event["from"], to=to,
                      value=event["value"], severity=rule.severity)
        logger.log(
            logging.WARNING if to == ALERT_FIRING else logging.INFO,
            "alert %s: %s -> %s (value=%s)", rule.name, event["from"], to,
            event["value"])
        self._log.append(event)
        return event

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation tick over every rule; returns the transitions
        that happened (empty most ticks)."""
        now = self.clock() if now is None else now
        transitions: List[Dict[str, Any]] = []
        with self._mu:
            for rule in self.rules:
                st = self._states[rule.name]
                try:
                    if rule.kind == "threshold":
                        cond, value, detail = self._eval_threshold(rule, now)
                    elif rule.kind == "trend":
                        cond, value, detail = self._eval_trend(rule, now)
                    else:
                        cond, value, detail = self._eval_burn(rule, now)
                except Exception as e:
                    logger.warning("alert rule %s evaluation failed: %s",
                                   rule.name, e)
                    continue
                st.value, st.detail = value, detail
                if cond:
                    st.clear_since = 0.0
                    if st.state in (ALERT_INACTIVE, ALERT_RESOLVED):
                        # Re-fire from resolved goes through pending
                        # again: the for_s confirm IS the flap
                        # suppression.
                        st.pending_since = now
                        transitions.append(self._transition(
                            rule, st, ALERT_PENDING, now))
                        if rule.for_s <= 0:
                            transitions.append(self._transition(
                                rule, st, ALERT_FIRING, now))
                    elif st.state == ALERT_PENDING and \
                            now - st.pending_since >= rule.for_s:
                        transitions.append(self._transition(
                            rule, st, ALERT_FIRING, now))
                else:
                    if st.state == ALERT_PENDING:
                        # Pending that never confirms: back to inactive,
                        # no firing, no publish.
                        transitions.append(self._transition(
                            rule, st, ALERT_INACTIVE, now))
                    elif st.state == ALERT_FIRING:
                        if st.clear_since <= 0.0:
                            st.clear_since = now
                        if now - st.clear_since >= rule.clear_for_s:
                            st.clear_since = 0.0
                            transitions.append(self._transition(
                                rule, st, ALERT_RESOLVED, now))
            self.m_firing.set(float(sum(
                1 for s in self._states.values()
                if s.state == ALERT_FIRING)))
        # Publish OUTSIDE the engine lock: a slow or down broker must
        # stall neither /alerts reads (snapshot takes _mu) nor the next
        # evaluation — only this call.
        if self.publish is not None:
            for event in transitions:
                if event["to"] not in (ALERT_FIRING, ALERT_RESOLVED):
                    continue
                try:
                    self.publish(event)
                except Exception as e:  # the bus must not break evaluation
                    logger.warning("alert publish failed: %s", e)
        return transitions

    # -- export --------------------------------------------------------------
    def firing(self) -> List[str]:
        with self._mu:
            return sorted(name for name, s in self._states.items()
                          if s.state == ALERT_FIRING)

    def snapshot(self) -> Dict[str, Any]:
        """The ``/alerts`` JSON body: per-rule state + the transition log
        (postmortem bundles embed this — the alert history a dead
        process can no longer serve)."""
        now = self.clock()
        with self._mu:
            alerts = []
            for rule in self.rules:
                st = self._states[rule.name]
                alerts.append({
                    "rule": rule.name, "kind": rule.kind,
                    "series": rule.series, "labels": rule.labels,
                    "severity": rule.severity, "state": st.state,
                    "since": st.since, "value": st.value
                    if st.value is None else round(st.value, 6),
                    "detail": dict(st.detail),
                    "fired_count": st.fired_count,
                    "fired_at": st.fired_at or None,
                    "resolved_at": st.resolved_at or None,
                    "for_s": rule.for_s,
                    "description": rule.description,
                })
            log = list(self._log)
        return {
            "generated_at": now,
            "firing": sorted(a["rule"] for a in alerts
                             if a["state"] == ALERT_FIRING),
            "alerts": alerts,
            "log": log,
        }


def default_rules(slo_budget: float = 10.0,
                  slo_budget_window_s: float = 3600.0,
                  fast_window_s: float = 300.0,
                  slow_window_s: float = 3600.0,
                  factor: float = 6.0,
                  for_s: float = 15.0,
                  per_chip_goodput_floor: float = 0.0,
                  outbox_utilization_max: float = 0.8,
                  dlq_slope_per_s: float = 0.0,
                  trend_window_s: float = 300.0) -> List[AlertRule]:
    """The default rule pack the watchtower installs (documented in
    docs/operations.md "Watchtower").  Series names are the watchtower's
    heartbeat folds plus the registry self-sample names, so the pack
    works identically in one-process rigs (the loadgen gate) and real
    fleets."""
    return [
        AlertRule(
            name="queue_wait_burn", kind="burn_rate",
            series="fleet_slo_breach_total", labels={"slo": "queue_wait"},
            budget=slo_budget, budget_window_s=slo_budget_window_s,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            factor=factor, for_s=for_s,
            description="queue-wait SLO breaches are burning the error "
                        "budget at a page-worthy rate in BOTH windows"),
        AlertRule(
            name="batch_age_burn", kind="burn_rate",
            series="fleet_slo_breach_total", labels={"slo": "batch_age"},
            budget=slo_budget, budget_window_s=slo_budget_window_s,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            factor=factor, for_s=for_s,
            description="whole-pipeline batch age is burning its budget "
                        "(frames stranded on the broker come back old)"),
        AlertRule(
            name="per_chip_goodput_collapse", kind="threshold",
            series="fleet_per_chip_goodput_tokens_per_s",
            op="<", value=per_chip_goodput_floor, agg="mean",
            group="min", window_s=trend_window_s, for_s=for_s,
            description="the worst mesh chip's goodput fell under the "
                        "floor while aggregate throughput may still look "
                        "fine (the PR-11 multichip judge, live).  The "
                        "default floor of 0 keeps the rule inert — an "
                        "idle fleet's meters decay to 0 by design, so "
                        "only a site-configured floor can distinguish "
                        "collapse from idleness"),
        AlertRule(
            name="dlq_growth", kind="trend",
            series="bus_dead_letters_total", op=">",
            slope_per_s=dlq_slope_per_s, window_s=trend_window_s,
            min_samples=3, for_s=for_s, severity="ticket",
            description="dead letters are accumulating (positive "
                        "least-squares slope over the window)"),
        AlertRule(
            name="outbox_near_full", kind="threshold",
            series="watchtower_outbox_utilization", op=">=",
            value=outbox_utilization_max, agg="last", group="max",
            for_s=0.0,
            description="a durable publish outbox is near its bound; "
                        "dispatch backpressure (and then OutboxFull) is "
                        "imminent"),
        AlertRule(
            name="stale_worker", kind="threshold",
            series="fleet_stale_workers", op=">", value=0.0, agg="last",
            for_s=0.0,
            description="at least one worker's heartbeat is older than "
                        "the liveness timeout"),
    ]


def rules_from_config(raw: Any,
                      defaults: Optional[List[AlertRule]] = None
                      ) -> List[AlertRule]:
    """Build the rule list from ``observability.alert_rules`` (a list of
    rule dicts — YAML config, a scenario's "alerts" block, or a parsed
    ``--alert-rules`` JSON value).  A configured rule REPLACES the
    same-named default; other defaults survive, so a site tuning one
    budget keeps the rest of the pack."""
    defaults = list(defaults if defaults is not None else default_rules())
    if not raw:
        return defaults
    if not isinstance(raw, list):
        raise ValueError("alert_rules must be a list of rule objects")
    configured = [AlertRule.from_dict(dict(d)) for d in raw]
    by_name = {r.name: r for r in defaults}
    for r in configured:
        by_name[r.name] = r
    # Configured-first ordering keeps scenario-declared rules visibly at
    # the top of /alerts; surviving defaults follow in pack order.
    names = [r.name for r in configured] + \
        [r.name for r in defaults if r.name not in
         {c.name for c in configured}]
    return [by_name[n] for n in names]

