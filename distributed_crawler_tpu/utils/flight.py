"""Flight recorder: a black box that survives the crash it describes.

The observability stack so far answers "where did the milliseconds go"
(`utils/trace.py`) and "how much/how fast" (`utils/metrics.py`) — but both
live behind HTTP endpoints that die with the process.  When a worker is
OOM-killed, wedges on a tunneled chip, or takes an unhandled exception,
the questions are retrospective: what was it DOING?  This module keeps a
bounded, thread-safe ring of structured events (state transitions,
dispatch/requeue decisions, batch outcomes, errors) recorded from the
orchestrator and both worker loops, and on the way down writes a
**postmortem bundle** — flight ring + trace export + metrics exposition +
config fingerprint — as one JSON file under ``--dump-dir``.

Three exits are hooked (see :func:`install` and `cli.py`):

- SIGTERM: ``cli._serve_forever``'s handler dumps before the graceful
  KeyboardInterrupt teardown runs;
- unhandled exception: chained ``sys.excepthook`` + ``threading.excepthook``
  (worker loops are threads) dump, then defer to the previous hook;
- fatal signal (SIGSEGV/SIGFPE/SIGABRT/SIGBUS): ``faulthandler`` writes
  native tracebacks to ``<dump-dir>/fatal_signal.log`` — the JSON bundle
  cannot be built from a signal handler, so the traceback file IS the
  black box for that class.

`tools/postmortem.py` renders a bundle as a human-readable timeline.
Recording is allocation-cheap (one dict append under a lock) and a
capacity of 0 disables it entirely; ``dump()`` is a no-op until a dump
dir is configured, so library users who never opt in pay nothing.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

logger = logging.getLogger("dct.flight")

DEFAULT_CAPACITY = 512  # events kept; a dump carries at most this many


class FlightRecorder:
    """Bounded ring of structured events + the postmortem bundle writer."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._events: "deque[Dict[str, Any]]" = deque(maxlen=max(1, capacity))
        self._enabled = capacity > 0
        self.capacity = capacity
        self.dump_dir = ""
        self._fingerprint: Dict[str, Any] = {}
        self._dumped: Dict[str, float] = {}  # reason -> wall time of dump

    # -- configuration ------------------------------------------------------
    def configure(self, capacity: Optional[int] = None,
                  dump_dir: Optional[str] = None,
                  fingerprint: Optional[Dict[str, Any]] = None) -> None:
        """Resize the ring / set the dump dir / stamp the config
        fingerprint (mode, worker id, key knobs) carried in every bundle."""
        with self._lock:
            if capacity is not None:
                self.capacity = capacity
                self._enabled = capacity > 0
                self._events = deque(self._events, maxlen=max(1, capacity))
            if dump_dir is not None:
                self.dump_dir = dump_dir
            if fingerprint is not None:
                self._fingerprint = dict(fingerprint)

    # -- recording ----------------------------------------------------------
    def record(self, kind: str, **fields: Any) -> None:
        """Append one event; cheap enough for per-dispatch call sites."""
        if not self._enabled:
            return
        event = {"ts": time.time(), "kind": kind, **fields}
        with self._lock:
            self._events.append(event)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._dumped.clear()

    # -- postmortem ---------------------------------------------------------
    def bundle(self, reason: str, error: str = "") -> Dict[str, Any]:
        """The postmortem payload: everything a dead process can no longer
        serve over HTTP, in one JSON-safe dict."""
        from . import trace as _trace
        from .metrics import REGISTRY, dtraces_snapshot

        try:
            traces = _trace.TRACER.export()
        except Exception as e:  # a corrupt ring must not block the dump
            traces = {"error": str(e)}
        try:
            metrics = REGISTRY.expose()
        except Exception as e:
            metrics = f"# exposition failed: {e}"
        bundle = {
            "schema": "dct-postmortem-v1",
            "reason": reason,
            "error": error,
            "written_at": time.time(),
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "config": dict(self._fingerprint),
            "flight": self.events(),
            "traces": traces,
            "metrics": metrics,
        }
        # Assembled distributed traces when this process runs a trace
        # collector (the orchestrator): the cross-process timeline is the
        # single most valuable postmortem artifact — a dead coordinator's
        # /dtraces can no longer be scraped.
        dtraces = dtraces_snapshot()
        if dtraces is not None:
            bundle["dtraces"] = dtraces
        # Watchtower surfaces: the alert lifecycle log and the recent
        # rolling series — "what was trending before the crash" is
        # exactly the question a postmortem reader asks first
        # (tools/postmortem.py renders both).
        from .metrics import alerts_snapshot

        alerts = alerts_snapshot()
        if alerts is not None:
            bundle["alerts"] = alerts
        # The autoscaler's decision log: "what did the control plane do
        # before the crash" — scale decisions next to the alerts that
        # triggered them (tools/postmortem.py renders the pairing).
        from .metrics import autoscaler_snapshot

        autoscaler = autoscaler_snapshot()
        if autoscaler is not None:
            bundle["autoscaler"] = autoscaler
        # Streaming-clustering state: a dead cluster worker's /clusters
        # (sizes, inertia trend, resume step) tells the reader whether
        # the centroid model was healthy when the process died.
        from .metrics import clusters_snapshot

        clusters = clusters_snapshot()
        if clusters is not None:
            bundle["clusters"] = clusters
        # Partitioned-bus shard table: which shard was dead/parked (and
        # how deep its outbox ran) when this process went down — the
        # first question after a sharded control-plane incident.
        from .metrics import shards_snapshot

        shards = shards_snapshot()
        if shards is not None:
            bundle["bus_shards"] = shards
        # Tenant accounting + error budgets: who was spending the chips
        # and whose budget was burning when this process went down — the
        # attribution question a multi-workload postmortem opens with.
        from .metrics import tenants_snapshot

        tenants = tenants_snapshot()
        if tenants is not None:
            bundle["tenants"] = tenants
        # The structured-log ring: the last WARNING+ records with their
        # trace_id correlation — the complaints right before the crash,
        # even when stderr scrolled away.
        from .metrics import logs_snapshot

        logs = logs_snapshot()
        if logs is not None:
            bundle["logs"] = logs
        try:
            from . import timeseries as _timeseries

            # Bounded like the flight/span rings: only the last few
            # minutes of history — a long-lived fleet's full store
            # would balloon the crash-path write, and the renderer
            # shows the pre-crash trend, not the epoch.
            ts = _timeseries.STORE.snapshot(since_s=180.0)
            if ts.get("series"):
                bundle["timeseries"] = ts
        except Exception as e:
            logger.debug("timeseries bundle capture failed: %s", e)
        return bundle

    def dump(self, reason: str, error: str = "",
             dump_dir: str = "") -> Optional[str]:
        """Write the bundle; returns the path, or None when no dump dir is
        configured / the write fails (a postmortem must never raise into
        the crash path that triggered it).  Per-reason dedup: an exception
        that unwinds through both ``threading.excepthook`` and the SIGTERM
        teardown produces ONE bundle, not a cascade."""
        target = dump_dir or self.dump_dir
        if not target:
            return None
        with self._lock:
            if reason in self._dumped:
                return None
            self._dumped[reason] = time.time()
        try:
            os.makedirs(target, exist_ok=True)
            stamp = time.strftime("%Y%m%d%H%M%S", time.gmtime())
            path = os.path.join(
                target, f"postmortem_{stamp}_{os.getpid()}_{reason}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.bundle(reason, error=error), f,
                          ensure_ascii=False, default=str)
            os.replace(tmp, path)  # atomic: no half-written bundles
        except Exception as e:
            logger.error("postmortem dump failed: %s", e)
            return None
        logger.warning("postmortem bundle written", extra={
            "path": path, "reason": reason})
        return path


RECORDER = FlightRecorder()

# Module-level conveniences bound to the process-wide recorder.
record = RECORDER.record
configure = RECORDER.configure
dump = RECORDER.dump

_installed = False
_fault_log = None  # keep the faulthandler file object referenced


def install(dump_dir: str, recorder: FlightRecorder = RECORDER) -> None:
    """Arm the crash hooks: excepthooks dump a JSON bundle; faulthandler
    covers fatal signals with a native-traceback file.  Idempotent —
    installing twice (orchestrator + an embedded worker) chains once."""
    global _installed, _fault_log
    recorder.configure(dump_dir=dump_dir)
    if _installed:
        return
    _installed = True

    prev_sys = sys.excepthook

    def _sys_hook(exc_type, exc, tb):
        recorder.dump("unhandled_exception",
                      error=f"{exc_type.__name__}: {exc}")
        prev_sys(exc_type, exc, tb)

    sys.excepthook = _sys_hook

    prev_thread = threading.excepthook

    def _thread_hook(args):
        if args.exc_type is not SystemExit:
            recorder.dump(
                "unhandled_exception",
                error=f"{args.exc_type.__name__}: {args.exc_value} "
                      f"(thread {getattr(args.thread, 'name', '?')})")
        prev_thread(args)

    threading.excepthook = _thread_hook

    try:
        import faulthandler

        os.makedirs(dump_dir, exist_ok=True)
        _fault_log = open(os.path.join(dump_dir, "fatal_signal.log"), "a",
                          encoding="utf-8")
        faulthandler.enable(file=_fault_log)
    except Exception as e:  # faulthandler is best-effort armor
        logger.warning("faulthandler arming failed: %s", e)
