"""Device-occupancy accounting: how busy the chip actually was.

The engine's one-deep software pipeline (`inference/engine.py`: dispatch
is async, so batch i+1's host-side pack overlaps batch i's device time)
makes every host span a lie about the device: ``engine.compute`` is just
the dispatch call, and the batch-latency histogram's window deliberately
contains the NEXT batch's host work.  The MFU meter (`utils/costmodel.py`)
answers "how many FLOP/s over the wall window" but cannot split a low
number into *device idle* vs *slow kernels*.  This module holds the
missing primitives:

- :class:`DeviceTimeline` — per-batch device intervals bounded by the
  async dispatch and the readback completion (the only two device-side
  edges the host can observe without a profiler).  From the rolling
  interval window it derives
  ``tpu_engine_device_busy_fraction`` (union of intervals over wall),
  ``tpu_engine_overlap_fraction`` (how much of the dispatched device
  time overlapped other host/device work — the pipelining actually
  achieved), and ``tpu_engine_pipeline_bubble_ms_total`` (device idle
  gaps BETWEEN batches of one stream: the host couldn't feed the chip —
  notably the serial tokenize→dispatch gap between coalesce groups).
  Gaps across stream boundaries (no queued work at all) are idle, not
  bubbles — the worker feed loops call ``start_stream()`` whenever
  their queue runs dry, so only gaps with work waiting score.
  **Mesh semantics**: one recorded interval is one HOST dispatch — on a
  data-parallel mesh that single dispatch covers ``n_devices`` chips
  executing the same program in lockstep (SPMD), so busy/overlap/bubble
  here describe the WHOLE mesh's shared envelope, not any chip alone (a
  host-bound feed starves all N chips together, and one bubble
  millisecond costs N chip-milliseconds).  Timelines carry their
  ``n_devices`` in every snapshot (plus the chip-weighted
  ``bubble_chip_ms_*`` twins) so the PR-9 occupancy meters stay
  meaningful as chips are added; per-chip *goodput* differences live in
  `utils/costmodel.EfficiencyMeter`'s ``per_chip`` rows, which see each
  chip's real-vs-pad row split.
- :class:`QueueDepthSampler` — a time-weighted queue-depth gauge.  The
  old edge-triggered ``m_queue_depth.set(qsize)`` only moved when a
  batch was enqueued/dequeued, so a scrape between edges aliased to
  whatever the last edge left behind (a queue that oscillates 0↔64
  between scrapes reads as flat 0).  The sampler integrates depth over
  time and exposes the window's time-weighted mean — what the queue
  depth WAS, not what it happened to be at the last edge.

Everything is host-side bookkeeping on ``time.perf_counter`` /
``time.monotonic``; nothing here touches jax.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .metrics import REGISTRY, MetricsRegistry


def merged_length(intervals: List[Tuple[float, float]]) -> float:
    """Total length of the union of (start, end) intervals."""
    if not intervals:
        return 0.0
    merged = 0.0
    cur_s, cur_e = None, None
    for s, e in sorted(intervals):
        if e <= s:
            continue
        if cur_s is None:
            cur_s, cur_e = s, e
        elif s <= cur_e:
            cur_e = max(cur_e, e)
        else:
            merged += cur_e - cur_s
            cur_s, cur_e = s, e
    if cur_s is not None:
        merged += cur_e - cur_s
    return merged


class DeviceTimeline:
    """Rolling window of device intervals + derived occupancy gauges.

    One interval per device batch: ``record(start, end)`` where ``start``
    is the async-dispatch wall (the engine's ``t0``) and ``end`` the
    moment the batch's results landed on host (the readback sync).  The
    readback end is an *upper bound* on when the device finished — the
    honest host-observable envelope, stated as such in /costs.

    ``start_stream()`` marks the next recorded interval as the first of
    a new dispatch stream: the gap before it is idle (no work offered),
    never a pipeline bubble.  Within a stream, any gap between one
    batch's readback and the next batch's dispatch is device time the
    host failed to cover — the bubble the continuous-batching feed
    exists to remove.
    """

    def __init__(self, registry: MetricsRegistry = REGISTRY,
                 window_s: float = 60.0, max_intervals: int = 2048,
                 clock=time.perf_counter, path: str = "text",
                 n_devices: int = 1):
        """``path`` labels this timeline's gauge/counter children
        ("text" for the embed+classify engine, "asr" for Whisper — the
        compile-miss counter's convention), so shared-process rigs with
        both pipelines never clobber one unlabeled series.

        ``n_devices`` is how many chips one recorded dispatch spans (the
        engine's mesh size; 1 single-device).  It does not change the
        fractions — SPMD chips share one envelope — but it labels every
        snapshot and scales the chip-weighted bubble twins, so a reader
        comparing occupancy across mesh sizes knows what one host
        interval covered."""
        self.window_s = window_s
        self.n_devices = max(1, int(n_devices))
        self._clock = clock
        self._lock = threading.Lock()
        self._intervals: "deque[Tuple[float, float]]" = \
            deque(maxlen=max_intervals)
        self._bubbles: "deque[Tuple[float, float]]" = \
            deque(maxlen=max_intervals)  # (at, bubble_s)
        self._prev_end: Optional[float] = None
        self._new_stream = True
        self._batches_total = 0
        self._bubble_s_total = 0.0
        self.m_busy = registry.gauge(
            "tpu_engine_device_busy_fraction",
            "rolling fraction of wall time with a device batch in flight "
            "(dispatch->readback union; readback is an upper bound on "
            "device-busy end)").labels(path=path)
        self.m_overlap = registry.gauge(
            "tpu_engine_overlap_fraction",
            "rolling fraction of dispatched device time that overlapped "
            "other in-flight work (the host/device pipelining achieved; "
            "0 = fully serial)").labels(path=path)
        self.m_bubble = registry.counter(
            "tpu_engine_pipeline_bubble_ms_total",
            "device idle between consecutive batches of one dispatch "
            "stream (the host failed to keep the chip fed), "
            "cumulative").labels(path=path)

    # -- recording -----------------------------------------------------------
    def reset(self) -> None:
        """Forget everything recorded so far (warmup exclusion: compile-
        dominated bring-up intervals must not score as serving busy time
        or bubbles)."""
        with self._lock:
            self._intervals.clear()
            self._bubbles.clear()
            self._prev_end = None
            self._new_stream = True
            self._batches_total = 0
            self._bubble_s_total = 0.0
        self.m_busy.set(0.0)
        self.m_overlap.set(0.0)

    def start_stream(self) -> None:
        """The next interval opens a new dispatch stream: the gap before
        it is idle-by-absence-of-work, not a bubble."""
        with self._lock:
            self._new_stream = True

    def record(self, start: float, end: float) -> None:
        """Account one device batch's [dispatch, readback-complete]
        interval (both on this timeline's clock, default perf_counter).
        O(1) on the serving hot path: the derived fractions are computed
        by :meth:`snapshot` (/costs scrapes + telemetry heartbeats), not
        here — recomputing the interval union per batch would spend the
        very inter-batch gap this module scores as bubble."""
        if end < start:
            start, end = end, start
        with self._lock:
            bubble = 0.0
            if not self._new_stream and self._prev_end is not None:
                bubble = max(0.0, start - self._prev_end)
            self._new_stream = False
            self._prev_end = max(self._prev_end or end, end)
            self._intervals.append((start, end))
            self._batches_total += 1
            if bubble > 0:
                self._bubbles.append((end, bubble))
                self._bubble_s_total += bubble
        if bubble > 0:
            self.m_bubble.inc(bubble * 1000.0)

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._intervals and self._intervals[0][1] < cutoff:
            self._intervals.popleft()
        while self._bubbles and self._bubbles[0][0] < cutoff:
            self._bubbles.popleft()

    # -- derived signals -----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The /costs ``occupancy`` map, refreshing the gauges as a side
        effect (heartbeat calls decay the fractions to 0 on an idle
        stream instead of freezing the last busy window's values).
        {} until the first batch ever lands."""
        now = self._clock()
        with self._lock:
            if not self._batches_total:
                return {}
            self._prune(now)
            intervals = list(self._intervals)
            bubble_window = sum(b for _, b in self._bubbles)
            batches_total = self._batches_total
            bubble_total = self._bubble_s_total
        union = merged_length(intervals)
        total = sum(e - s for s, e in intervals)
        # Window span: oldest interval start to now, clamped into the
        # configured window; floored by the union so a single just-landed
        # batch can't divide by ~0 wall.
        span = max(min(now - intervals[0][0], self.window_s), union, 1e-9) \
            if intervals else max(self.window_s, 1e-9)
        busy = union / span if intervals else 0.0
        overlap = (total - union) / total if total > 0 else 0.0
        active = union + bubble_window
        out = {
            "window_s": round(span, 3),
            "batches": len(intervals),
            "busy_fraction": round(busy, 6),
            "overlap_fraction": round(overlap, 6),
            "bubble_ms_window": round(bubble_window * 1000.0, 3),
            "bubble_share": round(bubble_window / active, 6)
            if active > 0 else 0.0,
            "bubble_ms_total": round(bubble_total * 1000.0, 3),
            "bubble_ms_per_batch": round(
                bubble_total * 1000.0 / batches_total, 4),
            "batches_total": batches_total,
            # Mesh labeling: one host interval = n_devices chips in
            # lockstep; the chip-weighted twin prices a bubble in
            # chip-milliseconds (1 ms of host gap idles N chips).
            "n_devices": self.n_devices,
            "bubble_chip_ms_window": round(
                bubble_window * 1000.0 * self.n_devices, 3),
            "bubble_chip_ms_total": round(
                bubble_total * 1000.0 * self.n_devices, 3),
        }
        self.m_busy.set(out["busy_fraction"])
        self.m_overlap.set(out["overlap_fraction"])
        return out


class QueueDepthSampler:
    """Time-weighted queue-depth over a rolling window.

    ``update(depth)`` records an edge (enqueue/dequeue) AND refreshes
    the gauge with the window's exact time-weighted mean — amortized
    O(1): a running sum of closed inter-edge segments (each edge is
    added once on append and subtracted once when it ages out) plus the
    left-boundary and live-tail segments computed directly.  Call
    ``sample()`` from the heartbeat loop too, so a queue that went
    quiet (no edges) still decays instead of freezing the last mean.
    """

    def __init__(self, gauge, window_s: float = 60.0,
                 clock=time.monotonic, max_events: int = 4096):
        self.gauge = gauge
        self.window_s = window_s
        self._clock = clock
        self._lock = threading.Lock()
        # (t, depth) transitions; _floor_depth is the depth in force just
        # before the oldest retained transition (pruning keeps the
        # integral exact at the window's left edge).  _seg_sum is
        # Σ depth_i · (t_{i+1} − t_i) over consecutive RETAINED pairs.
        self._events: "deque[Tuple[float, float]]" = deque()
        self._max_events = max(2, int(max_events))
        self._seg_sum = 0.0
        self._floor_depth = 0.0
        self._last_depth = 0.0

    def update(self, depth: int) -> None:
        now = self._clock()
        with self._lock:
            self._prune(now)
            if self._events:
                self._seg_sum += self._events[-1][1] \
                    * (now - self._events[-1][0])
            self._events.append((now, float(depth)))
            self._last_depth = float(depth)
            value = self._mean_locked(now)
        self._set(value)

    def current(self) -> float:
        with self._lock:
            return self._last_depth

    def sample(self) -> float:
        """Time-weighted mean depth over the window; refreshes the gauge
        (the heartbeat-side decay path for edge-quiet queues)."""
        now = self._clock()
        with self._lock:
            self._prune(now)
            value = self._mean_locked(now)
        return self._set(value)

    def _set(self, value: float) -> float:
        if self.gauge is not None:
            self.gauge.set(round(value, 4))
        return value

    def _prune(self, now: float) -> None:
        """Expire edges older than the window (and enforce the bound);
        each edge is popped exactly once, so the cost amortizes O(1)."""
        cutoff = now - self.window_s
        while self._events and (self._events[0][0] <= cutoff
                                or len(self._events) > self._max_events):
            t0, d0 = self._events.popleft()
            if self._events:
                # Callers (update/sample) hold self._lock around every
                # _prune call; the write is lock-guarded at the call site.
                self._seg_sum -= d0 * (self._events[0][0] - t0)  # crawlint: disable=LCK001
            self._floor_depth = d0

    def _mean_locked(self, now: float) -> float:
        if not self._events:
            return self._last_depth  # constant since before the window
        cutoff = now - self.window_s
        head_t = self._events[0][0]
        tail_t, tail_d = self._events[-1]
        total = (self._floor_depth * max(0.0, head_t - cutoff)
                 + self._seg_sum + tail_d * (now - tail_t))
        span = now - cutoff
        return total / span if span > 0 else self._last_depth
