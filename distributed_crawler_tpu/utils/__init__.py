"""Shared utilities: structured logging, time parsing, metrics, span
tracing, file janitor."""

from .timeparse import parse_date_between, parse_duration, parse_time_ago

__all__ = ["parse_time_ago", "parse_date_between", "parse_duration"]
