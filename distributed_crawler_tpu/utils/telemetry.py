"""Telemetry snapshots: what a heartbeat carries beyond "alive".

`StatusMessage.resource_usage` has existed since the first heartbeat but
was minted empty everywhere (the reference never filled it either), so the
only fleet-wide questions the orchestrator could answer were "alive?" and
"queue length?".  This module is the fill: a cheap, never-raising snapshot
of the process and device state that matters at TPU-serving scale —

- process RSS (``/proc/self/statm``; peak-RSS fallback off Linux),
- JAX per-device memory stats (``device.memory_stats()``, guarded: the CPU
  backend returns None/raises, and jax is only queried when the process
  already imported it — a crawl worker never pays the import),
- compile-cache activity deltas (engine ``compile_cache_stats()``): a
  nonzero delta between heartbeats means live batches paid XLA compiles,
- the engine's rolling efficiency meters (MFU, goodput tokens/s, padding
  density — `utils/costmodel.py`) when the engine exposes them,
- labeled-counter counts (e.g. batch outcomes by ok/error/requeued),
- a per-stage latency digest (p50/p95/max per span name) over the spans
  completed since the previous snapshot, computed from the PR-2 trace ring.

The snapshot is a plain nested dict of JSON-safe scalars, so it round-trips
through both bus transports unchanged and lands in the orchestrator's
FleetView (`orchestrator/fleet.py`) / the `/cluster` endpoint verbatim.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from . import trace as _trace

logger = logging.getLogger("dct.telemetry")

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def process_rss_bytes() -> int:
    """Resident set size of this process; 0 when unknowable."""
    try:
        with open("/proc/self/statm", "r", encoding="ascii") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        pass
    try:  # macOS/BSD fallback: peak RSS (bytes on mac, KiB elsewhere)
        import resource

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return peak if sys.platform == "darwin" else peak * 1024
    except (ImportError, OSError, AttributeError, ValueError):
        return 0


def device_memory_stats() -> List[Dict[str, Any]]:
    """Per-device memory stats from an ALREADY-IMPORTED jax; [] otherwise.

    Importing jax here would make every crawl worker's heartbeat pay the
    multi-second import, so only processes that already run device code
    (the TPU worker imported jax long before the first heartbeat) report
    device memory.  The CPU backend's ``memory_stats()`` returns None (or
    the attribute is missing entirely) — both degrade to [].
    """
    jax = sys.modules.get("jax")
    if jax is None:
        return []
    out: List[Dict[str, Any]] = []
    try:
        for dev in jax.devices():
            stats_fn = getattr(dev, "memory_stats", None)
            stats = stats_fn() if callable(stats_fn) else None
            if not stats:
                continue
            out.append({
                "device": f"{dev.platform}:{dev.id}",
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "bytes_limit": int(stats.get("bytes_limit", 0)),
                "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
            })
    except Exception as e:  # backends without stats must not break beats
        logger.debug("device memory stats unavailable: %s", e)
        return []
    return out


class TelemetryEmitter:
    """Stateful snapshot source: one per heartbeat loop.

    Statefulness is what turns cumulative counters into the *deltas* the
    fleet view wants ("did compiles happen since the last heartbeat?"),
    and bounds the latency digest to spans completed since the previous
    snapshot instead of re-digesting the whole ring forever.
    """

    def __init__(self, engine=None, counters: Optional[Dict[str, Any]] = None,
                 include_device: bool = False, tracer=None):
        """``engine`` is anything with ``compile_cache_stats()``;
        ``counters`` maps a telemetry key to a labeled
        `utils.metrics.Counter` whose per-label values are reported (e.g.
        ``{"batch_outcomes": worker.m_outcomes}``)."""
        self.engine = engine
        self.counters = dict(counters or {})
        self.include_device = include_device
        self.tracer = tracer or _trace.TRACER
        self._lock = threading.Lock()
        self._last_wall = 0.0
        self._last_compile_misses: Optional[float] = None

    def snapshot(self) -> Dict[str, Any]:
        """One heartbeat's worth of telemetry; never raises."""
        try:
            return self._snapshot()
        except Exception as e:  # telemetry must never break a heartbeat
            logger.debug("telemetry snapshot degraded: %s", e)
            return {"rss_bytes": process_rss_bytes()}

    def _snapshot(self) -> Dict[str, Any]:
        now = time.time()
        with self._lock:
            since, self._last_wall = self._last_wall, now
        out: Dict[str, Any] = {
            "rss_bytes": process_rss_bytes(),
            "py_threads": threading.active_count(),
        }
        if self.include_device:
            mem = device_memory_stats()
            if mem:
                out["device_memory"] = mem
        if self.engine is not None:
            stats_fn = getattr(self.engine, "compile_cache_stats", None)
            if callable(stats_fn):
                stats = dict(stats_fn())
                misses = float(stats.get("misses_total", 0.0))
                with self._lock:
                    prev = self._last_compile_misses
                    self._last_compile_misses = misses
                stats["misses_delta"] = \
                    misses - prev if prev is not None else misses
                out["compile_cache"] = stats
            eff_fn = getattr(self.engine, "efficiency_snapshot", None)
            if callable(eff_fn):
                # Rolling MFU/goodput/padding-density from the engine's
                # EfficiencyMeter (`utils/costmodel.py`) — {} until the
                # first batch, so idle workers don't heartbeat zeros.
                eff = eff_fn()
                if eff:
                    out["efficiency"] = eff
            occ_fn = getattr(self.engine, "occupancy_snapshot", None)
            if callable(occ_fn):
                # Device occupancy (`utils/occupancy.py`): busy/overlap
                # fractions + bubble accounting.  This per-beat call is
                # ALSO what keeps the occupancy gauges fresh on plain
                # /metrics scrapes — the hot path records intervals but
                # never derives (O(1) by design).
                occ = occ_fn()
                if occ:
                    out["occupancy"] = occ
        for key, counter in self.counters.items():
            series = getattr(counter, "series", None)
            if not callable(series):
                continue
            values: Dict[str, float] = {}
            for labels, value in series():
                if not labels:
                    continue  # the unlabeled parent is the redundant total
                values["|".join(str(v) for v in labels.values())] = value
            out[key] = values
        digest = _trace.latency_digest(self.tracer.spans(), since_wall=since)
        if digest:
            out["latency_ms"] = digest
        return out
