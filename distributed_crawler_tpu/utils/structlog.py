"""Structured logging: zerolog-equivalent tagged JSON log lines.

Parity with the reference's zerolog usage (`main.go:56,186-200`): level from
config, console or JSON writer, Unix timestamps, and greppable ``log_tag``
domain streams (``rw_pool``, ``rw_channel``, ``rw_lookup_stats``, ...).
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any, Dict

from . import trace as _trace

_RESERVED = set(logging.LogRecord("", 0, "", 0, "", (), None).__dict__) | {"message", "asctime"}


def _trace_fields() -> Dict[str, str]:
    """trace_id/span of the innermost open span on this thread, if any —
    log lines emitted inside a span join the /traces timeline without
    callers threading ids by hand.  Explicit extras win (setdefault)."""
    tid = _trace.current_trace_id()
    if not tid:
        return {}
    out = {"trace_id": tid}
    name = _trace.current_span_name()
    if name:
        out["span"] = name
    return out


class JsonFormatter(logging.Formatter):
    """One JSON object per line, zerolog-style: level, ts (unix), message, extras."""

    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, Any] = {
            "level": record.levelname.lower(),
            "ts": int(time.time()),
            "logger": record.name,
            "message": record.getMessage(),
        }
        for k, v in record.__dict__.items():
            if k not in _RESERVED and not k.startswith("_"):
                out[k] = v
        for k, v in _trace_fields().items():
            out.setdefault(k, v)
        if record.exc_info and record.exc_info[0] is not None:
            out["error"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False, default=str)


class ConsoleFormatter(logging.Formatter):
    """Human console writer with inline key=value extras."""

    def format(self, record: logging.LogRecord) -> str:
        fields = {k: v for k, v in record.__dict__.items()
                  if k not in _RESERVED and not k.startswith("_")}
        for k, v in _trace_fields().items():
            fields.setdefault(k, v)
        extras = " ".join(f"{k}={v}" for k, v in fields.items())
        base = f"{self.formatTime(record, '%H:%M:%S')} {record.levelname:<5} {record.name}: {record.getMessage()}"
        return f"{base} {extras}" if extras else base


def setup_logging(level: str = "info", json_output: bool = False,
                  stream=None) -> logging.Logger:
    """Configure the 'dct' logger tree; returns the root 'dct' logger."""
    logger = logging.getLogger("dct")
    logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    logger.handlers.clear()
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(JsonFormatter() if json_output else ConsoleFormatter())
    logger.addHandler(handler)
    logger.propagate = False
    return logger


def tagged(logger: logging.Logger, log_tag: str, **fields) -> "logging.LoggerAdapter":
    """A LoggerAdapter that stamps every record with a log_tag domain stream."""
    merged = {"log_tag": log_tag, **fields}

    class _Adapter(logging.LoggerAdapter):
        def process(self, msg, kwargs):
            extra = dict(merged)
            extra.update(kwargs.get("extra") or {})
            kwargs["extra"] = extra
            return msg, kwargs

    return _Adapter(logger, merged)
