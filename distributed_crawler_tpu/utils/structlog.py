"""Structured logging: zerolog-equivalent tagged JSON log lines.

Parity with the reference's zerolog usage (`main.go:56,186-200`): level from
config, console or JSON writer, Unix timestamps, and greppable ``log_tag``
domain streams (``rw_pool``, ``rw_channel``, ``rw_lookup_stats``, ...).
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from . import trace as _trace

_RESERVED = set(logging.LogRecord("", 0, "", 0, "", (), None).__dict__) | {"message", "asctime"}


def _trace_fields() -> Dict[str, str]:
    """trace_id/span of the innermost open span on this thread, if any —
    log lines emitted inside a span join the /traces timeline without
    callers threading ids by hand.  Explicit extras win (setdefault)."""
    tid = _trace.current_trace_id()
    if not tid:
        return {}
    out = {"trace_id": tid}
    name = _trace.current_span_name()
    if name:
        out["span"] = name
    return out


class JsonFormatter(logging.Formatter):
    """One JSON object per line, zerolog-style: level, ts (unix), message, extras."""

    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, Any] = {
            "level": record.levelname.lower(),
            "ts": int(time.time()),
            "logger": record.name,
            "message": record.getMessage(),
        }
        for k, v in record.__dict__.items():
            if k not in _RESERVED and not k.startswith("_"):
                out[k] = v
        for k, v in _trace_fields().items():
            out.setdefault(k, v)
        if record.exc_info and record.exc_info[0] is not None:
            out["error"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False, default=str)


class ConsoleFormatter(logging.Formatter):
    """Human console writer with inline key=value extras."""

    def format(self, record: logging.LogRecord) -> str:
        fields = {k: v for k, v in record.__dict__.items()
                  if k not in _RESERVED and not k.startswith("_")}
        for k, v in _trace_fields().items():
            fields.setdefault(k, v)
        extras = " ".join(f"{k}={v}" for k, v in fields.items())
        base = f"{self.formatTime(record, '%H:%M:%S')} {record.levelname:<5} {record.name}: {record.getMessage()}"
        return f"{base} {extras}" if extras else base


# ---------------------------------------------------------------------------
# Bounded WARNING+ ring (ISSUE 17): the last N structured records kept
# in-process and served at /logs on the metrics port.  stderr scrolls
# away and journald is not always there; the ring answers "what did this
# process complain about right before the incident" over HTTP and rides
# along in postmortem bundles.  trace_id correlation comes from the same
# `_trace_fields()` seam the formatters use, so a ring record links to
# its /traces timeline.

_RING_CAPACITY = 256


class RingHandler(logging.Handler):
    """Keep the last ``capacity`` WARNING+ records as plain dicts."""

    def __init__(self, capacity: int = _RING_CAPACITY):
        super().__init__(level=logging.WARNING)
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._ring_lock = threading.Lock()

    def emit(self, record: logging.LogRecord) -> None:
        try:
            entry: Dict[str, Any] = {
                "level": record.levelname.lower(),
                "ts": round(record.created, 3),
                "logger": record.name,
                "message": record.getMessage(),
            }
            for k, v in record.__dict__.items():
                if k not in _RESERVED and not k.startswith("_"):
                    entry[k] = v
            for k, v in _trace_fields().items():
                entry.setdefault(k, v)
            if record.exc_info and record.exc_info[0] is not None:
                entry["error"] = self.format(record) if self.formatter \
                    else logging.Formatter().formatException(record.exc_info)
            with self._ring_lock:
                self._ring.append(entry)
        except Exception:  # never let telemetry break the caller
            self.handleError(record)

    def snapshot(self, limit: int = 0) -> List[Dict[str, Any]]:
        with self._ring_lock:
            records = list(self._ring)
        if limit and limit > 0:
            records = records[-limit:]
        return records


_ring_handler: Optional[RingHandler] = None
_ring_install_lock = threading.Lock()


def install_ring_handler(capacity: int = _RING_CAPACITY) -> RingHandler:
    """Attach the process-wide WARNING+ ring to the 'dct' logger tree.
    Idempotent: repeat calls return the existing ring (the buffer
    survives `setup_logging` re-running on the same process)."""
    global _ring_handler
    with _ring_install_lock:
        if _ring_handler is None:
            _ring_handler = RingHandler(capacity)
        logger = logging.getLogger("dct")
        if _ring_handler not in logger.handlers:
            logger.addHandler(_ring_handler)
        return _ring_handler


def uninstall_ring_handler() -> Optional[RingHandler]:
    """Detach the ring from the 'dct' logger tree and forget it; returns
    the detached handler (None when nothing was installed).  Pair with
    ``reinstall_ring_handler`` — ``install_ring_handler`` after an
    uninstall would start a fresh empty ring, dropping the buffer."""
    global _ring_handler
    with _ring_install_lock:
        handler = _ring_handler
        _ring_handler = None
        if handler is not None:
            logging.getLogger("dct").removeHandler(handler)
        return handler


def reinstall_ring_handler(handler: Optional[RingHandler]) -> None:
    """Reattach a handler returned by ``uninstall_ring_handler``, records
    intact.  No-op on None, so save/restore composes unconditionally."""
    if handler is None:
        return
    global _ring_handler
    with _ring_install_lock:
        _ring_handler = handler
        logger = logging.getLogger("dct")
        if handler not in logger.handlers:
            logger.addHandler(handler)


def ring_snapshot(limit: int = 0) -> List[Dict[str, Any]]:
    """The ring's records oldest-first ([] before install / when quiet);
    ``limit`` keeps only the newest N.  This is the /logs body."""
    handler = _ring_handler
    if handler is None:
        return []
    return handler.snapshot(limit=limit)


def setup_logging(level: str = "info", json_output: bool = False,
                  stream=None) -> logging.Logger:
    """Configure the 'dct' logger tree; returns the root 'dct' logger."""
    logger = logging.getLogger("dct")
    logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    logger.handlers.clear()
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(JsonFormatter() if json_output else ConsoleFormatter())
    logger.addHandler(handler)
    logger.propagate = False
    # handlers.clear() above dropped the ring; re-attach so the WARNING+
    # buffer keeps feeding /logs across logging re-configuration.
    install_ring_handler()
    return logger


def tagged(logger: logging.Logger, log_tag: str, **fields) -> "logging.LoggerAdapter":
    """A LoggerAdapter that stamps every record with a log_tag domain stream."""
    merged = {"log_tag": log_tag, **fields}

    class _Adapter(logging.LoggerAdapter):
        def process(self, msg, kwargs):
            extra = dict(merged)
            extra.update(kwargs.get("extra") or {})
            kwargs["extra"] = extra
            return msg, kwargs

    return _Adapter(logger, merged)
