"""Structured logging: zerolog-equivalent tagged JSON log lines.

Parity with the reference's zerolog usage (`main.go:56,186-200`): level from
config, console or JSON writer, Unix timestamps, and greppable ``log_tag``
domain streams (``rw_pool``, ``rw_channel``, ``rw_lookup_stats``, ...).
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any, Dict

_RESERVED = set(logging.LogRecord("", 0, "", 0, "", (), None).__dict__) | {"message", "asctime"}


class JsonFormatter(logging.Formatter):
    """One JSON object per line, zerolog-style: level, ts (unix), message, extras."""

    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, Any] = {
            "level": record.levelname.lower(),
            "ts": int(time.time()),
            "logger": record.name,
            "message": record.getMessage(),
        }
        for k, v in record.__dict__.items():
            if k not in _RESERVED and not k.startswith("_"):
                out[k] = v
        if record.exc_info and record.exc_info[0] is not None:
            out["error"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False, default=str)


class ConsoleFormatter(logging.Formatter):
    """Human console writer with inline key=value extras."""

    def format(self, record: logging.LogRecord) -> str:
        extras = " ".join(
            f"{k}={v}" for k, v in record.__dict__.items()
            if k not in _RESERVED and not k.startswith("_")
        )
        base = f"{self.formatTime(record, '%H:%M:%S')} {record.levelname:<5} {record.name}: {record.getMessage()}"
        return f"{base} {extras}" if extras else base


def setup_logging(level: str = "info", json_output: bool = False,
                  stream=None) -> logging.Logger:
    """Configure the 'dct' logger tree; returns the root 'dct' logger."""
    logger = logging.getLogger("dct")
    logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    logger.handlers.clear()
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(JsonFormatter() if json_output else ConsoleFormatter())
    logger.addHandler(handler)
    logger.propagate = False
    return logger


def tagged(logger: logging.Logger, log_tag: str, **fields) -> "logging.LoggerAdapter":
    """A LoggerAdapter that stamps every record with a log_tag domain stream."""
    merged = {"log_tag": log_tag, **fields}

    class _Adapter(logging.LoggerAdapter):
        def process(self, msg, kwargs):
            extra = dict(merged)
            extra.update(kwargs.get("extra") or {})
            kwargs["extra"] = extra
            return msg, kwargs

    return _Adapter(logger, merged)
