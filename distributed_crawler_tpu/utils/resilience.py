"""One resiliency policy layer: retry/backoff, timeouts, circuit breakers.

The reference crawler never hand-rolled a retry loop: every sidecar call
went through Dapr's *declarative* resiliency spec (retries with
exponential backoff, per-op timeouts, circuit breakers with half-open
probes — `resiliency.yaml` in the reference deployment).  Our port had
grown at least three ad-hoc re-implementations (the gRPC bus's local
dispatch loop, FLOOD_WAIT sleeps in the crawl runner, the orchestrator's
per-page retry counters) and no breaker anywhere: a wedged state backend
turned into an error storm instead of a degraded-but-alive coordinator.

This module is the single place policy lives:

- :class:`RetryPolicy` — declarative jittered exponential backoff with an
  optional retryable-error predicate and support for **server-directed
  backoff hints**: an exception carrying a ``retry_after_s`` attribute
  (e.g. `clients.errors.FloodWaitError`) overrides the computed delay,
  capped by ``retry_after_cap_s`` so one hostile hint can't park a
  dispatch thread for minutes.
- :class:`CircuitBreaker` — closed → open after ``failure_threshold``
  consecutive failures; open → half-open after ``recovery_timeout_s``;
  a bounded number of half-open probes decides re-close vs re-open.
  Every transition updates ``resilience_circuit_state{target}`` and is
  flight-recorded, so postmortems show the breaker history next to the
  crash.
- :class:`Policy` / :func:`with_policy` — retry + breaker + per-attempt
  timeout composed behind one ``call``; the orchestrator applies it to
  state-store ops and bus publishes, the crawl worker to fetches.
- :func:`retry_call` — the functional form the bus transports use in
  their dispatch loops (stop-event-aware waits, no breaker).

Metrics: ``resilience_retries_total{op}`` counts every retried attempt;
``resilience_circuit_state{target}`` is 0 closed, 0.5 half-open, 1 open.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from dataclasses import dataclass
from typing import Any, Callable, Optional

from . import flight
from .metrics import REGISTRY, MetricsRegistry

logger = logging.getLogger("dct.resilience")

CIRCUIT_CLOSED = "closed"
CIRCUIT_OPEN = "open"
CIRCUIT_HALF_OPEN = "half_open"

_STATE_VALUE = {CIRCUIT_CLOSED: 0.0, CIRCUIT_HALF_OPEN: 0.5,
                CIRCUIT_OPEN: 1.0}


class CircuitOpenError(RuntimeError):
    """Raised instead of attempting an op whose breaker is open."""

    def __init__(self, target: str):
        super().__init__(f"circuit for {target!r} is open")
        self.target = target


class OperationTimeout(TimeoutError):
    """A policy-guarded op exceeded its per-attempt ``timeout_s``."""

    def __init__(self, op: str, timeout_s: float):
        super().__init__(f"{op} exceeded {timeout_s}s timeout")
        self.op = op
        self.timeout_s = timeout_s


@dataclass(frozen=True)
class RetryPolicy:
    """Declarative backoff: attempt ``n`` (0-based) waits
    ``base_delay_s * multiplier**n`` capped at ``max_delay_s``, widened by
    up to ``jitter`` (a fraction, so 0.1 = ±10%).  ``retryable`` filters
    which exceptions are worth another attempt (None = all).  A
    ``retry_after_s`` attribute on the exception (FLOOD_WAIT and
    HTTP-429 taxonomies) overrides the computed delay, capped at
    ``retry_after_cap_s``."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.1
    retry_after_cap_s: float = 30.0
    retryable: Optional[Callable[[BaseException], bool]] = None

    def should_retry(self, exc: BaseException) -> bool:
        return self.retryable is None or bool(self.retryable(exc))

    def delay_s(self, attempt: int, exc: Optional[BaseException] = None,
                rng: Callable[[], float] = random.random) -> float:
        """Wait before retrying after 0-based ``attempt`` failed with
        ``exc``.  Deterministic with ``jitter=0`` (tests)."""
        hint = getattr(exc, "retry_after_s", None)
        if hint is not None:
            try:
                return min(float(hint), self.retry_after_cap_s)
            except (TypeError, ValueError):
                pass
        delay = min(self.base_delay_s * (self.multiplier ** attempt),
                    self.max_delay_s)
        if self.jitter > 0:
            delay *= 1.0 + self.jitter * (2.0 * rng() - 1.0)
        return max(0.0, delay)


def retry_call(fn: Callable[..., Any], *args: Any,
               retry: RetryPolicy,
               op: str = "op",
               stop: Optional[threading.Event] = None,
               sleep: Optional[Callable[[float], None]] = None,
               registry: MetricsRegistry = REGISTRY,
               breaker: Optional["CircuitBreaker"] = None,
               **kwargs: Any) -> Any:
    """Run ``fn(*args, **kwargs)`` under ``retry``; returns its result or
    raises the last exception once attempts are exhausted (or the error
    is classified non-retryable).  This is THE attempt loop — `Policy`
    delegates here rather than keeping a diverging copy.

    ``stop`` makes the between-attempt waits interruptible (the bus
    dispatch loops pass their shutdown event so a close() never blocks on
    a backoff) — a set event short-circuits the *wait*, not the remaining
    attempts, preserving at-least-once delivery during drain.

    ``breaker`` (if given) is consulted before and fed after every
    attempt.  A breaker that opens MID-retry re-raises the real
    underlying error; :class:`CircuitOpenError` surfaces only when the
    op was shed without a single attempt.
    """
    waiter = sleep
    if waiter is None:
        waiter = stop.wait if stop is not None else time.sleep
    retries = registry.counter(
        "resilience_retries_total",
        "Retried attempts per operation (utils/resilience.py)")
    attempts = max(1, retry.max_attempts)
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        if breaker is not None and not breaker.allow():
            if last is not None:
                raise last
            raise CircuitOpenError(breaker.target)
        try:
            result = fn(*args, **kwargs)
        except Exception as e:
            if breaker is not None:
                breaker.record_failure()
            last = e
            if attempt + 1 >= attempts or not retry.should_retry(e):
                raise
            retries.labels(op=op).inc()
            delay = retry.delay_s(attempt, e)
            logger.warning("%s failed (attempt %d/%d): %s; retrying in "
                           "%.3fs", op, attempt + 1, attempts, e, delay)
            if delay > 0:
                waiter(delay)
            continue
        if breaker is not None:
            breaker.record_success()
        return result
    raise last if last is not None else RuntimeError("unreachable")


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probes.

    closed: ops flow; ``failure_threshold`` consecutive failures open it.
    open: ops are rejected (:meth:`allow` returns False) until
    ``recovery_timeout_s`` passes, then it turns half-open.
    half-open: up to ``half_open_max_probes`` ops are let through; one
    success closes the circuit, one failure re-opens it (and restarts the
    recovery clock).

    Transitions update ``resilience_circuit_state{target}`` and land in
    the flight ring (kind ``circuit``), so an operator can answer "when
    did the state store start failing" from a postmortem bundle alone.
    """

    def __init__(self, target: str, failure_threshold: int = 5,
                 recovery_timeout_s: float = 30.0,
                 half_open_max_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic,
                 registry: MetricsRegistry = REGISTRY):
        self.target = target
        self.failure_threshold = max(1, failure_threshold)
        self.recovery_timeout_s = recovery_timeout_s
        self.half_open_max_probes = max(1, half_open_max_probes)
        self.clock = clock
        self._lock = threading.Lock()
        self._state = CIRCUIT_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probes = 0
        self._gauge = registry.gauge(
            "resilience_circuit_state",
            "Circuit state per target: 0 closed, 0.5 half-open, 1 open"
        ).labels(target=target)
        self._opens = registry.counter(
            "resilience_circuit_open_total",
            "Circuit open transitions per target").labels(target=target)
        self._gauge.set(0.0)

    # -- state --------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    @property
    def is_open(self) -> bool:
        """True while ops should be shed (open AND not yet probe-time)."""
        return not self.allow(consume_probe=False)

    def _transition_locked(self, new_state: str) -> None:
        if new_state == self._state:
            return
        old, self._state = self._state, new_state
        self._gauge.set(_STATE_VALUE[new_state])
        if new_state == CIRCUIT_OPEN:
            self._opens.inc()
        flight.record("circuit", target=self.target, frm=old, to=new_state,
                      failures=self._failures)
        log = logger.warning if new_state == CIRCUIT_OPEN else logger.info
        log("circuit %s: %s -> %s", self.target, old, new_state)

    def _maybe_half_open_locked(self) -> None:
        # Caller holds _lock (the `_locked` suffix contract).
        if self._state == CIRCUIT_OPEN and \
                self.clock() - self._opened_at >= self.recovery_timeout_s:
            self._probes = 0  # crawlint: disable=LCK001
            self._transition_locked(CIRCUIT_HALF_OPEN)

    # -- the op protocol ----------------------------------------------------
    def allow(self, consume_probe: bool = True) -> bool:
        """May an op proceed right now?  In half-open state each True
        consumes one probe slot (unless ``consume_probe=False``, the
        read-only form status endpoints use)."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == CIRCUIT_CLOSED:
                return True
            if self._state == CIRCUIT_HALF_OPEN:
                if self._probes < self.half_open_max_probes:
                    if consume_probe:
                        self._probes += 1
                    return True
                return False
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state != CIRCUIT_CLOSED:
                self._transition_locked(CIRCUIT_CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == CIRCUIT_HALF_OPEN:
                # The probe failed: back to open, restart the clock.
                self._opened_at = self.clock()
                self._transition_locked(CIRCUIT_OPEN)
            elif self._state == CIRCUIT_CLOSED and \
                    self._failures >= self.failure_threshold:
                self._opened_at = self.clock()
                self._transition_locked(CIRCUIT_OPEN)


class Policy:
    """Retry + breaker + per-attempt timeout behind one ``call``.

    The per-attempt ``timeout_s`` runs the op on a (lazily built, shared)
    worker thread and abandons it on expiry — Python can't interrupt a
    blocked call, so the thread may linger, but the *caller* gets its
    deadline back (exactly what a wedged state backend needs: the
    orchestrator loop keeps ticking while the breaker counts the
    timeouts and opens).
    """

    def __init__(self, op: str, retry: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 timeout_s: float = 0.0,
                 registry: MetricsRegistry = REGISTRY):
        self.op = op
        self.retry = retry or RetryPolicy()
        self.breaker = breaker
        self.timeout_s = timeout_s
        self.registry = registry
        self._executor: Optional[ThreadPoolExecutor] = None
        self._exec_lock = threading.Lock()

    # -- introspection ------------------------------------------------------
    @property
    def circuit_open(self) -> bool:
        return self.breaker is not None and self.breaker.is_open

    # -- execution ----------------------------------------------------------
    def _run_once(self, fn: Callable[..., Any], args, kwargs) -> Any:
        if self.timeout_s <= 0:
            return fn(*args, **kwargs)
        with self._exec_lock:
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=2, thread_name_prefix=f"dct-res-{self.op}")
            executor = self._executor
        future = executor.submit(fn, *args, **kwargs)
        try:
            return future.result(timeout=self.timeout_s)
        except _FutureTimeout:
            future.cancel()
            raise OperationTimeout(self.op, self.timeout_s) from None

    def call(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
        """Run ``fn`` under the full policy: every attempt checks the
        breaker (shedding is cheap — no call, no wait), failures feed it,
        and retries follow the backoff schedule — all via the one shared
        attempt loop (:func:`retry_call`)."""
        def attempt_once() -> Any:
            return self._run_once(fn, args, kwargs)

        return retry_call(attempt_once, retry=self.retry, op=self.op,
                          registry=self.registry, breaker=self.breaker)


def with_policy(policy: Policy) -> Callable[[Callable[..., Any]],
                                            Callable[..., Any]]:
    """Decorator form: ``@with_policy(Policy("state_store", ...))``."""

    def deco(fn: Callable[..., Any]) -> Callable[..., Any]:
        def wrapped(*args: Any, **kwargs: Any) -> Any:
            return policy.call(fn, *args, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        wrapped.__doc__ = fn.__doc__
        wrapped.__wrapped__ = fn
        return wrapped

    return deco
