"""End-to-end span tracing: see where every batch's millisecond went.

Bus envelopes have carried ``trace_id``s since the first message type
(`bus/messages.py:new_trace_id`) but nothing ever correlated them; the
north-star metrics (posts/sec/chip, p50 batch latency) are totals with no
attribution.  This module is the missing layer, shaped like Dapr-style
distributed tracing scaled down to in-process cost:

- :func:`span` — a ``perf_counter`` context manager recording one named,
  attributed span.  Spans nest: a span opened inside another inherits its
  trace id and parent span via a contextvar, so the orchestrator's dispatch
  span, the bus delivery span, and the engine's per-stage spans all land in
  one trace without any plumbing through call signatures.
- :func:`record` — a retroactive span for durations measured elsewhere
  (queue-wait age, ack round trips).
- :func:`inject` / :func:`payload_span` — the propagation seam both bus
  transports use: publish stamps the current span id into the envelope as
  ``parent_span``; delivery re-roots the consumer's context from the
  envelope's ``trace_id``/``parent_span``.
- a bounded ring buffer of completed spans, grouped into traces and served
  as JSON at the metrics server's ``/traces`` endpoint
  (`utils/metrics.py`), plus slow-span threshold logging.

Tracing never invents trace ids for untraced messages: a payload without a
``trace_id`` passes through both buses untouched, and ``payload_span`` is a
no-op for it — only envelopes that opted into tracing pay for it.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import math
import secrets
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

logger = logging.getLogger("dct.trace")

DEFAULT_CAPACITY = 2048  # completed spans kept for /traces

# (trace_id, span_id, span_name) of the innermost open span on this
# thread/task.  Only the first two participate in propagation; the name
# rides along so log formatters (`utils/structlog.py`) can stamp records
# with the stage they were emitted from.
_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "dct_trace_ctx", default=None)


def _new_trace_id() -> str:
    """Same shape as `bus/messages.py:new_trace_id` (kept local: utils must
    not import the bus layer it instruments)."""
    return ("trace_" + time.strftime("%Y%m%d%H%M%S", time.gmtime())
            + "_" + secrets.token_hex(4))


def _new_span_id() -> str:
    return "sp_" + secrets.token_hex(6)


@dataclass
class Span:
    """One completed, named timing with attribution."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str = ""
    start_wall: float = 0.0        # epoch seconds at span open
    duration_s: float = 0.0
    attrs: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_wall": self.start_wall,
            "duration_ms": round(self.duration_s * 1000.0, 3),
            "attrs": self.attrs,
        }


class _OpenSpan:
    """Handle yielded by :meth:`Tracer.span`; ``set`` adds attrs late
    (e.g. an outcome only known at the end of the block)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: str, attrs: Dict[str, Any]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs

    def set(self, **attrs: Any) -> None:
        self.attrs.update(attrs)


class Tracer:
    """Bounded in-process span collector with slow-span logging."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 slow_span_s: float = 0.0):
        self._lock = threading.Lock()
        self._spans: "deque[Span]" = deque(maxlen=max(1, capacity))
        self._enabled = capacity > 0
        self._completed_total = 0  # spans ever appended (export cursor)
        self.capacity = capacity
        self.slow_span_s = slow_span_s

    # -- configuration ------------------------------------------------------
    def configure(self, capacity: Optional[int] = None,
                  slow_span_s: Optional[float] = None) -> None:
        """Resize the ring / set the slow threshold (CLI flags).  A
        capacity of 0 disables span recording entirely (context propagation
        still works, so downstream hops that kept tracing on still
        correlate)."""
        with self._lock:
            if capacity is not None:
                self.capacity = capacity
                self._enabled = capacity > 0
                self._spans = deque(self._spans, maxlen=max(1, capacity))
            if slow_span_s is not None:
                self.slow_span_s = slow_span_s

    # -- recording ----------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, trace_id: str = "",
             parent_id: Optional[str] = None,
             **attrs: Any) -> Iterator[_OpenSpan]:
        """Record a named span around the block.

        ``trace_id`` wins when given (a bus hop re-rooting from an
        envelope); otherwise the ambient context's trace continues, and a
        fresh trace starts if there is none.  The ambient parent is used
        unless ``parent_id`` overrides it (an envelope's ``parent_span``).
        """
        ambient = _CTX.get()
        if not trace_id:
            trace_id = ambient[0] if ambient else _new_trace_id()
        if parent_id is None:
            # Only inherit the ambient span as parent when it belongs to
            # the SAME trace — a bus hop with an explicit trace_id must not
            # claim the publisher thread's unrelated span as its parent.
            parent_id = ambient[1] if ambient and ambient[0] == trace_id \
                else ""
        span_id = _new_span_id()
        handle = _OpenSpan(name, trace_id, span_id, parent_id, dict(attrs))
        token = _CTX.set((trace_id, span_id, name))
        start_wall = time.time()
        t0 = time.perf_counter()
        try:
            yield handle
        except BaseException:
            handle.attrs.setdefault("error", True)
            raise
        finally:
            _CTX.reset(token)
            self._finish(Span(name=handle.name, trace_id=trace_id,
                              span_id=span_id, parent_id=parent_id,
                              start_wall=start_wall,
                              duration_s=time.perf_counter() - t0,
                              attrs=handle.attrs))

    def record(self, name: str, duration_s: float, trace_id: str = "",
               parent_id: str = "", **attrs: Any) -> None:
        """Retroactive span: the duration was measured elsewhere (queue-wait
        age computed at dequeue, an ack round trip)."""
        ambient = _CTX.get()
        if not trace_id:
            if ambient is None:
                return  # nothing to attach to; don't invent a trace
            trace_id = ambient[0]
        if not parent_id and ambient and ambient[0] == trace_id:
            parent_id = ambient[1]
        self._finish(Span(name=name, trace_id=trace_id,
                          span_id=_new_span_id(), parent_id=parent_id,
                          start_wall=time.time() - duration_s,
                          duration_s=duration_s, attrs=dict(attrs)))

    def _finish(self, s: Span) -> None:
        if self._enabled:
            with self._lock:
                self._spans.append(s)
                self._completed_total += 1
        if self.slow_span_s > 0 and s.duration_s >= self.slow_span_s:
            # The slow-trace log line (docs/operations.md "Observability"):
            # span name, trace id for /traces correlation, duration, attrs.
            logger.warning(
                "slow span %s %.1fms (threshold %.0fms) trace=%s attrs=%s",
                s.name, s.duration_s * 1000.0, self.slow_span_s * 1000.0,
                s.trace_id, s.attrs)

    # -- introspection / export --------------------------------------------
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def spans_with_total(self) -> Tuple[List[Span], int]:
        """(ring contents, spans ever completed) in ONE atomic read — the
        export cursor a `SpanExporter` needs; splitting the two reads
        would let a span complete in between and be shipped twice or
        never."""
        with self._lock:
            return list(self._spans), self._completed_total

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()

    def export(self, limit: int = 0) -> Dict[str, Any]:
        """Spans grouped into traces, most recently completed trace first —
        the JSON body of the ``/traces`` endpoint."""
        spans = self.spans()
        by_trace: Dict[str, List[Span]] = {}
        last_seen: Dict[str, int] = {}
        for idx, s in enumerate(spans):  # ring order == completion order
            by_trace.setdefault(s.trace_id, []).append(s)
            # Recency is a trace's LAST completed span, not its first — a
            # long-lived trace whose result leg just landed must sort
            # ahead of short traces that finished in between.
            last_seen[s.trace_id] = idx
        traces = []
        for tid in sorted(last_seen, key=last_seen.__getitem__,
                          reverse=True):
            group = by_trace[tid]
            start = min(s.start_wall for s in group)
            end = max(s.start_wall + s.duration_s for s in group)
            traces.append({
                "trace_id": tid,
                "span_count": len(group),
                "duration_ms": round((end - start) * 1000.0, 3),
                "spans": [s.to_dict() for s in group],
            })
            if limit and len(traces) >= limit:
                break
        return {"traces": traces, "capacity": self.capacity,
                "slow_span_ms": self.slow_span_s * 1000.0}


TRACER = Tracer()

# Module-level conveniences bound to the process-wide tracer.
span = TRACER.span
record = TRACER.record
configure = TRACER.configure


def current_trace_id() -> str:
    ctx = _CTX.get()
    return ctx[0] if ctx else ""


def current_span_id() -> str:
    ctx = _CTX.get()
    return ctx[1] if ctx else ""


def current_span_name() -> str:
    ctx = _CTX.get()
    return ctx[2] if ctx and len(ctx) > 2 else ""


def latency_digest(spans: List[Span],
                   since_wall: float = 0.0) -> Dict[str, Dict[str, float]]:
    """Per-span-name p50/p95/max/count over ``spans`` (optionally only
    those that COMPLETED after ``since_wall``) — the compact shape
    heartbeats carry fleet-wide instead of shipping whole span rings."""
    by_name: Dict[str, List[float]] = {}
    for s in spans:
        if since_wall and (s.start_wall + s.duration_s) <= since_wall:
            continue
        by_name.setdefault(s.name, []).append(s.duration_s * 1000.0)
    out: Dict[str, Dict[str, float]] = {}
    for name, vals in by_name.items():
        vals.sort()
        n = len(vals)

        def rank(q: float) -> float:
            # Nearest-rank (ceil), not floor interpolation: with few
            # samples a floor index collapses p95 onto the MINIMUM —
            # e.g. [1ms, 1000ms] must report p95=1000, not 1.
            return vals[min(n - 1, max(0, math.ceil(q * n) - 1))]

        out[name] = {
            "count": n,
            "p50_ms": round(rank(0.5), 3),
            "p95_ms": round(rank(0.95), 3),
            "max_ms": round(vals[-1], 3),
        }
    return out


def span_from_dict(d: Dict[str, Any]) -> Span:
    """Inverse of :meth:`Span.to_dict` — the decode side of span export
    (`bus/messages.py:SpanBatchMessage` ships the dict form)."""
    return Span(
        name=str(d.get("name", "") or ""),
        trace_id=str(d.get("trace_id", "") or ""),
        span_id=str(d.get("span_id", "") or ""),
        parent_id=str(d.get("parent_id", "") or ""),
        start_wall=float(d.get("start_wall") or 0.0),
        duration_s=float(d.get("duration_ms") or 0.0) / 1000.0,
        attrs=dict(d.get("attrs") or {}),
    )


class SpanExporter:
    """Bounded, trace-consistent sampling of NEW completed spans.

    Each ``collect()`` returns the spans completed since the previous
    collect (starting from construction time — a fresh exporter never
    re-ships a ring full of history), after:

    - **trace-consistent sampling**: ``sample_rate`` keeps or drops
      whole traces by a stable hash of the trace id (crc32), so every
      process sampling at the same rate ships the SAME subset of traces
      and the collector can still assemble complete cross-process
      traces.  Untraced spans (no trace id) are never shipped.
    - **bounding**: at most ``max_spans`` per collect, newest kept (the
      freshest spans are the ones an operator is debugging).
    - **ownership filtering**: ``name_prefixes`` restricts the export to
      the spans THIS component produced.  The ring is process-wide; in
      shared-process deployments (--bus-serve single-service, the
      loadgen gate, an orchestrator embedding a worker) an unfiltered
      exporter would ship — and claim authorship of — every other
      component's spans, and the export publish's own ``bus.deliver``
      span would feed back into the next export forever.

    The second return value counts spans NOT shipped (ring eviction
    between collects, sampling, the bound) so the collector can report
    loss instead of silently under-representing a hot worker.  Spans
    excluded by the ownership filter are someone else's to ship and are
    NOT counted as dropped.
    """

    def __init__(self, tracer: Optional[Tracer] = None,
                 max_spans: int = 512, sample_rate: float = 1.0,
                 name_prefixes: Tuple[str, ...] = ()):
        self.tracer = tracer or TRACER
        self.max_spans = max(1, int(max_spans))
        self.sample_rate = min(1.0, max(0.0, float(sample_rate)))
        self.name_prefixes = tuple(name_prefixes)
        # Serializes collect(): the heartbeat thread and on-demand
        # callers (the loadgen gate's phase-boundary flush) may race,
        # and an unsynchronized cursor would ship one window twice.
        self._lock = threading.Lock()
        _, self._cursor = self.tracer.spans_with_total()

    def keeps(self, trace_id: str) -> bool:
        """Stable per-trace sampling decision (shared across processes)."""
        if not trace_id:
            return False
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        import zlib

        return (zlib.crc32(trace_id.encode("utf-8")) % 10_000) < \
            self.sample_rate * 10_000

    def collect(self) -> Tuple[List[Span], int]:
        """(spans to ship, dropped count) since the previous collect."""
        with self._lock:
            spans, total = self.tracer.spans_with_total()
            fresh_n, self._cursor = total - self._cursor, total
        if fresh_n <= 0:
            return [], 0
        fresh = spans[-fresh_n:] if fresh_n <= len(spans) else spans
        dropped = fresh_n - len(fresh)  # evicted before we got here
        if self.name_prefixes:
            fresh = [s for s in fresh
                     if s.name.startswith(self.name_prefixes)]
        sampled = [s for s in fresh if self.keeps(s.trace_id)]
        dropped += len(fresh) - len(sampled)
        if len(sampled) > self.max_spans:
            dropped += len(sampled) - self.max_spans
            sampled = sampled[-self.max_spans:]
        return sampled, dropped


def inject(payload: Any) -> Any:
    """Stamp the current span into an outbound envelope (publish side).

    Returns ``payload`` augmented with ``parent_span`` (a shallow copy —
    the caller's dict is never mutated) when ALL of: a span is open on this
    thread, the payload is a dict that carries a truthy ``trace_id``, and
    no ``parent_span`` is set yet.  Everything else passes through
    untouched, so untraced messages stay byte-identical.
    """
    ctx = _CTX.get()
    if (ctx is None or not isinstance(payload, dict)
            or not payload.get("trace_id") or payload.get("parent_span")):
        return payload
    return {**payload, "parent_span": ctx[1]}


def payload_span(name: str, payload: Any, **attrs: Any):
    """Delivery-side twin of :func:`inject`: a span re-rooted from the
    envelope's ``trace_id``/``parent_span``; a no-op context manager when
    the payload carries no trace id."""
    tid = payload.get("trace_id") if isinstance(payload, dict) else None
    if not tid:
        return contextlib.nullcontext()
    return TRACER.span(name, trace_id=tid,
                       parent_id=payload.get("parent_span", "") or "",
                       **attrs)
