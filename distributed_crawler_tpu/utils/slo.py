"""SLO watchdog: declared latency budgets evaluated over the span ring.

The tracing layer (`utils/trace.py`) records where every batch's
millisecond went; the telemetry layer digests those spans into p50/p95
per stage.  What was missing is a *judgement*: is serving inside its
budget?  This module holds the declared budgets
(``--slo-batch-p95-ms``, ``--slo-queue-wait-ms``) and, on every
evaluation tick (the worker heartbeat loops), computes nearest-rank p95
over the spans completed since the previous tick for each SLO's span
set.  A breach:

- increments ``slo_breach_total{slo=…}``,
- logs a WARNING naming the worst offender's ``trace_id`` (pull its full
  timeline from ``/traces`` while it is still in the buffer),
- records a ``slo_breach`` event into the flight-recorder ring, so
  postmortem bundles carry the budget history alongside the crash.

Evaluation is windowed, not cumulative: one terrible minute trips one
breach per tick it spans, and a recovered service stops counting — the
counter's rate IS the badness rate.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from . import flight, trace
from .metrics import REGISTRY, MetricsRegistry

logger = logging.getLogger("dct.slo")

# Span names that measure one unit of work end to end, per worker kind.
# The batch budget reads whichever of these the process emits.
BATCH_SPANS = ("tpu_worker.process", "tpu_worker.coalesce",
               "worker.process", "cluster_worker.process")
QUEUE_WAIT_SPANS = ("tpu_worker.queue_wait", "asr_worker.queue_wait",
                    "cluster_worker.queue_wait")
# Whole-pipeline age of a record batch (creation -> device), recorded by
# the TPU worker from ``RecordBatch.created_at``.  Unlike queue_wait —
# which only sees time inside THIS worker's queue — batch age covers the
# bus/broker leg, so it is the budget that catches a dead worker's
# backlog: frames stranded on the broker while the worker was down come
# back old, even though they clear the local queue instantly.
BATCH_AGE_SPANS = ("tpu_worker.batch_age", "asr_worker.batch_age",
                   "cluster_worker.batch_age")
# The ASR worker's unit of work (an audio-batch group through decode →
# window → bucketed Whisper programs).  A separate budget from the text
# batch one because the latency regimes differ by orders of magnitude
# (seconds of greedy decode vs milliseconds of embed+classify).
ASR_BATCH_SPANS = ("asr_worker.process", "asr_worker.coalesce")


@dataclass(frozen=True)
class SLO:
    """One declared budget: the p95 of ``span_names`` must stay under
    ``budget_ms``."""

    name: str                       # label value in slo_breach_total{slo=}
    span_names: Tuple[str, ...]
    budget_ms: float


def standard_slos(batch_p95_ms: float = 0.0,
                  queue_wait_ms: float = 0.0,
                  batch_age_ms: float = 0.0,
                  asr_batch_p95_ms: float = 0.0) -> List[SLO]:
    """The CLI's budget set; zero/negative budgets are simply absent."""
    out: List[SLO] = []
    if batch_p95_ms > 0:
        out.append(SLO("batch_p95", BATCH_SPANS, batch_p95_ms))
    if queue_wait_ms > 0:
        out.append(SLO("queue_wait", QUEUE_WAIT_SPANS, queue_wait_ms))
    if batch_age_ms > 0:
        out.append(SLO("batch_age", BATCH_AGE_SPANS, batch_age_ms))
    if asr_batch_p95_ms > 0:
        out.append(SLO("asr_batch", ASR_BATCH_SPANS, asr_batch_p95_ms))
    return out


class SLOWatchdog:
    """Windowed budget evaluation over the process tracer's span ring."""

    def __init__(self, slos: List[SLO], tracer: Optional[trace.Tracer] = None,
                 registry: MetricsRegistry = REGISTRY):
        self.slos = list(slos)
        self.tracer = tracer or trace.TRACER
        self._lock = threading.Lock()
        self._last_eval = time.time()
        self._warned_disabled = False
        self._breach_counts: Dict[str, int] = {s.name: 0 for s in self.slos}
        # {(tenant, slo): count} — children of the same counter family,
        # NEVER replacing the aggregate (the tenant-labeled series carry
        # the extra ``tenant`` label; the parent {slo=} series stays the
        # fleet truth existing dashboards and gates read).
        self._tenant_breach_counts: Dict[Tuple[str, str], int] = {}
        self.m_breaches = registry.counter(
            "slo_breach_total",
            "declared latency budgets busted, by SLO name (one per "
            "evaluation tick the breach spans; tenant-labeled children "
            "split the same events by workload)")

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One tick: digest spans completed since the last tick against
        every budget; returns the breach records (also counted, logged,
        and flight-recorded).  Cheap when nothing completed."""
        now = now if now is not None else time.time()
        with self._lock:
            since, self._last_eval = self._last_eval, now
        if not self.slos:
            return []
        if getattr(self.tracer, "capacity", 1) <= 0:
            # Budgets ride the span ring: with recording off they can
            # never be evaluated — say so ONCE instead of staying
            # silently green forever.  Checked per tick (not at
            # construction) because the tracer is reconfigurable.
            if not self._warned_disabled:
                self._warned_disabled = True
                logger.warning(
                    "SLO budgets declared (%s) but span recording is "
                    "disabled (--trace-buffer 0); budgets will NOT be "
                    "evaluated", ", ".join(s.name for s in self.slos))
            return []
        self._warned_disabled = False
        spans = [s for s in self.tracer.spans()
                 if (s.start_wall + s.duration_s) > since]
        breaches: List[Dict[str, Any]] = []
        for slo in self.slos:
            matched = [s for s in spans if s.name in slo.span_names]
            if not matched:
                continue
            matched.sort(key=lambda s: s.duration_s)
            n = len(matched)
            # Nearest-rank p95, matching utils/trace.latency_digest.
            p95_span = matched[min(n - 1, max(0, math.ceil(0.95 * n) - 1))]
            p95_ms = p95_span.duration_s * 1000.0
            if p95_ms <= slo.budget_ms:
                continue
            worst = matched[-1]
            self.m_breaches.labels(slo=slo.name).inc()
            with self._lock:
                self._breach_counts[slo.name] = \
                    self._breach_counts.get(slo.name, 0) + 1
            logger.warning(
                "SLO %s busted: p95 %.1fms > budget %.0fms over %d spans "
                "(worst %s %.1fms trace=%s)",
                slo.name, p95_ms, slo.budget_ms, n, worst.name,
                worst.duration_s * 1000.0, worst.trace_id)
            flight.record("slo_breach", slo=slo.name,
                          p95_ms=round(p95_ms, 1),
                          budget_ms=slo.budget_ms, spans=n,
                          worst_span=worst.name,
                          worst_ms=round(worst.duration_s * 1000.0, 1),
                          trace_id=worst.trace_id)
            breaches.append({
                "slo": slo.name, "p95_ms": round(p95_ms, 1),
                "budget_ms": slo.budget_ms, "spans": n,
                "worst_trace_id": worst.trace_id,
            })
        # Per-tenant children (ISSUE 17): the same spans, split by their
        # ``tenant`` attr, each judged against the same budget.  Runs
        # even when the aggregate stayed green — one hot tenant can bust
        # its own p95 inside a healthy fleet p95.
        for slo in self.slos:
            by_tenant: Dict[str, List[Any]] = {}
            for s in spans:
                if s.name not in slo.span_names:
                    continue
                tenant = getattr(s, "attrs", {}).get("tenant")
                if tenant:
                    by_tenant.setdefault(str(tenant), []).append(s)
            for tenant, matched in by_tenant.items():
                matched.sort(key=lambda s: s.duration_s)
                n = len(matched)
                p95_span = matched[min(n - 1,
                                       max(0, math.ceil(0.95 * n) - 1))]
                if p95_span.duration_s * 1000.0 <= slo.budget_ms:
                    continue
                self.m_breaches.labels(slo=slo.name, tenant=tenant).inc()
                with self._lock:
                    key = (tenant, slo.name)
                    self._tenant_breach_counts[key] = \
                        self._tenant_breach_counts.get(key, 0) + 1
        return breaches

    def snapshot(self) -> Dict[str, Any]:
        """Budgets + cumulative breach counts (the /costs ``slo`` map).
        ``tenant_breaches`` nests {tenant: {slo: count}} so heartbeats
        can carry the per-tenant split next to the aggregate."""
        with self._lock:
            counts = dict(self._breach_counts)
            tenant_counts = dict(self._tenant_breach_counts)
        by_tenant: Dict[str, Dict[str, int]] = {}
        for (tenant, slo_name), n in sorted(tenant_counts.items()):
            by_tenant.setdefault(tenant, {})[slo_name] = n
        return {
            "budgets": [{"slo": s.name, "budget_ms": s.budget_ms,
                         "spans": list(s.span_names)} for s in self.slos],
            "breaches": counts,
            "tenant_breaches": by_tenant,
        }
