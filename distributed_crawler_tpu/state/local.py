"""Local-filesystem state manager.

Parity with the reference's `LocalStateManager` (`state/storageproviders.go`):
- layout: ``<base>/<crawl_id>/state.json``, ``metadata.json``,
  ``media-cache.json`` (`:636-646`), per-channel
  ``<crawl_id>/<channel>/posts/posts.jsonl`` (`:285-291`), media under
  ``<crawl_id>/media/<channel>/`` (`:325-344`), exports under
  ``<crawl_id>/exports/`` (`:574-580`)
- resume from persisted state incl. previous-crawl metadata scan (`:489-548`)
- random-walk / tandem methods are not implemented for local storage
  (`:144-243`) — use CompositeStateManager with a SqlConfig for those.
"""

from __future__ import annotations

import json
import logging
from typing import Any, List, Optional, Tuple

from ..datamodel import Post
from .base import BaseStateManager
from .datamodels import Page, State, utcnow
from .interface import StateConfig
from .media_cache import ShardedMediaCache
from .providers import LocalStorageProvider, StorageProvider

logger = logging.getLogger("dct.state.local")


class LocalStateManager(BaseStateManager):
    """Filesystem-backed state manager (`state/storageproviders.go:84-647`)."""

    def __init__(self, config: StateConfig, provider: Optional[StorageProvider] = None):
        super().__init__(config)
        base_path = (config.local.base_path if config.local else None) or config.storage_root
        if provider is None:
            provider = LocalStorageProvider(base_path)
        self.provider = provider
        self.media_cache = ShardedMediaCache(provider, config.crawl_id)
        self._initialized = False

    # --- paths (`storageproviders.go:636-646`) ----------------------------
    def _state_path(self) -> str:
        return f"{self.config.crawl_id}/state.json"

    def _metadata_path(self, crawl_id: Optional[str] = None) -> str:
        return f"{crawl_id or self.config.crawl_id}/metadata.json"

    # --- lifecycle -------------------------------------------------------
    def initialize(self, seed_urls: List[str]) -> None:
        """Load persisted state if present, else seed a fresh one
        (`storageproviders.go:360-430`).  A snapshot with no layers is not a
        resumable crawl — seed fresh instead (an empty state.json can be left
        behind by a temporary resume-probe manager)."""
        self._initialized = True
        existing = self.provider.load_json(self._state_path())
        if existing and existing.get("layers"):
            self.set_state(State.from_dict(existing))
            logger.info("resumed state for crawl %s (%d pages)",
                        self.config.crawl_id, len(self.page_map))
            return
        super().initialize(seed_urls)
        self.save_state()

    def save_state(self) -> None:
        """Persist state.json + metadata.json (`storageproviders.go:245-272`)."""
        state = self.get_state()
        self.provider.save_json(self._state_path(), state.to_dict())
        self.provider.save_json(self._metadata_path(), self.metadata.to_dict())
        self.media_cache.save()

    def close(self) -> None:
        # A manager that never initialized (e.g. the temporary resume probe
        # in determine_crawl_id) must not overwrite state on close.
        if self._initialized:
            self.save_state()
        # Push any provider-side write buffering (the object store batches
        # appends; local FS is a no-op).
        flush = getattr(self.provider, "flush", None)
        if callable(flush):
            flush()

    # --- posts/files ------------------------------------------------------
    def store_post(self, channel_id: str, post: Post) -> None:
        """Append to the per-channel JSONL (`storageproviders.go:275-298`)."""
        rel = f"{self.config.crawl_id}/{channel_id}/posts/posts.jsonl"
        self.provider.append_jsonl(rel, post.to_json())

    def store_file(self, channel_id: str, source_file_path: str,
                   file_name: str) -> Tuple[str, str]:
        """Copy media in, delete the source (`storageproviders.go:301-344`)."""
        rel = f"{self.config.crawl_id}/media/{channel_id}/{file_name}"
        stored = self.provider.store_file(rel, source_file_path, delete_source=True)
        return stored, file_name

    def export_pages_to_binding(self, crawl_id: str) -> None:
        """Write a pages-export JSONL snapshot (`storageproviders.go:574-589`)."""
        state = self.get_state()
        stamp = utcnow().strftime("%Y%m%d%H%M%S")
        rel = f"{crawl_id}/exports/pages-export-{stamp}.jsonl"
        for layer in state.layers:
            for page in layer.pages:
                self.provider.append_jsonl(rel, json.dumps(page.to_dict()))

    # --- media cache ------------------------------------------------------
    def has_processed_media(self, media_id: str) -> bool:
        return self.media_cache.has(media_id)

    def mark_media_as_processed(self, media_id: str) -> None:
        self.media_cache.mark(media_id, platform=self.config.platform)

    # --- resume -----------------------------------------------------------
    def find_incomplete_crawl(self, crawl_id: str) -> Tuple[str, bool]:
        """Check persisted metadata for this and previous crawl executions
        (`storageproviders.go:489-548`)."""
        exec_id, found = super().find_incomplete_crawl(crawl_id)
        if found:
            return exec_id, True
        meta = self.provider.load_json(self._metadata_path(crawl_id))
        if meta:
            if meta.get("status") != "completed" and meta.get("executionId"):
                return meta["executionId"], True
            for prev_id in meta.get("previousCrawlId") or []:
                prev_meta = self.provider.load_json(self._metadata_path(prev_id))
                if prev_meta and prev_meta.get("status") != "completed" \
                        and prev_meta.get("executionId"):
                    return prev_meta["executionId"], True
        return "", False

    # --- random-walk (not supported on plain local storage) ---------------
    def get_pages_from_page_buffer(self, limit: int) -> List[Page]:
        raise NotImplementedError("page buffer requires a SQL-backed state manager")

    def execute_database_operation(self, sql_query: str, params: List[Any]) -> None:
        raise NotImplementedError("database operations require a SQL-backed state manager")

    def add_page_to_page_buffer(self, page: Page) -> None:
        raise NotImplementedError("page buffer requires a SQL-backed state manager")

    def delete_page_buffer_pages(self, page_ids: List[str], page_urls: List[str]) -> None:
        raise NotImplementedError("page buffer requires a SQL-backed state manager")
