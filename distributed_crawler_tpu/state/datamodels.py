"""State-layer data models.

Parity with the reference's `state/datamodels.go`: Page/Message/Layer/State,
EdgeRecord, PendingEdgeBatch/PendingEdge, CrawlMetadata, media cache records,
and the thread-safe DiscoveredChannels set.
"""

from __future__ import annotations

import random
import threading
import uuid
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Any, Dict, List, Optional

from ..datamodel.post import format_time, parse_time

# Page status machine (state/datamodels.go:46, §5.4 of SURVEY.md):
# unfetched -> processing -> fetched | error | deadend | abandoned.
# "error" is non-terminal (the orchestrator retries it up to its budget);
# "abandoned" is the terminal form — permanent failure or an exhausted
# retry budget — and carries no live retry-counter entry, which is what
# keeps the orchestrator's per-page retry map bounded.
PAGE_UNFETCHED = "unfetched"
PAGE_PROCESSING = "processing"
PAGE_FETCHED = "fetched"
PAGE_ERROR = "error"
PAGE_DEADEND = "deadend"
PAGE_ABANDONED = "abandoned"

# PendingEdgeBatch statuses (state/datamodels.go:93).
BATCH_OPEN = "open"
BATCH_CLOSED = "closed"
BATCH_PROCESSING = "processing"
BATCH_COMPLETED = "completed"

# PendingEdge validation statuses (state/datamodels.go:107).
EDGE_PENDING = "pending"
EDGE_VALIDATING = "validating"
EDGE_VALID = "valid"
EDGE_NOT_CHANNEL = "not_channel"
EDGE_INVALID = "invalid"
EDGE_DUPLICATE = "duplicate"


def new_id() -> str:
    return str(uuid.uuid4())


def utcnow() -> datetime:
    return datetime.now(timezone.utc)


@dataclass
class Message:
    """A message associated with a page (`state/datamodels.go:65-71`)."""

    chat_id: int = 0
    message_id: int = 0
    status: str = ""
    page_id: str = ""
    platform: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "chatId": self.chat_id,
            "messageId": self.message_id,
            "status": self.status,
            "pageId": self.page_id,
            "platform": self.platform,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Message":
        return cls(
            chat_id=int(d.get("chatId") or 0),
            message_id=int(d.get("messageId") or 0),
            status=d.get("status", "") or "",
            page_id=d.get("pageId", "") or "",
            platform=d.get("platform", "") or "",
        )


@dataclass
class Page:
    """A URL/page being crawled (`state/datamodels.go:41-62`)."""

    id: str = ""
    url: str = ""
    depth: int = 0
    status: str = PAGE_UNFETCHED
    error: str = ""
    timestamp: Optional[datetime] = None
    platform: str = ""
    parent_id: str = ""
    messages: List[Message] = field(default_factory=list)
    connection_id: str = ""
    # UUID propagated through a forward chain; new UUID on walkback.
    sequence_id: str = ""
    # Overrides the state manager's own crawl_id when writing to page_buffer
    # (set by the validator when processing a batch from another crawl).
    crawl_id: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "url": self.url,
            "depth": self.depth,
            "status": self.status,
            "error": self.error,
            "timestamp": format_time(self.timestamp),
            "platform": self.platform,
            "parentId": self.parent_id,
            "messages": [m.to_dict() for m in self.messages],
            "LastConnectionID": self.connection_id,
            "sequenceId": self.sequence_id,
            "crawlId": self.crawl_id,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Page":
        return cls(
            id=d.get("id", "") or "",
            url=d.get("url", "") or "",
            depth=int(d.get("depth") or 0),
            status=d.get("status", PAGE_UNFETCHED) or PAGE_UNFETCHED,
            error=d.get("error", "") or "",
            timestamp=parse_time(d.get("timestamp")),
            platform=d.get("platform", "") or "",
            parent_id=d.get("parentId", "") or "",
            messages=[Message.from_dict(m) for m in (d.get("messages") or [])],
            connection_id=d.get("LastConnectionID", "") or "",
            sequence_id=d.get("sequenceId", "") or "",
            crawl_id=d.get("crawlId", "") or "",
        )


@dataclass
class EdgeRecord:
    """A directed edge in the random-walk graph (`state/datamodels.go:73-81`)."""

    destination_channel: str = ""
    discovery_time: Optional[datetime] = None
    source_channel: str = ""
    walkback: bool = False
    skipped: bool = False
    # UUID shared across all edges in one forward chain.
    sequence_id: str = ""
    # If set, overrides the state manager's own crawl ID in edge_records.
    crawl_id: str = ""


@dataclass
class PendingEdgeBatch:
    """A batch of edges from one source channel in tandem mode
    (`state/datamodels.go:86-95`)."""

    batch_id: str = ""
    crawl_id: str = ""
    source_channel: str = ""
    source_page_id: str = ""
    source_depth: int = 0
    sequence_id: str = ""
    status: str = BATCH_OPEN
    attempt_count: int = 0


@dataclass
class PendingEdge:
    """A single extracted username awaiting HTTP validation
    (`state/datamodels.go:98-109`)."""

    pending_id: int = 0
    batch_id: str = ""
    crawl_id: str = ""
    destination_channel: str = ""
    source_channel: str = ""
    sequence_id: str = ""
    discovery_time: Optional[datetime] = None
    source_type: str = ""  # mention | text_url | url | plaintext | ""
    validation_status: str = EDGE_PENDING
    validation_reason: str = ""  # "" | not_supergroup | not_found


@dataclass
class PendingEdgeUpdate:
    """Result of validating one pending edge (`state/datamodels.go:112-116`)."""

    pending_id: int = 0
    validation_status: str = ""
    validation_reason: str = ""


class DiscoveredChannels:
    """Thread-safe insert-once set with O(1) random pick
    (`state/datamodels.go:118-162`)."""

    def __init__(self):
        self._items: Dict[str, bool] = {}
        self._keys: List[str] = []
        self._lock = threading.RLock()

    def add(self, item: str) -> bool:
        """Add; returns False if already present (reference returns an error)."""
        with self._lock:
            if item in self._items:
                return False
            self._items[item] = True
            self._keys.append(item)
            return True

    def contains(self, item: str) -> bool:
        with self._lock:
            return item in self._items

    def random(self) -> str:
        with self._lock:
            if not self._keys:
                raise LookupError("no discovered channels to pull from at random")
            return random.choice(self._keys)

    def __len__(self) -> int:
        with self._lock:
            return len(self._keys)


@dataclass
class Layer:
    """Pages at the same depth (`state/datamodels.go:165-169`)."""

    depth: int = 0
    pages: List[Page] = field(default_factory=list)


@dataclass
class CrawlMetadata:
    """Metadata about a crawl operation (`state/datamodels.go:172-183`)."""

    crawl_id: str = ""
    execution_id: str = ""
    start_time: Optional[datetime] = None
    end_time: Optional[datetime] = None
    status: str = "running"  # running | completed | failed
    previous_crawl_id: List[str] = field(default_factory=list)
    platform: str = ""
    target_channels: List[str] = field(default_factory=list)
    messages_count: int = 0
    errors_count: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "crawlId": self.crawl_id,
            "executionId": self.execution_id,
            "startTime": format_time(self.start_time),
            "endTime": format_time(self.end_time),
            "status": self.status,
            "previousCrawlId": self.previous_crawl_id,
            "platform": self.platform,
            "targetChannels": self.target_channels,
            "messagesCount": self.messages_count,
            "errorsCount": self.errors_count,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CrawlMetadata":
        return cls(
            crawl_id=d.get("crawlId", "") or "",
            execution_id=d.get("executionId", "") or "",
            start_time=parse_time(d.get("startTime")),
            end_time=parse_time(d.get("endTime")),
            status=d.get("status", "running") or "running",
            previous_crawl_id=list(d.get("previousCrawlId") or []),
            platform=d.get("platform", "") or "",
            target_channels=list(d.get("targetChannels") or []),
            messages_count=int(d.get("messagesCount") or 0),
            errors_count=int(d.get("errorsCount") or 0),
        )


@dataclass
class MediaCacheItem:
    """An entry in the media dedup cache (`state/datamodels.go:186-191`)."""

    id: str = ""
    first_seen: Optional[datetime] = None
    metadata: str = ""
    platform: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "firstSeen": format_time(self.first_seen),
            "metadata": self.metadata,
            "platform": self.platform,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MediaCacheItem":
        return cls(
            id=d.get("id", "") or "",
            first_seen=parse_time(d.get("firstSeen")),
            metadata=d.get("metadata", "") or "",
            platform=d.get("platform", "") or "",
        )


@dataclass
class State:
    """Complete crawl state snapshot (`state/datamodels.go:210-214`)."""

    layers: List[Layer] = field(default_factory=list)
    metadata: CrawlMetadata = field(default_factory=CrawlMetadata)
    last_updated: Optional[datetime] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "layers": [
                {"depth": l.depth, "pages": [p.to_dict() for p in l.pages]}
                for l in self.layers
            ],
            "metadata": self.metadata.to_dict(),
            "lastUpdated": format_time(self.last_updated),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "State":
        return cls(
            layers=[
                Layer(depth=int(l.get("depth") or 0),
                      pages=[Page.from_dict(p) for p in (l.get("pages") or [])])
                for l in (d.get("layers") or [])
            ],
            metadata=CrawlMetadata.from_dict(d.get("metadata") or {}),
            last_updated=parse_time(d.get("lastUpdated")),
        )
