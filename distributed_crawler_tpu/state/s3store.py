"""S3 object-store adapter: real cloud blob storage behind the chunker seam.

The reference shipped crawl output to Azure blob through its storage binding
(`state/daprstate.go:29-35`); this build's equivalent seam is
`state/objectstore.ObjectStoreClient`, and this module is its first real
cloud adapter.  No SDK is vendored (and none is installed in the image), so
the client speaks the S3 REST API directly over stdlib HTTP with AWS
Signature Version 4 request signing — which also makes it portable across
every S3-compatible store (AWS, GCS interop, MinIO, Ceph RGW) via the
``endpoint`` parameter.

Surface (the full :class:`~.objectstore.ObjectStoreClient` protocol):
put/get/head/list/delete plus multipart create/upload/complete/abort — the
part-level operations `ObjectStoreUploader` needs for retry+resume of the
chunker's 170 MiB combined files.

URL form (``make_object_client``):

    s3://bucket/optional/prefix?endpoint=http://127.0.0.1:9000&region=us-east-1

Credentials come from ``AWS_ACCESS_KEY_ID`` / ``AWS_SECRET_ACCESS_KEY``
(query-string overrides exist for hermetic tests only).  Custom endpoints
use path-style addressing (bucket in the path), the convention every
S3-compatible emulator expects; bare ``s3://bucket`` targets AWS with
virtual-host-style addressing.

Error taxonomy: 5xx / connection errors raise
:class:`~.objectstore.TransientStoreError` (the uploader retries those);
4xx raise ``ValueError`` (mis-signed, missing bucket — retrying can't fix
it); 404 on get/head returns ``None`` per the protocol.
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import hmac
import os
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple

from .objectstore import KeepAliveHttpTransport, TransientStoreError

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _uri_encode(s: str, encode_slash: bool) -> str:
    """AWS SigV4 URI encoding: RFC 3986 with '~' unreserved."""
    safe = "-._~" + ("" if encode_slash else "/")
    return urllib.parse.quote(s, safe=safe)


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode("utf-8"), hashlib.sha256).digest()


class SigV4Signer:
    """AWS Signature Version 4 for S3 (single-chunk payloads)."""

    def __init__(self, access_key: str, secret_key: str, region: str,
                 service: str = "s3"):
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.service = service

    def sign(self, method: str, host: str, path: str,
             query: List[Tuple[str, str]], payload_sha256: str,
             now: Optional[_dt.datetime] = None) -> Dict[str, str]:
        """Returns the headers to attach (Host excluded — http.client sets
        it; it IS part of the signature)."""
        now = now or _dt.datetime.now(_dt.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        canonical_query = "&".join(
            f"{_uri_encode(k, True)}={_uri_encode(v, True)}"
            for k, v in sorted(query))
        headers = {"host": host, "x-amz-content-sha256": payload_sha256,
                   "x-amz-date": amz_date}
        signed_names = ";".join(sorted(headers))
        canonical_headers = "".join(
            f"{k}:{headers[k].strip()}\n" for k in sorted(headers))
        canonical_request = "\n".join([
            method, _uri_encode(path, False) or "/", canonical_query,
            canonical_headers, signed_names, payload_sha256])
        scope = f"{datestamp}/{self.region}/{self.service}/aws4_request"
        string_to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical_request.encode("utf-8")).hexdigest()])
        key = _hmac(_hmac(_hmac(_hmac(
            ("AWS4" + self.secret_key).encode("utf-8"), datestamp),
            self.region), self.service), "aws4_request")
        signature = hmac.new(key, string_to_sign.encode("utf-8"),
                             hashlib.sha256).hexdigest()
        return {
            "x-amz-content-sha256": payload_sha256,
            "x-amz-date": amz_date,
            "Authorization": (
                f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
                f"SignedHeaders={signed_names}, Signature={signature}"),
        }


class S3ObjectClient:
    """`ObjectStoreClient` over the S3 REST API (stdlib HTTP + SigV4)."""

    def __init__(self, bucket: str, prefix: str = "",
                 endpoint: str = "", region: str = "us-east-1",
                 access_key: str = "", secret_key: str = "",
                 timeout_s: float = 30.0):
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.region = region
        self.timeout_s = timeout_s
        access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID", "")
        secret_key = secret_key or os.environ.get("AWS_SECRET_ACCESS_KEY", "")
        if not access_key or not secret_key:
            raise ValueError(
                "s3:// needs credentials: set AWS_ACCESS_KEY_ID / "
                "AWS_SECRET_ACCESS_KEY")
        self._signer = SigV4Signer(access_key, secret_key, region)
        if endpoint:
            u = urllib.parse.urlsplit(endpoint)
            tls = u.scheme == "https"
            host = u.netloc
            self._path_style = True  # emulators/MinIO convention
        else:
            tls = True
            host = f"{bucket}.s3.{region}.amazonaws.com"
            self._path_style = False
        self._host = host
        self._http = KeepAliveHttpTransport(host, tls, timeout_s, "s3")

    # -- transport ---------------------------------------------------------
    def _object_path(self, key: str) -> str:
        full = f"{self.prefix}/{key}" if self.prefix else key
        base = f"/{self.bucket}" if self._path_style else ""
        return f"{base}/{full}"

    def _bucket_path(self) -> str:
        return f"/{self.bucket}" if self._path_style else "/"

    def _request(self, method: str, path: str,
                 query: Optional[List[Tuple[str, str]]] = None,
                 body: bytes = b"") -> Tuple[int, Dict[str, str], bytes]:
        query = query or []
        payload_hash = (hashlib.sha256(body).hexdigest() if body
                        else _EMPTY_SHA256)
        headers = self._signer.sign(method, self._host, path, query,
                                    payload_hash)
        if body:
            headers["Content-Length"] = str(len(body))
        # The wire path/query must byte-match the canonical forms that were
        # signed (sorted query, SigV4 percent-encoding), or the server's
        # recomputed signature won't agree.
        qs = "&".join(f"{_uri_encode(k, True)}={_uri_encode(v, True)}"
                      for k, v in sorted(query))
        url = _uri_encode(path, False) + (f"?{qs}" if qs else "")
        return self._http.http_request(method, url, body, headers)

    def close(self) -> None:
        self._http.close()

    def _raise_for(self, status: int, method: str, path: str,
                   body: bytes) -> None:
        self._http.raise_for(status, method, path, body)

    # -- ObjectStoreClient protocol ---------------------------------------
    def put_object(self, key: str, data: bytes) -> None:
        status, _, body = self._request("PUT", self._object_path(key),
                                        body=data)
        self._raise_for(status, "PUT", key, body)

    def get_object(self, key: str) -> Optional[bytes]:
        status, _, body = self._request("GET", self._object_path(key))
        if status == 404:
            return None
        self._raise_for(status, "GET", key, body)
        return body

    def head_object(self, key: str) -> Optional[int]:
        status, headers, body = self._request("HEAD", self._object_path(key))
        if status == 404:
            return None
        self._raise_for(status, "HEAD", key, body)
        cl = {k.lower(): v for k, v in headers.items()}.get(
            "content-length")
        return int(cl) if cl is not None else 0

    def list_objects(self, prefix: str) -> List[str]:
        full_prefix = (f"{self.prefix}/{prefix}" if self.prefix
                       else prefix)
        keys: List[str] = []
        token = ""
        while True:
            query = [("list-type", "2"), ("prefix", full_prefix)]
            if token:
                query.append(("continuation-token", token))
            status, _, body = self._request("GET", self._bucket_path(),
                                            query=query)
            self._raise_for(status, "LIST", prefix, body)
            root = ET.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[:root.tag.index("}") + 1]
            for el in root.iter(f"{ns}Key"):
                k = el.text or ""
                if self.prefix and k.startswith(self.prefix + "/"):
                    k = k[len(self.prefix) + 1:]
                keys.append(k)
            truncated = root.find(f"{ns}IsTruncated")
            if truncated is None or truncated.text != "true":
                break
            nxt = root.find(f"{ns}NextContinuationToken")
            if nxt is None or not nxt.text:
                break
            token = nxt.text
        return sorted(keys)

    def delete_object(self, key: str) -> None:
        status, _, body = self._request("DELETE", self._object_path(key))
        if status == 404:
            return
        self._raise_for(status, "DELETE", key, body)

    # -- multipart (the uploader's retry/resume surface) -------------------
    def create_multipart(self, key: str) -> str:
        status, _, body = self._request("POST", self._object_path(key),
                                        query=[("uploads", "")])
        self._raise_for(status, "POST?uploads", key, body)
        root = ET.fromstring(body)
        ns = root.tag[:root.tag.index("}") + 1] if \
            root.tag.startswith("{") else ""
        el = root.find(f"{ns}UploadId")
        if el is None or not el.text:
            raise TransientStoreError(
                f"s3 create_multipart {key}: no UploadId in response")
        return el.text

    def upload_part(self, key: str, upload_id: str, part_no: int,
                    data: bytes) -> str:
        # The protocol's part_no is 0-based; S3 part numbers start at 1.
        status, headers, body = self._request(
            "PUT", self._object_path(key),
            query=[("partNumber", str(part_no + 1)),
                   ("uploadId", upload_id)], body=data)
        self._raise_for(status, "PUT?partNumber", key, body)
        etag = {k.lower(): v for k, v in headers.items()}.get("etag", "")
        if not etag:
            raise TransientStoreError(
                f"s3 upload_part {key}#{part_no}: no ETag returned")
        return etag

    def complete_multipart(self, key: str, upload_id: str,
                           etags: List[str]) -> None:
        parts_xml = "".join(
            f"<Part><PartNumber>{i + 1}</PartNumber>"
            f"<ETag>{etag}</ETag></Part>"
            for i, etag in enumerate(etags))
        payload = (f"<CompleteMultipartUpload>{parts_xml}"
                   f"</CompleteMultipartUpload>").encode("utf-8")
        status, _, body = self._request(
            "POST", self._object_path(key),
            query=[("uploadId", upload_id)], body=payload)
        self._raise_for(status, "POST?uploadId", key, body)
        # S3 can return 200 with an <Error> body for a failed complete.
        if b"<Error>" in body:
            raise TransientStoreError(
                f"s3 complete_multipart {key}: "
                f"{body[:300].decode('utf-8', 'replace')}")

    def abort_multipart(self, key: str, upload_id: str) -> None:
        status, _, body = self._request(
            "DELETE", self._object_path(key),
            query=[("uploadId", upload_id)])
        if status == 404:
            return
        self._raise_for(status, "DELETE?uploadId", key, body)


def parse_s3_url(url: str) -> S3ObjectClient:
    """``s3://bucket[/prefix]?endpoint=...&region=...`` → client.

    Query params: ``endpoint`` (S3-compatible base URL; empty = AWS),
    ``region``, and — FOR TESTS ONLY — ``access_key``/``secret_key``
    (production credentials belong in the environment, never in a URL that
    lands in logs and config files)."""
    u = urllib.parse.urlsplit(url)
    if u.scheme != "s3" or not u.netloc:
        raise ValueError(f"not an s3 URL: {url}")
    q = dict(urllib.parse.parse_qsl(u.query))
    return S3ObjectClient(
        bucket=u.netloc,
        prefix=u.path.strip("/"),
        endpoint=q.get("endpoint", ""),
        region=q.get("region", "us-east-1"),
        access_key=q.get("access_key", ""),
        secret_key=q.get("secret_key", ""),
    )
