"""State & storage layer: crawl state, posts/files, media cache, random-walk graph.

Parity with the reference's `state/` package (SURVEY.md §2 row "State interface
+ base" through "Dapr state manager"):
- `StateManager` ABC — the ~50-method `StateManagementInterface`
  (`state/interface.go:16-220`)
- `BaseStateManager` — in-memory layers/pages with URL dedup + max-pages
  deadend replacement (`state/base.go`)
- `LocalStateManager` — filesystem provider (`state/storageproviders.go`)
- `SqlGraphStore` — the random-walk graph + tandem validator queue the
  reference kept in PostgreSQL behind a Dapr binding (`state/daprstate.go:
  3076-4391`), here an in-tree SQL store with atomic claim semantics
- `ShardedMediaCache` — index + 5000-item shards + 30-day expiry
  (`state/daprstate.go:1252-1680`)
- `CompositeStateManager` — the full-featured manager combining all of the
  above (the `DaprStateManager` equivalent)
- `create_state_manager` factory (`state/statefactory.go`), replaceable for
  test mocking.
"""

from .base import BaseStateManager
from .composite import CompositeStateManager
from .datamodels import (
    CrawlMetadata,
    DiscoveredChannels,
    EdgeRecord,
    Layer,
    MediaCacheItem,
    Message,
    Page,
    PendingEdge,
    PendingEdgeBatch,
    PendingEdgeUpdate,
    State,
)
from .factory import create_state_manager, get_factory, set_factory
from .interface import LocalConfig, SqlConfig, StateConfig, StateManager
from .local import LocalStateManager
from .media_cache import ShardedMediaCache
from .providers import LocalStorageProvider, StorageProvider
from .sqlstore import SqliteBinding, SqlBinding, SqlGraphStore

__all__ = [
    "StateManager",
    "StateConfig",
    "LocalConfig",
    "SqlConfig",
    "BaseStateManager",
    "LocalStateManager",
    "CompositeStateManager",
    "ShardedMediaCache",
    "StorageProvider",
    "LocalStorageProvider",
    "SqlBinding",
    "SqliteBinding",
    "SqlGraphStore",
    "create_state_manager",
    "set_factory",
    "get_factory",
    "Page",
    "Message",
    "Layer",
    "State",
    "CrawlMetadata",
    "EdgeRecord",
    "PendingEdge",
    "PendingEdgeBatch",
    "PendingEdgeUpdate",
    "MediaCacheItem",
    "DiscoveredChannels",
]
