"""In-memory base state manager.

Parity with the reference's `BaseStateManager` (`state/base.go:15-552`):
layer map + page map behind a lock, URL dedup + max-pages deadend-replacement
in add_layer, message status tracking, crawl metadata, incomplete-crawl
detection, and the in-memory discovered-channels set.
"""

from __future__ import annotations

import dataclasses
import errno
import logging
import os
import shutil
import threading
from datetime import datetime
from typing import Any, Dict, List, Tuple

from ..datamodel import ChannelData, Post
from .datamodels import (
    PAGE_DEADEND,
    PAGE_FETCHED,
    CrawlMetadata,
    DiscoveredChannels,
    EdgeRecord,
    Layer,
    Message,
    Page,
    State,
    new_id,
    utcnow,
)
from .interface import StateConfig, StateManager

logger = logging.getLogger("dct.state")


class BaseStateManager(StateManager):
    """Common in-memory state shared by all backends (`state/base.go`)."""

    def __init__(self, config: StateConfig):
        self.config = config
        self._lock = threading.RLock()
        self.metadata = CrawlMetadata(
            crawl_id=config.crawl_id,
            execution_id=config.crawl_execution_id,
            start_time=utcnow(),
            status="running",
            platform=config.platform,
        )
        self.last_updated = utcnow()
        # depth -> [page IDs]
        self.layer_map: Dict[int, List[str]] = {}
        # page ID -> Page
        self.page_map: Dict[str, Page] = {}
        self.discovered_channels = DiscoveredChannels()
        self.edge_records: List[EdgeRecord] = []
        self._object_uploader = None  # built lazily from object_store_url

    # --- lifecycle -------------------------------------------------------
    def initialize(self, seed_urls: List[str]) -> None:
        """Create the depth-0 layer from seeds (`state/base.go:54-93`)."""
        with self._lock:
            self.layer_map.setdefault(0, [])
            for url in seed_urls:
                page = Page(id=new_id(), url=url, depth=0, timestamp=utcnow(),
                            platform=self.config.platform)
                if self.config.sampling_method == "random-walk":
                    # Each seed starts its own chain.
                    page.sequence_id = new_id()
                    self.discovered_channels.add(url)
                self.page_map[page.id] = page
                self.layer_map[0].append(page.id)
        logger.info("initialized state with %d seed URLs", len(seed_urls))

    def save_state(self) -> None:
        return None  # persistence is backend-specific

    def close(self) -> None:
        return None

    # --- pages -----------------------------------------------------------
    def get_page(self, page_id: str) -> Page:
        with self._lock:
            page = self.page_map.get(page_id)
            if page is None:
                raise KeyError(f"page with ID {page_id} not found")
            return page

    def update_page(self, page: Page) -> None:
        with self._lock:
            self.page_map[page.id] = page
            ids = self.layer_map.get(page.depth)
            if ids is not None and page.id not in ids:
                ids.append(page.id)

    def update_message(self, page_id: str, chat_id: int, message_id: int,
                       status: str) -> None:
        """Set a message's status, appending it if new (`state/base.go:182-215`)."""
        with self._lock:
            page = self.page_map.get(page_id)
            if page is None:
                raise KeyError(f"page with ID {page_id} not found")
            for m in page.messages:
                if m.chat_id == chat_id and m.message_id == message_id:
                    m.status = status
                    return
            page.messages.append(Message(chat_id=chat_id, message_id=message_id,
                                         status=status, page_id=page_id))

    # --- layers ----------------------------------------------------------
    def add_layer(self, pages: List[Page]) -> None:
        """Add pages at one depth with URL dedup and the max-pages
        deadend-replacement policy (`state/base.go:219-322`)."""
        if not pages:
            return
        with self._lock:
            total_existing = len(self.page_map)
            deadend_count = sum(1 for p in self.page_map.values()
                                if p.status == PAGE_DEADEND)
            max_pages = self.config.max_pages
            max_reached = max_pages > 0 and total_existing >= max_pages
            if max_reached:
                logger.info(
                    "maximum page limit reached (%d/%d), only adding replacements "
                    "for %d deadend pages", total_existing, max_pages, deadend_count)

            # Random-walk deliberately allows revisiting a URL — a walk may
            # legitimately return to a channel (`daprstate.go:648-656`).
            dedup_urls = self.config.sampling_method != "random-walk"
            existing_urls = {p.url: pid for pid, p in self.page_map.items()}
            depth = pages[0].depth
            self.layer_map.setdefault(depth, [])
            replacements_available = deadend_count
            added = 0
            for page in pages:
                if dedup_urls and page.url in existing_urls:
                    continue
                if max_reached:
                    if replacements_available <= 0:
                        continue
                    replacements_available -= 1
                if not page.id:
                    page.id = new_id()
                if page.timestamp is None:
                    page.timestamp = utcnow()
                self.page_map[page.id] = page
                existing_urls[page.url] = page.id
                self.layer_map[depth].append(page.id)
                added += 1
            logger.debug("added %d unique pages to depth %d (filtered %d duplicates)",
                         added, depth, len(pages) - added)

    def get_layer_by_depth(self, depth: int) -> List[Page]:
        with self._lock:
            ids = self.layer_map.get(depth, [])
            return [self.page_map[i] for i in ids if i in self.page_map]

    def get_max_depth(self) -> int:
        with self._lock:
            if not self.layer_map:
                raise LookupError("no layers found")
            return max(self.layer_map)

    def export_pages_to_binding(self, crawl_id: str) -> None:
        return None  # backend-specific

    # --- state snapshot --------------------------------------------------
    def get_state(self) -> State:
        """Consistent snapshot with copied pages (`state/base.go:345-372` —
        Go returns value copies; we must copy explicitly so serialization
        outside the lock can't observe torn in-place mutations)."""
        with self._lock:
            def copy_page(p: Page) -> Page:
                return dataclasses.replace(
                    p, messages=[dataclasses.replace(m) for m in p.messages])

            layers = [
                Layer(depth=d, pages=[copy_page(self.page_map[i])
                                      for i in ids if i in self.page_map])
                for d, ids in sorted(self.layer_map.items())
            ]
            return State(layers=layers,
                         metadata=dataclasses.replace(
                             self.metadata,
                             previous_crawl_id=list(self.metadata.previous_crawl_id),
                             target_channels=list(self.metadata.target_channels)),
                         last_updated=self.last_updated)

    def set_state(self, state: State) -> None:
        """Replace in-memory state (`state/base.go:375-397`)."""
        with self._lock:
            self.metadata = state.metadata
            self.last_updated = utcnow()
            self.layer_map = {}
            self.page_map = {}
            for layer in state.layers:
                self.layer_map[layer.depth] = []
                for page in layer.pages:
                    self.page_map[page.id] = page
                    self.layer_map[layer.depth].append(page.id)

    # --- crawl management ------------------------------------------------
    def get_previous_crawls(self) -> List[str]:
        with self._lock:
            return list(self.metadata.previous_crawl_id)

    def update_crawl_metadata(self, crawl_id: str, metadata: Dict[str, Any]) -> None:
        """`state/base.go:408-443`."""
        with self._lock:
            if self.metadata.crawl_id != crawl_id:
                raise ValueError("cannot update metadata for a different crawl ID")
            for key, value in metadata.items():
                if key == "status" and isinstance(value, str):
                    self.metadata.status = value
                elif key == "endTime":
                    from ..datamodel.post import parse_time
                    if isinstance(value, datetime):
                        self.metadata.end_time = value
                    elif isinstance(value, str):
                        self.metadata.end_time = parse_time(value)
                elif key == "previousCrawlID":
                    if isinstance(value, str):
                        self.metadata.previous_crawl_id.append(value)
                    elif isinstance(value, list):
                        self.metadata.previous_crawl_id.extend(value)
                elif key == "messagesCount" and isinstance(value, int):
                    self.metadata.messages_count = value
                elif key == "errorsCount" and isinstance(value, int):
                    self.metadata.errors_count = value
            self.last_updated = utcnow()

    def find_incomplete_crawl(self, crawl_id: str) -> Tuple[str, bool]:
        """`state/base.go:466-516`: incomplete if status != completed, or any
        page isn't fetched."""
        with self._lock:
            if self.metadata.crawl_id == crawl_id:
                if self.metadata.status != "completed" and self.metadata.execution_id:
                    return self.metadata.execution_id, True
                for ids in self.layer_map.values():
                    for pid in ids:
                        page = self.page_map.get(pid)
                        if page is not None and page.status != PAGE_FETCHED:
                            return self.metadata.execution_id, True
            return "", False

    # --- media cache (backend-specific; in-memory default) ----------------
    def has_processed_media(self, media_id: str) -> bool:
        return False

    def mark_media_as_processed(self, media_id: str) -> None:
        return None

    # --- post/file storage (backend-specific) -----------------------------
    def store_post(self, channel_id: str, post: Post) -> None:
        raise NotImplementedError

    def store_file(self, channel_id: str, source_file_path: str,
                   file_name: str) -> Tuple[str, str]:
        raise NotImplementedError

    # --- discovered channels ----------------------------------------------
    def initialize_discovered_channels(self) -> None:
        return None

    def _random_walk_pick(self) -> str:
        """Source of random seed candidates; backends override."""
        return self.get_random_discovered_channel()

    def initialize_random_walk_layer(self) -> None:
        """Seed layer 0 (each seed starting its own chain) with seed_size
        distinct random channels from `_random_walk_pick`."""
        picks: List[str] = []
        seen = set()
        want = self.config.seed_size
        attempts = 0
        while len(picks) < want and attempts < want * 20 + 20:
            attempts += 1
            try:
                c = self._random_walk_pick()
            except LookupError:
                break
            if c not in seen:
                seen.add(c)
                picks.append(c)
        if picks:
            BaseStateManager.initialize(self, picks)

    def get_random_discovered_channel(self) -> str:
        return self.discovered_channels.random()

    def is_discovered_channel(self, channel_id: str) -> bool:
        return self.discovered_channels.contains(channel_id)

    def add_discovered_channel(self, channel_id: str) -> None:
        self.discovered_channels.add(channel_id)

    def store_channel_data(self, channel_id: str, channel_data: ChannelData) -> None:
        return None

    # --- random-walk graph (in-memory default) ----------------------------
    def save_edge_records(self, edges: List[EdgeRecord]) -> None:
        with self._lock:
            self.edge_records.extend(edges)

    def get_pages_from_page_buffer(self, limit: int) -> List[Page]:
        raise NotImplementedError

    def execute_database_operation(self, sql_query: str, params: List[Any]) -> None:
        raise NotImplementedError

    def add_page_to_page_buffer(self, page: Page) -> None:
        raise NotImplementedError

    def delete_page_buffer_pages(self, page_ids: List[str], page_urls: List[str]) -> None:
        raise NotImplementedError

    # --- combined-file upload (the blob output binding) --------------------
    def object_uploader(self):
        """Lazily-built `ObjectStoreUploader` from ``object_store_url``;
        None when no remote store is configured (combined files then stay
        local, the pre-binding behavior)."""
        if self._object_uploader is None and self.config.object_store_url:
            from .objectstore import ObjectStoreUploader, make_object_client

            self._object_uploader = ObjectStoreUploader(
                make_object_client(self.config.object_store_url))
        return self._object_uploader

    def upload_combined_file(self, filename: str) -> None:
        """Ship a chunker-combined file to the object store under
        ``combined/<crawl>/<basename>`` (`chunk/main.go:349-421` uploaded
        through the Dapr blob binding the same way).

        Without a remote store configured, the file is MOVED into
        ``{storage_root}/combined/<crawl>/`` — the localstorage-binding
        analog (`resources/local-storage.yaml`) — because the chunker
        deletes its working copy after a successful upload; a plain no-op
        here would silently destroy every combined file."""
        crawl = (self.config.crawl_execution_id or self.config.crawl_id
                 or "adhoc")
        uploader = self.object_uploader()
        if uploader is not None:
            key = f"combined/{crawl}/{os.path.basename(filename)}"
            uploader.upload_file(filename, key)
            return
        dest_dir = os.path.join(self.config.storage_root or ".",
                                "combined", crawl)
        os.makedirs(dest_dir, exist_ok=True)
        dest = os.path.join(dest_dir, os.path.basename(filename))
        if os.path.abspath(dest) == os.path.abspath(filename):
            return
        try:
            os.replace(filename, dest)  # same-fs: one atomic rename
        except OSError as e:
            if e.errno != errno.EXDEV:
                raise
            # The chunker's write dir (often /tmp) and storage_root may be
            # different filesystems.  Keep the all-or-nothing contract:
            # copy to a same-fs temp name, atomically publish, THEN drop
            # the source — a crash mid-copy never leaves a truncated file
            # under the final name.
            tmp = dest + ".tmp"
            shutil.copy2(filename, tmp)
            os.replace(tmp, dest)
            os.unlink(filename)
