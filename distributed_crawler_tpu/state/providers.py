"""Storage providers: the byte-level backends behind state managers.

The reference reached blob/local storage through Dapr output bindings
(`state/daprstate.go:29-35,1106-1249`); this build keeps the same provider
seam in-tree so posts/files/state land in identical layouts (JSONL per
channel, state.json/metadata.json/media-cache.json per crawl,
`state/storageproviders.go:245-344,592-647`).
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from typing import Any, Dict, List, Optional, Protocol, runtime_checkable


@runtime_checkable
class StorageProvider(Protocol):
    """Minimal byte/JSON storage surface used by state managers."""

    def save_json(self, rel_path: str, data: Any) -> None: ...

    def load_json(self, rel_path: str) -> Optional[Any]: ...

    def append_jsonl(self, rel_path: str, line: str) -> None: ...

    def put_text(self, rel_path: str, text: str) -> None: ...

    def get_text(self, rel_path: str) -> Optional[str]: ...

    def store_file(self, rel_path: str, source_path: str,
                   delete_source: bool = True) -> str: ...

    def exists(self, rel_path: str) -> bool: ...

    def list_dir(self, rel_path: str) -> List[str]: ...

    def delete(self, rel_path: str) -> None: ...

    def flush(self) -> None:
        """Push any client-side write buffering to durable storage.
        A no-op for providers that write through (local FS); the object
        store batches appends and relies on this at shutdown."""
        ...


class LocalStorageProvider:
    """Filesystem provider (`state/storageproviders.go:17-72`)."""

    def __init__(self, base_path: str):
        self.base_path = base_path
        os.makedirs(base_path, exist_ok=True)
        self._lock = threading.Lock()

    def flush(self) -> None:  # writes go straight to disk
        pass

    def _abs(self, rel_path: str) -> str:
        return os.path.join(self.base_path, rel_path)

    def save_json(self, rel_path: str, data: Any) -> None:
        path = self._abs(rel_path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f, ensure_ascii=False)
        os.replace(tmp, path)  # atomic on POSIX

    def load_json(self, rel_path: str) -> Optional[Any]:
        path = self._abs(rel_path)
        if not os.path.exists(path):
            return None
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)

    def append_jsonl(self, rel_path: str, line: str) -> None:
        path = self._abs(rel_path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # The lock's PURPOSE is to serialize this append: interleaved
        # writers would corrupt the JSONL stream, so the file I/O is the
        # critical section (not incidental work done under it).
        with self._lock:
            with open(path, "a", encoding="utf-8") as f:  # crawlint: disable=LCK002
                f.write(line.rstrip("\n") + "\n")

    def put_text(self, rel_path: str, text: str) -> None:
        """Atomic whole-file write (temp + rename): rewriting the same path
        with the same content is idempotent, the basis of exactly-once-ish
        result writeback (SURVEY.md §7 hard part (d))."""
        path = self._abs(rel_path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(text)
        os.replace(tmp, path)

    def get_text(self, rel_path: str) -> Optional[str]:
        path = self._abs(rel_path)
        if not os.path.exists(path):
            return None
        with open(path, "r", encoding="utf-8") as f:
            return f.read()

    def store_file(self, rel_path: str, source_path: str,
                   delete_source: bool = True) -> str:
        """Copy then delete source (`state/storageproviders.go:301-344`)."""
        dest = self._abs(rel_path)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        shutil.copy2(source_path, dest)
        if delete_source:
            try:
                os.remove(source_path)
            except OSError:
                pass
        return dest

    def exists(self, rel_path: str) -> bool:
        return os.path.exists(self._abs(rel_path))

    def list_dir(self, rel_path: str) -> List[str]:
        path = self._abs(rel_path)
        if not os.path.isdir(path):
            return []
        return sorted(os.listdir(path))

    def delete(self, rel_path: str) -> None:
        path = self._abs(rel_path)
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)


class InMemoryStorageProvider:
    """Test double recording every write — the analog of the reference's fake
    Dapr client (`state/export_test.go:24-110`)."""

    def __init__(self):
        self.json_store: Dict[str, Any] = {}
        self.jsonl_store: Dict[str, List[str]] = {}
        self.flushes = 0
        self.text_store: Dict[str, str] = {}
        self.files: Dict[str, bytes] = {}
        self.calls: List[tuple] = []

    def flush(self) -> None:
        self.calls.append(("flush", ""))
        self.flushes += 1

    def save_json(self, rel_path: str, data: Any) -> None:
        self.calls.append(("save_json", rel_path))
        self.json_store[rel_path] = json.loads(json.dumps(data))

    def load_json(self, rel_path: str) -> Optional[Any]:
        self.calls.append(("load_json", rel_path))
        return self.json_store.get(rel_path)

    def append_jsonl(self, rel_path: str, line: str) -> None:
        self.calls.append(("append_jsonl", rel_path))
        if rel_path in self.text_store:
            # Appending to a put_text file: byte-append exactly as the
            # filesystem provider would (no line re-normalization of the
            # prior content).
            self.text_store[rel_path] += line.rstrip("\n") + "\n"
            return
        self.jsonl_store.setdefault(rel_path, []).append(line.rstrip("\n"))

    def put_text(self, rel_path: str, text: str) -> None:
        # Byte-exact round trip, matching LocalStorageProvider's atomic
        # whole-file write (no line normalization).
        self.calls.append(("put_text", rel_path))
        self.text_store[rel_path] = text
        self.jsonl_store.pop(rel_path, None)  # put_text overwrites appends

    def get_text(self, rel_path: str) -> Optional[str]:
        if rel_path in self.text_store:
            return self.text_store[rel_path]
        lines = self.jsonl_store.get(rel_path)
        if lines is None:
            return None
        return "\n".join(lines) + "\n"

    def store_file(self, rel_path: str, source_path: str,
                   delete_source: bool = True) -> str:
        self.calls.append(("store_file", rel_path, source_path))
        with open(source_path, "rb") as f:
            self.files[rel_path] = f.read()
        if delete_source:
            try:
                os.remove(source_path)
            except OSError:
                pass
        return rel_path

    def exists(self, rel_path: str) -> bool:
        return (rel_path in self.json_store or rel_path in self.jsonl_store
                or rel_path in self.text_store or rel_path in self.files)

    def list_dir(self, rel_path: str) -> List[str]:
        prefix = rel_path.rstrip("/") + "/"
        names = set()
        for key in (list(self.json_store) + list(self.jsonl_store)
                    + list(self.text_store) + list(self.files)):
            if key.startswith(prefix):
                names.add(key[len(prefix):].split("/", 1)[0])
        return sorted(names)

    def delete(self, rel_path: str) -> None:
        self.json_store.pop(rel_path, None)
        self.jsonl_store.pop(rel_path, None)
        self.text_store.pop(rel_path, None)
        self.files.pop(rel_path, None)
