"""The state-management interface every backend implements.

Parity with the reference's `StateManagementInterface`
(`state/interface.go:16-220`): initialization/resume, page+layer ops, post and
file storage, media cache, random-walk graph ops, tandem validator queue ops,
and edge repair.  Method names are the snake_case forms of the reference's.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from datetime import datetime
from typing import Any, Dict, List, Optional, Tuple

from ..datamodel import ChannelData, Post
from .datamodels import (
    EdgeRecord,
    Page,
    PendingEdge,
    PendingEdgeBatch,
    PendingEdgeUpdate,
)


@dataclass
class LocalConfig:
    """Local-filesystem backend config (`state/interface.go:324-328`)."""

    base_path: str = ""


@dataclass
class SqlConfig:
    """SQL graph-store config — replaces the reference's Dapr postgres binding
    (`state/interface.go:306-320`).  ``url`` is a sqlite path (default) or a
    DB-API connection string for an external engine; ":memory:" for tests."""

    url: str = ""
    echo_sql: bool = False


@dataclass
class StateConfig:
    """Common config for all state managers (`state/interface.go:243-290`)."""

    storage_root: str = ""
    crawl_id: str = ""
    crawl_label: str = ""
    crawl_execution_id: str = ""
    platform: str = "telegram"
    sampling_method: str = "channel"
    seed_size: int = 0
    max_pages: int = 0  # 0 = unlimited
    local: Optional[LocalConfig] = None
    sql: Optional[SqlConfig] = None
    combine_files: bool = False
    combine_watch_dir: str = ""
    combine_temp_dir: str = ""
    # Remote blob target for combined files / results ("memory://",
    # "file:///path", or a cloud scheme once an SDK adapter is wired) —
    # the Dapr output-binding analog (`state/daprstate.go:29-35`).
    object_store_url: str = ""
    extra: Dict[str, Any] = field(default_factory=dict)


class StateManager(abc.ABC):
    """Abstract state manager (`state/interface.go:16-220`)."""

    # --- lifecycle -------------------------------------------------------
    @abc.abstractmethod
    def initialize(self, seed_urls: List[str]) -> None:
        """Set up state with seed data or load existing state."""

    @abc.abstractmethod
    def save_state(self) -> None:
        """Persist current state to the backend."""

    @abc.abstractmethod
    def close(self) -> None:
        """Cleanup on shutdown."""

    # --- pages / layers --------------------------------------------------
    @abc.abstractmethod
    def get_page(self, page_id: str) -> Page: ...

    @abc.abstractmethod
    def update_page(self, page: Page) -> None: ...

    @abc.abstractmethod
    def update_message(self, page_id: str, chat_id: int, message_id: int,
                       status: str) -> None: ...

    @abc.abstractmethod
    def add_layer(self, pages: List[Page]) -> None: ...

    @abc.abstractmethod
    def get_layer_by_depth(self, depth: int) -> List[Page]: ...

    @abc.abstractmethod
    def get_max_depth(self) -> int: ...

    @abc.abstractmethod
    def export_pages_to_binding(self, crawl_id: str) -> None: ...

    # --- data storage ----------------------------------------------------
    @abc.abstractmethod
    def store_post(self, channel_id: str, post: Post) -> None: ...

    @abc.abstractmethod
    def store_file(self, channel_id: str, source_file_path: str,
                   file_name: str) -> Tuple[str, str]:
        """Store a media file; returns (stored_path, filename)."""

    # --- crawl management ------------------------------------------------
    @abc.abstractmethod
    def get_previous_crawls(self) -> List[str]: ...

    @abc.abstractmethod
    def update_crawl_metadata(self, crawl_id: str, metadata: Dict[str, Any]) -> None: ...

    @abc.abstractmethod
    def find_incomplete_crawl(self, crawl_id: str) -> Tuple[str, bool]:
        """Returns (execution_id, exists)."""

    # --- media cache -----------------------------------------------------
    @abc.abstractmethod
    def has_processed_media(self, media_id: str) -> bool: ...

    @abc.abstractmethod
    def mark_media_as_processed(self, media_id: str) -> None: ...

    # --- random-walk: seed channels -------------------------------------
    def load_seed_channels(self) -> None:
        return None

    def upsert_seed_channel_chat_id(self, username: str, chat_id: int) -> None:
        return None

    def get_cached_chat_id(self, username: str) -> Tuple[int, bool]:
        return 0, False

    def is_seed_channel(self, username: str) -> bool:
        return False

    def get_channel_last_crawled(self, username: str) -> Optional[datetime]:
        return None

    def mark_channel_crawled(self, username: str, chat_id: int) -> None:
        return None

    def mark_seed_channel_invalid(self, username: str) -> None:
        return None

    def get_random_seed_channel(self) -> str:
        raise NotImplementedError

    # --- random-walk: invalid channels -----------------------------------
    def load_invalid_channels(self) -> None:
        return None

    def is_invalid_channel(self, username: str) -> bool:
        return False

    def mark_channel_invalid(self, username: str, reason: str) -> None:
        return None

    # --- random-walk: discovered channels --------------------------------
    @abc.abstractmethod
    def initialize_discovered_channels(self) -> None: ...

    @abc.abstractmethod
    def initialize_random_walk_layer(self) -> None: ...

    @abc.abstractmethod
    def get_random_discovered_channel(self) -> str: ...

    @abc.abstractmethod
    def is_discovered_channel(self, channel_id: str) -> bool: ...

    @abc.abstractmethod
    def add_discovered_channel(self, channel_id: str) -> None: ...

    @abc.abstractmethod
    def store_channel_data(self, channel_id: str, channel_data: ChannelData) -> None: ...

    # --- random-walk: graph database --------------------------------------
    @abc.abstractmethod
    def save_edge_records(self, edges: List[EdgeRecord]) -> None: ...

    @abc.abstractmethod
    def get_pages_from_page_buffer(self, limit: int) -> List[Page]: ...

    @abc.abstractmethod
    def execute_database_operation(self, sql_query: str, params: List[Any]) -> None: ...

    @abc.abstractmethod
    def add_page_to_page_buffer(self, page: Page) -> None: ...

    @abc.abstractmethod
    def delete_page_buffer_pages(self, page_ids: List[str], page_urls: List[str]) -> None: ...

    # --- combined files --------------------------------------------------
    def upload_combined_file(self, filename: str) -> None:
        return None

    # --- tandem validator -------------------------------------------------
    def create_pending_batch(self, batch: PendingEdgeBatch) -> None:
        raise NotImplementedError

    def insert_pending_edge(self, edge: PendingEdge) -> None:
        raise NotImplementedError

    def close_pending_batch(self, batch_id: str) -> None:
        raise NotImplementedError

    def claim_pending_edges(self, limit: int) -> List[PendingEdge]:
        raise NotImplementedError

    def update_pending_edge(self, update: PendingEdgeUpdate) -> None:
        raise NotImplementedError

    def claim_walkback_batch(self) -> Tuple[Optional[PendingEdgeBatch], List[PendingEdge]]:
        raise NotImplementedError

    def complete_pending_batch(self, batch_id: str) -> None:
        raise NotImplementedError

    def recover_stale_batch_claims(self, stale_threshold_s: float) -> int:
        raise NotImplementedError

    def recover_stale_edge_claims(self, stale_threshold_s: float) -> int:
        raise NotImplementedError

    def recover_orphan_edges(self) -> int:
        raise NotImplementedError

    def flush_batch_stats(self, batch_id: str, crawl_id: str,
                          edges: List[PendingEdge]) -> None:
        raise NotImplementedError

    def claim_discovered_channel(self, username: str, crawl_id: str) -> bool:
        raise NotImplementedError

    def is_channel_discovered(self, username: str) -> bool:
        raise NotImplementedError

    def count_incomplete_batches(self, crawl_id: str) -> int:
        raise NotImplementedError

    def insert_access_event(self, reason: str) -> None:
        raise NotImplementedError

    # --- edge repair (400-replacement) ------------------------------------
    def get_edge_record(self, sequence_id: str, destination_channel: str) -> Optional[EdgeRecord]:
        raise NotImplementedError

    def delete_edge_record(self, sequence_id: str, destination_channel: str) -> None:
        raise NotImplementedError

    def get_random_skipped_edge(self, sequence_id: str, source_channel: str) -> Optional[EdgeRecord]:
        raise NotImplementedError

    def promote_edge(self, sequence_id: str, destination_channel: str) -> None:
        raise NotImplementedError
