"""The full-featured state manager: persistence + SQL graph + media cache.

This is the TPU build's equivalent of the reference's `DaprStateManager`
(`state/daprstate.go`, 4391 LoC): instead of a sidecar (KV state store +
storage bindings + postgres binding over gRPC) it composes in-tree parts
behind the same interface:

- page/layer/metadata persistence through a StorageProvider
  (`daprstate.go:284,897,1703,2768`)
- JSONL posts + media files through the same provider
  (`daprstate.go:1106-1249`)
- sharded media cache with 30-day expiry (`daprstate.go:1252-1680`)
- URL dedup cache spanning previous crawls (`daprstate.go:550-624,2700`)
- the random-walk graph + tandem queue in SqlGraphStore
  (`daprstate.go:3076-4391`)
- in-memory caches: seed-channel chat IDs, seed membership, invalid channels
  (`daprstate.go:48-70`)
"""

from __future__ import annotations

import logging
import os
import threading
from datetime import datetime
from typing import Any, Dict, List, Optional, Tuple

from ..datamodel import ChannelData
from .datamodels import (
    EdgeRecord,
    Page,
    PendingEdge,
    PendingEdgeBatch,
    PendingEdgeUpdate,
    State,
    new_id,
)
from .interface import StateConfig
from .local import LocalStateManager
from .providers import StorageProvider
from .sqlstore import SqlGraphStore, SqliteBinding

logger = logging.getLogger("dct.state.composite")


class CompositeStateManager(LocalStateManager):
    """Full state manager: LocalStateManager persistence + SQL graph store."""

    def __init__(self, config: StateConfig,
                 provider: Optional[StorageProvider] = None,
                 graph: Optional[SqlGraphStore] = None):
        super().__init__(config, provider=provider)
        if graph is None:
            url = config.sql.url if config.sql else ""
            if not url:
                # The graph must survive the process: discovered_channels is a
                # cross-crawl exactly-once claim registry (sql/schema.sql).
                url = (os.path.join(config.storage_root, "graph.db")
                       if config.storage_root else ":memory:")
            graph = SqlGraphStore(SqliteBinding(url), config.crawl_id)
            graph.ensure_schema()
        self.graph = graph
        self._cache_lock = threading.RLock()
        # username -> chat ID (`daprstate.go` seed chat-ID cache)
        self._chat_id_cache: Dict[str, int] = {}
        self._seed_channels: set = set()
        self._invalid_channels: set = set()
        # URL -> crawl_id where first seen (dedup across crawls)
        self._url_cache: Dict[str, str] = {}

    # --- resume + URL cache ----------------------------------------------
    def _hydrate_url_cache(self) -> None:
        """Load URLs processed by previous crawl executions
        (`daprstate.go:550-624,2700`)."""
        with self._cache_lock:
            meta = self.provider.load_json(self._metadata_path())
            for prev_id in (meta or {}).get("previousCrawlId") or []:
                prev_state = self.provider.load_json(f"{prev_id}/state.json")
                if not prev_state:
                    continue
                for layer in prev_state.get("layers") or []:
                    for p in layer.get("pages") or []:
                        if p.get("url"):
                            self._url_cache.setdefault(p["url"], prev_id)

    def initialize(self, seed_urls: List[str]) -> None:
        """Resume persisted state, skipping seed URLs a previous crawl already
        processed (`daprstate.go:487-500`), and hydrate the cross-crawl URL
        cache."""
        self._hydrate_url_cache()
        if self.config.sampling_method != "random-walk":
            skipped = [u for u in seed_urls if self.seen_url(u)]
            if skipped:
                logger.info("skipping %d seed URLs already processed in "
                            "previous crawls", len(skipped))
            seed_urls = [u for u in seed_urls if u not in set(skipped)]
        super().initialize(seed_urls)
        with self._cache_lock:
            for page in self.page_map.values():
                self._url_cache.setdefault(page.url, self.config.crawl_id)

    def add_layer(self, pages: List[Page]) -> None:
        super().add_layer(pages)
        with self._cache_lock:
            for page in pages:
                if page.url:
                    self._url_cache.setdefault(page.url, self.config.crawl_id)

    def seen_url(self, url: str) -> bool:
        with self._cache_lock:
            return url in self._url_cache

    # --- seed channels ----------------------------------------------------
    def load_seed_channels(self) -> None:
        """Hydrate discovered set + chat-ID cache from seed_channels
        (`state/interface.go:80-82`)."""
        rows = self.graph.load_seed_channels()
        with self._cache_lock:
            for username, chat_id in rows:
                self._seed_channels.add(username)
                if chat_id:
                    self._chat_id_cache[username] = int(chat_id)
                self.discovered_channels.add(username)
        logger.info("loaded %d seed channels", len(rows),
                    extra={"log_tag": "rw_pool"})

    def upsert_seed_channel_chat_id(self, username: str, chat_id: int) -> None:
        with self._cache_lock:
            self._chat_id_cache[username] = chat_id
        self.graph.upsert_seed_channel_chat_id(username, chat_id)

    def get_cached_chat_id(self, username: str) -> Tuple[int, bool]:
        with self._cache_lock:
            chat_id = self._chat_id_cache.get(username)
            return (chat_id, True) if chat_id is not None else (0, False)

    def is_seed_channel(self, username: str) -> bool:
        with self._cache_lock:
            return username in self._seed_channels

    def get_channel_last_crawled(self, username: str) -> Optional[datetime]:
        return self.graph.get_channel_last_crawled(username)

    def mark_channel_crawled(self, username: str, chat_id: int) -> None:
        with self._cache_lock:
            if chat_id:
                self._chat_id_cache[username] = chat_id
        self.graph.mark_channel_crawled(username, chat_id)

    def mark_seed_channel_invalid(self, username: str) -> None:
        self.graph.mark_seed_channel_invalid(username)

    def get_random_seed_channel(self) -> str:
        username = self.graph.get_random_seed_channel()
        if username is None:
            raise LookupError("no seed channels available")
        return username

    # --- invalid channels -------------------------------------------------
    def load_invalid_channels(self) -> None:
        rows = self.graph.load_invalid_channels()
        with self._cache_lock:
            self._invalid_channels.update(rows)
        logger.info("loaded %d invalid channels", len(rows),
                    extra={"log_tag": "rw_pool"})

    def is_invalid_channel(self, username: str) -> bool:
        with self._cache_lock:
            return username in self._invalid_channels

    def mark_channel_invalid(self, username: str, reason: str) -> None:
        with self._cache_lock:
            self._invalid_channels.add(username)
        self.graph.mark_channel_invalid(username, reason)

    # --- discovered channels ---------------------------------------------
    def initialize_discovered_channels(self) -> None:
        """Hydrate the in-memory set from discovered_channels
        (`state/interface.go:91-93`)."""
        for username in self.graph.load_discovered_channels():
            self.discovered_channels.add(username)

    def add_discovered_channel(self, channel_id: str) -> None:
        self.discovered_channels.add(channel_id)
        self.graph.add_discovered_channel(channel_id, self.config.crawl_id)

    def claim_discovered_channel(self, username: str, crawl_id: str) -> bool:
        won = self.graph.claim_discovered_channel(username, crawl_id)
        if won:
            self.discovered_channels.add(username)
        return won

    def is_channel_discovered(self, username: str) -> bool:
        if self.discovered_channels.contains(username):
            return True
        return self.graph.is_channel_discovered(username)

    def _random_walk_pick(self) -> str:
        # Random-walk layers draw from the persistent seed pool, not the
        # in-memory discovered set (`daprstate.go` GetRandomSeedChannel).
        return self.get_random_seed_channel()

    def store_channel_data(self, channel_id: str, channel_data: ChannelData) -> None:
        """Persist channel metadata JSON next to the channel's posts
        (`daprstate.go` StoreChannelData analog)."""
        self.provider.save_json(
            f"{self.config.crawl_id}/{channel_id}/channel.json",
            channel_data.to_dict())

    # --- random-walk graph delegation -------------------------------------
    def save_edge_records(self, edges: List[EdgeRecord]) -> None:
        self.graph.save_edge_records(edges)

    def get_pages_from_page_buffer(self, limit: int) -> List[Page]:
        return self.graph.get_pages_from_page_buffer(limit)

    def execute_database_operation(self, sql_query: str, params: List[Any]) -> None:
        self.graph.execute(sql_query, params or [])

    def add_page_to_page_buffer(self, page: Page) -> None:
        if not page.id:
            page.id = new_id()
        self.graph.add_page_to_page_buffer(page)

    def delete_page_buffer_pages(self, page_ids: List[str],
                                 page_urls: List[str]) -> None:
        self.graph.delete_page_buffer_pages(page_ids, page_urls)

    # --- tandem validator delegation ---------------------------------------
    def create_pending_batch(self, batch: PendingEdgeBatch) -> None:
        self.graph.create_pending_batch(batch)

    def insert_pending_edge(self, edge: PendingEdge) -> None:
        self.graph.insert_pending_edge(edge)

    def close_pending_batch(self, batch_id: str) -> None:
        self.graph.close_pending_batch(batch_id)

    def claim_pending_edges(self, limit: int) -> List[PendingEdge]:
        return self.graph.claim_pending_edges(limit)

    def update_pending_edge(self, update: PendingEdgeUpdate) -> None:
        self.graph.update_pending_edge(update)

    def claim_walkback_batch(self) -> Tuple[Optional[PendingEdgeBatch],
                                            List[PendingEdge]]:
        return self.graph.claim_walkback_batch()

    def complete_pending_batch(self, batch_id: str) -> None:
        self.graph.complete_pending_batch(batch_id)

    def recover_stale_batch_claims(self, stale_threshold_s: float) -> int:
        return self.graph.recover_stale_batch_claims(stale_threshold_s)

    def recover_stale_edge_claims(self, stale_threshold_s: float) -> int:
        return self.graph.recover_stale_edge_claims(stale_threshold_s)

    def recover_orphan_edges(self) -> int:
        return self.graph.recover_orphan_edges()

    def flush_batch_stats(self, batch_id: str, crawl_id: str,
                          edges: List[PendingEdge]) -> None:
        self.graph.flush_batch_stats(batch_id, crawl_id, edges)

    def count_incomplete_batches(self, crawl_id: str) -> int:
        return self.graph.count_incomplete_batches(crawl_id)

    def insert_access_event(self, reason: str) -> None:
        self.graph.insert_access_event(reason)

    # --- edge repair -------------------------------------------------------
    def get_edge_record(self, sequence_id: str,
                        destination_channel: str) -> Optional[EdgeRecord]:
        return self.graph.get_edge_record(sequence_id, destination_channel)

    def delete_edge_record(self, sequence_id: str, destination_channel: str) -> None:
        self.graph.delete_edge_record(sequence_id, destination_channel)

    def get_random_skipped_edge(self, sequence_id: str,
                                source_channel: str) -> Optional[EdgeRecord]:
        return self.graph.get_random_skipped_edge(sequence_id, source_channel)

    def promote_edge(self, sequence_id: str, destination_channel: str) -> None:
        self.graph.promote_edge(sequence_id, destination_channel)
