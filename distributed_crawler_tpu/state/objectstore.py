"""Remote object storage: the blob seam behind the chunker and TPU worker.

The reference reached Azure-blob / local-storage through Dapr output
bindings (`state/daprstate.go:29-35`, `resources/local-storage.yaml`); this
build keeps the same seam in-tree with an S3-shaped client protocol:

- :class:`ObjectStoreClient` — the low-level blob surface (multipart
  create/upload/complete, put/get/list/delete).  Real SDK adapters (S3,
  GCS, Azure) implement this; this repo ships two offline backends:
  :class:`LocalFSObjectClient` (the ``local-storage.yaml`` binding analog,
  usable in production single-host deploys) and
  :class:`InMemoryObjectClient` (test double with fault injection).
- :class:`ObjectStoreUploader` — the retry+resume engine: files upload in
  parts with exponential backoff per part, resuming from the last
  completed part instead of byte 0 — the property the chunker's 170 MiB
  combined files need on a flaky uplink.
- :class:`ObjectStorageProvider` — adapts a client to the
  `providers.StorageProvider` protocol, so state managers and the TPU
  worker's result writeback can sink straight to the object store.

URL scheme (``make_object_client``): ``memory://`` | ``file:///path``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Protocol, Tuple, runtime_checkable

import json as _json
import logging

logger = logging.getLogger("dct.objectstore")

DEFAULT_PART_SIZE = 8 * 1024 * 1024
DEFAULT_MAX_RETRIES = 4
DEFAULT_BACKOFF_S = 0.2


@runtime_checkable
class ObjectStoreClient(Protocol):
    """S3-shaped blob surface; all keys are forward-slash paths."""

    def put_object(self, key: str, data: bytes) -> None: ...

    def get_object(self, key: str) -> Optional[bytes]: ...

    def head_object(self, key: str) -> Optional[int]: ...

    def list_objects(self, prefix: str) -> List[str]: ...

    def delete_object(self, key: str) -> None: ...

    def create_multipart(self, key: str) -> str: ...

    def upload_part(self, key: str, upload_id: str, part_no: int,
                    data: bytes) -> str: ...

    def complete_multipart(self, key: str, upload_id: str,
                           etags: List[str]) -> None: ...

    def abort_multipart(self, key: str, upload_id: str) -> None: ...


class TransientStoreError(Exception):
    """Retryable failure (network blip, 5xx) — the uploader retries these."""


class KeepAliveHttpTransport:
    """Shared HTTP plumbing for the cloud adapters (s3store, azurestore).

    One persistent keep-alive connection per client, serialized by a lock:
    a 170 MiB multipart upload is ~34 parts and a TLS handshake per part
    would dominate the upload hot path.  Any transport error drops the
    connection (the uploader's retry gets a fresh one) and surfaces as
    :class:`TransientStoreError`.
    """

    def __init__(self, host: str, tls: bool, timeout_s: float,
                 scheme_name: str):
        self._host = host
        self._tls = tls
        self._timeout_s = timeout_s
        self._scheme_name = scheme_name
        self._lock = threading.Lock()
        self._conn = None

    def http_request(self, method: str, url: str, body: bytes,
                     headers: Dict[str, str]):
        """Returns ``(status, headers_dict, body_bytes)``."""
        import http.client
        import socket

        with self._lock:
            if self._conn is None:
                conn_cls = (http.client.HTTPSConnection if self._tls
                            else http.client.HTTPConnection)
                self._conn = conn_cls(self._host, timeout=self._timeout_s)
            conn = self._conn
            try:
                conn.request(method, url, body=body or None,
                             headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                return resp.status, dict(resp.getheaders()), data
            except (OSError, socket.timeout,
                    http.client.HTTPException) as e:
                self._conn = None
                try:
                    conn.close()
                except OSError:
                    pass
                raise TransientStoreError(
                    f"{self._scheme_name} {method} {url.split('?')[0]}: "
                    f"{e}") from e

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except OSError:
                    pass
                self._conn = None

    def raise_for(self, status: int, method: str, what: str,
                  body: bytes) -> None:
        """Shared status taxonomy: 5xx transient, 3xx/4xx config errors
        (a redirect would break the signed Host, and handing redirect XML
        back as object data would be silent corruption)."""
        if status >= 500:
            raise TransientStoreError(
                f"{self._scheme_name} {method} {what}: HTTP {status}")
        if status >= 300:
            raise ValueError(
                f"{self._scheme_name} {method} {what}: HTTP {status}: "
                f"{body[:300].decode('utf-8', 'replace')}")


class InMemoryObjectClient:
    """Test double with injectable faults.

    ``fail(op, times)`` makes the next ``times`` calls of ``op`` raise
    :class:`TransientStoreError` — the hook the retry/resume tests use.
    """

    def __init__(self):
        self.objects: Dict[str, bytes] = {}
        self._mp: Dict[str, Dict[int, bytes]] = {}
        self._faults: Dict[str, int] = {}
        self.calls: List[Tuple[str, str]] = []
        self._lock = threading.RLock()

    def fail(self, op: str, times: int = 1) -> None:
        with self._lock:
            self._faults[op] = self._faults.get(op, 0) + times

    def _maybe_fail(self, op: str) -> None:
        with self._lock:
            if self._faults.get(op, 0) > 0:
                self._faults[op] -= 1
                raise TransientStoreError(f"injected {op} failure")

    def put_object(self, key: str, data: bytes) -> None:
        self.calls.append(("put_object", key))
        self._maybe_fail("put_object")
        with self._lock:
            self.objects[key] = bytes(data)

    def get_object(self, key: str) -> Optional[bytes]:
        self.calls.append(("get_object", key))
        self._maybe_fail("get_object")
        with self._lock:
            return self.objects.get(key)

    def head_object(self, key: str) -> Optional[int]:
        with self._lock:
            data = self.objects.get(key)
        return None if data is None else len(data)

    def list_objects(self, prefix: str) -> List[str]:
        self._maybe_fail("list_objects")
        with self._lock:
            return sorted(k for k in self.objects if k.startswith(prefix))

    def delete_object(self, key: str) -> None:
        self._maybe_fail("delete_object")
        with self._lock:
            self.objects.pop(key, None)

    def create_multipart(self, key: str) -> str:
        self.calls.append(("create_multipart", key))
        self._maybe_fail("create_multipart")
        upload_id = f"mp-{len(self._mp)}-{key}"
        with self._lock:
            self._mp[upload_id] = {}
        return upload_id

    def upload_part(self, key: str, upload_id: str, part_no: int,
                    data: bytes) -> str:
        self.calls.append(("upload_part", f"{key}#{part_no}"))
        self._maybe_fail("upload_part")
        with self._lock:
            self._mp[upload_id][part_no] = bytes(data)
        return f"etag-{part_no}"

    def complete_multipart(self, key: str, upload_id: str,
                           etags: List[str]) -> None:
        self.calls.append(("complete_multipart", key))
        self._maybe_fail("complete_multipart")
        with self._lock:
            parts = self._mp.pop(upload_id)
            self.objects[key] = b"".join(
                parts[i] for i in sorted(parts))

    def abort_multipart(self, key: str, upload_id: str) -> None:
        with self._lock:
            self._mp.pop(upload_id, None)


class LocalFSObjectClient:
    """Object store on a local directory — the `resources/local-storage.yaml`
    binding analog (`state/daprstate.go:1106-1249` wrote blobs through the
    same seam).  Objects are files under ``root``; multipart uploads stage
    parts in a hidden ``.mp-<id>`` directory and concatenate on complete, so
    a completed object is always whole."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.RLock()
        self._counter = 0

    def _path(self, key: str) -> str:
        root = os.path.normpath(self.root)
        path = os.path.normpath(os.path.join(root, key))
        # commonpath, not startswith: '../store-evil' shares the string
        # prefix of root but is a sibling directory.
        if path != root and os.path.commonpath([root, path]) != root:
            raise ValueError(f"key escapes store root: {key}")
        return path

    def put_object(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def get_object(self, key: str) -> Optional[bytes]:
        path = self._path(key)
        if not os.path.isfile(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def head_object(self, key: str) -> Optional[int]:
        path = self._path(key)
        return os.path.getsize(path) if os.path.isfile(path) else None

    def list_objects(self, prefix: str) -> List[str]:
        keys = []
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = [d for d in dirnames if not d.startswith(".mp-")]
            for name in filenames:
                if name.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name), self.root)
                key = rel.replace(os.sep, "/")
                if key.startswith(prefix):
                    keys.append(key)
        return sorted(keys)

    def delete_object(self, key: str) -> None:
        path = self._path(key)
        if os.path.isfile(path):
            os.remove(path)

    def create_multipart(self, key: str) -> str:
        with self._lock:
            self._counter += 1
            upload_id = f"{self._counter}-{time.time_ns()}"
        os.makedirs(self._mp_dir(upload_id), exist_ok=True)
        return upload_id

    def _mp_dir(self, upload_id: str) -> str:
        return os.path.join(self.root, f".mp-{upload_id}")

    def upload_part(self, key: str, upload_id: str, part_no: int,
                    data: bytes) -> str:
        part_path = os.path.join(self._mp_dir(upload_id), f"part_{part_no:06d}")
        tmp = part_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, part_path)
        return f"etag-{part_no}"

    def complete_multipart(self, key: str, upload_id: str,
                           etags: List[str]) -> None:
        mp_dir = self._mp_dir(upload_id)
        parts = sorted(n for n in os.listdir(mp_dir)
                       if n.startswith("part_") and not n.endswith(".tmp"))
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as out:
            for name in parts:
                with open(os.path.join(mp_dir, name), "rb") as f:
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        out.write(chunk)
        os.replace(tmp, path)
        self.abort_multipart(key, upload_id)

    def abort_multipart(self, key: str, upload_id: str) -> None:
        import shutil

        shutil.rmtree(self._mp_dir(upload_id), ignore_errors=True)


class ObjectStoreUploader:
    """Part-level retry+resume over any :class:`ObjectStoreClient`.

    ``upload_file`` streams the file in ``part_size`` parts.  Each part
    retries up to ``max_retries`` times with exponential backoff; a
    mid-file failure resumes from the first unfinished part, never byte 0.
    Files at or under ``part_size`` use a single ``put_object`` (retried
    whole — the small-object fast path)."""

    def __init__(self, client: ObjectStoreClient,
                 part_size: int = DEFAULT_PART_SIZE,
                 max_retries: int = DEFAULT_MAX_RETRIES,
                 backoff_s: float = DEFAULT_BACKOFF_S):
        self.client = client
        self.part_size = part_size
        self.max_retries = max_retries
        self.backoff_s = backoff_s

    def _with_retry(self, op_name: str, fn):
        last: Optional[Exception] = None
        for attempt in range(self.max_retries):
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — classify below
                last = e
                logger.warning("%s failed (attempt %d/%d): %s", op_name,
                               attempt + 1, self.max_retries, e)
                if attempt + 1 < self.max_retries:
                    time.sleep(self.backoff_s * (2 ** attempt))
        assert last is not None
        raise last

    def _upload_multipart(self, key: str, chunks) -> None:
        """One multipart state machine for both byte- and file-sourced
        uploads: per-part retry, complete, abort-on-failure."""
        upload_id = self._with_retry(
            f"create-multipart {key}",
            lambda: self.client.create_multipart(key))
        try:
            etags: List[str] = []
            for part_no, chunk in enumerate(chunks):
                etags.append(self._with_retry(
                    f"part {part_no} of {key}",
                    lambda c=chunk, n=part_no:
                    self.client.upload_part(key, upload_id, n, c)))
            self._with_retry(
                f"complete {key}",
                lambda: self.client.complete_multipart(key, upload_id, etags))
        except Exception:
            try:
                self.client.abort_multipart(key, upload_id)
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass
            raise

    def upload_bytes(self, key: str, data: bytes) -> None:
        if len(data) <= self.part_size:
            self._with_retry(f"put {key}",
                             lambda: self.client.put_object(key, data))
            return
        self._upload_multipart(
            key, (data[start:start + self.part_size]
                  for start in range(0, len(data), self.part_size)))

    def upload_file(self, path: str, key: str) -> int:
        """Upload ``path`` to ``key``; returns bytes uploaded."""
        size = os.path.getsize(path)
        if size <= self.part_size:
            with open(path, "rb") as f:
                self.upload_bytes(key, f.read())
            return size

        def file_chunks():
            with open(path, "rb") as f:
                while True:
                    chunk = f.read(self.part_size)
                    if not chunk:
                        return
                    yield chunk

        self._upload_multipart(key, file_chunks())
        return size


class ObjectStorageProvider:
    """`providers.StorageProvider` over an object store, so state managers
    and the TPU worker's writeback can target the remote store directly."""

    # Appended lines buffer client-side until this many bytes per key —
    # an object store has no append, so per-line read-modify-write would
    # be O(n²) total traffic over a large file.
    APPEND_FLUSH_BYTES = 256 * 1024

    def __init__(self, client: ObjectStoreClient,
                 uploader: Optional[ObjectStoreUploader] = None):
        self.client = client
        self.uploader = uploader or ObjectStoreUploader(client)
        self._lock = threading.Lock()
        self._append_buf: dict = {}  # key -> bytearray of pending lines

    def save_json(self, rel_path: str, data: Any) -> None:
        self.uploader.upload_bytes(
            rel_path, _json.dumps(data, ensure_ascii=False).encode("utf-8"))

    def load_json(self, rel_path: str) -> Optional[Any]:
        raw = self.client.get_object(rel_path)
        return None if raw is None else _json.loads(raw.decode("utf-8"))

    def append_jsonl(self, rel_path: str, line: str) -> None:
        # Buffered append (single-writer per key is the provider
        # contract; each worker owns its result keys).  The read-modify-
        # write against the store happens once per APPEND_FLUSH_BYTES —
        # not once per line — and on flush()/close()/read-back.
        with self._lock:
            buf = self._append_buf.setdefault(rel_path, bytearray())
            buf += line.rstrip("\n").encode("utf-8") + b"\n"
            if len(buf) >= self.APPEND_FLUSH_BYTES:
                self._flush_key_locked(rel_path)

    def _flush_key_locked(self, rel_path: str) -> bytes:
        """Upload buffered appends for ``rel_path``; returns the merged
        object bytes (so readers need no second GET).  On upload failure
        the buffer is REINSTATED before re-raising — accepted lines are
        never dropped; the next flush retries them."""
        buf = self._append_buf.pop(rel_path, None)
        if not buf:
            return self.client.get_object(rel_path) or b""
        prior = self.client.get_object(rel_path) or b""
        merged = prior + bytes(buf)
        try:
            self.uploader.upload_bytes(rel_path, merged)
        except Exception:
            existing = self._append_buf.get(rel_path)
            if existing:  # appends that raced in during the upload
                self._append_buf[rel_path] = buf + existing
            else:
                self._append_buf[rel_path] = buf
            raise
        return merged

    def flush(self) -> None:
        """Push all buffered appends to the store (call before handing
        keys to another reader, and on shutdown)."""
        with self._lock:
            for key in list(self._append_buf):
                self._flush_key_locked(key)

    def close(self) -> None:
        self.flush()

    def put_text(self, rel_path: str, text: str) -> None:
        with self._lock:
            self._append_buf.pop(rel_path, None)  # overwrite semantics
        self.uploader.upload_bytes(rel_path, text.encode("utf-8"))

    def get_text(self, rel_path: str) -> Optional[str]:
        with self._lock:
            if self._append_buf.get(rel_path):
                # Flush returns the merged bytes: readers see appended
                # rows without a second GET.
                return self._flush_key_locked(rel_path).decode("utf-8")
        raw = self.client.get_object(rel_path)
        return None if raw is None else raw.decode("utf-8")

    def store_file(self, rel_path: str, source_path: str,
                   delete_source: bool = True) -> str:
        self.uploader.upload_file(source_path, rel_path)
        if delete_source:
            try:
                os.remove(source_path)
            except OSError:
                pass
        return rel_path

    def exists(self, rel_path: str) -> bool:
        with self._lock:
            if self._append_buf.get(rel_path):
                return True  # buffered-but-unflushed rows still count
        return self.client.head_object(rel_path) is not None

    def list_dir(self, rel_path: str) -> List[str]:
        prefix = rel_path.rstrip("/") + "/"
        names = set()
        with self._lock:
            for key, buf in self._append_buf.items():
                if buf and key.startswith(prefix):
                    names.add(key[len(prefix):].split("/", 1)[0])
        for key in self.client.list_objects(prefix):
            names.add(key[len(prefix):].split("/", 1)[0])
        return sorted(names)

    def delete(self, rel_path: str) -> None:
        prefix = rel_path.rstrip("/") + "/"
        with self._lock:
            # Drop the exact key AND any buffered keys under the prefix,
            # or a later flush would resurrect "deleted" objects.
            self._append_buf.pop(rel_path, None)
            for key in [k for k in self._append_buf
                        if k.startswith(prefix)]:
                self._append_buf.pop(key, None)
        for key in self.client.list_objects(prefix):
            self.client.delete_object(key)
        self.client.delete_object(rel_path)


def make_object_client(url: str) -> ObjectStoreClient:
    """``memory://`` | ``file:///abs/path`` | ``file:relative/path`` |
    ``s3://bucket/prefix?endpoint=...`` (any S3-compatible store,
    `state/s3store.py` — the reference's cloud-blob binding analog,
    `state/daprstate.go:29-35`)."""
    if url == "memory://":
        return InMemoryObjectClient()
    if url.startswith("file://"):
        return LocalFSObjectClient(url[len("file://"):] or "/")
    if url.startswith("file:"):
        return LocalFSObjectClient(url[len("file:"):])
    if url.startswith("s3://"):
        from .s3store import parse_s3_url

        return parse_s3_url(url)
    if url.startswith("azure://"):
        from .azurestore import parse_azure_url

        return parse_azure_url(url)
    if "://" in url:
        raise ValueError(
            f"no client for object-store scheme {url.split('://')[0]!r}; "
            f"implement ObjectStoreClient and wire it in make_object_client")
    return LocalFSObjectClient(url)
