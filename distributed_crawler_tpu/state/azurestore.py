"""Azure Blob Storage adapter: the reference's actual blob target.

The reference shipped crawl output to Azure blob through its Dapr storage
binding (`state/daprstate.go:29-35`); this adapter implements the same
`ObjectStoreClient` protocol (`state/objectstore.py`) directly against the
Blob service REST API — stdlib HTTP with Shared Key request signing, no
SDK (none is installed in the image), so it also works against Azurite and
this repo's test emulator via the ``endpoint`` parameter.

Multipart mapping onto block blobs:

- ``create_multipart`` mints a client-side upload id (block ids are
  namespaced by it; Azure has no server-side upload session),
- ``upload_part`` → Put Block with blockid = b64("{upload_id}:{part:06d}"),
- ``complete_multipart`` → Put Block List (commits in part order),
- ``abort_multipart`` → no-op (uncommitted blocks are garbage-collected by
  the service after 7 days).

URL form (``make_object_client``):

    azure://account/container/prefix?endpoint=http://127.0.0.1:10000/account

Credentials: ``AZURE_STORAGE_KEY`` (base64 account key; query-string
override exists for hermetic tests only).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from email.utils import formatdate
from typing import Dict, List, Optional, Tuple

from .objectstore import KeepAliveHttpTransport

API_VERSION = "2021-08-06"


class SharedKeySigner:
    """Azure Storage Shared Key authorization (Blob service)."""

    def __init__(self, account: str, key_b64: str):
        self.account = account
        try:
            self.key = base64.b64decode(key_b64)
        except Exception as e:
            raise ValueError(f"azure account key is not base64: {e}") from e

    def sign(self, method: str, path: str, query: List[Tuple[str, str]],
             headers: Dict[str, str], content_length: int) -> str:
        """Returns the Authorization header value.  ``headers`` must
        already contain every x-ms-* header that will be sent."""
        xms = sorted((k.lower(), v.strip()) for k, v in headers.items()
                     if k.lower().startswith("x-ms-"))
        canonical_headers = "".join(f"{k}:{v}\n" for k, v in xms)
        resource = f"/{self.account}{path}"
        canonical_resource = resource + "".join(
            f"\n{k.lower()}:{v}" for k, v in sorted(query))
        string_to_sign = "\n".join([
            method,
            "",  # Content-Encoding
            "",  # Content-Language
            str(content_length) if content_length else "",
            "",  # Content-MD5
            headers.get("Content-Type", ""),
            "",  # Date (x-ms-date is used instead)
            "",  # If-Modified-Since
            "",  # If-Match
            "",  # If-None-Match
            "",  # If-Unmodified-Since
            "",  # Range
        ]) + "\n" + canonical_headers + canonical_resource
        sig = base64.b64encode(
            hmac.new(self.key, string_to_sign.encode("utf-8"),
                     hashlib.sha256).digest()).decode("ascii")
        return f"SharedKey {self.account}:{sig}"


class AzureBlobObjectClient:
    """`ObjectStoreClient` over the Azure Blob REST API."""

    def __init__(self, account: str, container: str, prefix: str = "",
                 endpoint: str = "", account_key: str = "",
                 timeout_s: float = 30.0):
        self.account = account
        self.container = container
        self.prefix = prefix.strip("/")
        self.timeout_s = timeout_s
        account_key = account_key or os.environ.get("AZURE_STORAGE_KEY", "")
        if not account_key:
            raise ValueError(
                "azure:// needs credentials: set AZURE_STORAGE_KEY")
        self._signer = SharedKeySigner(account, account_key)
        if endpoint:
            u = urllib.parse.urlsplit(endpoint)
            tls = u.scheme == "https"
            host = u.netloc
            # Azurite-style endpoints carry the account in the path.
            self._base = u.path.rstrip("/")
        else:
            tls = True
            host = f"{account}.blob.core.windows.net"
            self._base = ""
        self._http = KeepAliveHttpTransport(host, tls, timeout_s, "azure")

    # -- transport ---------------------------------------------------------
    def _blob_path(self, key: str) -> str:
        full = f"{self.prefix}/{key}" if self.prefix else key
        return (f"{self._base}/{self.container}/" +
                urllib.parse.quote(full, safe="/-._~"))

    def _container_path(self) -> str:
        return f"{self._base}/{self.container}"

    def _request(self, method: str, path: str,
                 query: Optional[List[Tuple[str, str]]] = None,
                 body: bytes = b"",
                 extra_headers: Optional[Dict[str, str]] = None
                 ) -> Tuple[int, Dict[str, str], bytes]:
        query = query or []
        headers = {
            # formatdate: locale-independent RFC 1123 (strftime's %a/%b
            # would emit localized day/month names and real Azure would
            # 403 every request under a non-English LC_TIME).
            "x-ms-date": formatdate(usegmt=True),
            "x-ms-version": API_VERSION,
        }
        if extra_headers:
            headers.update(extra_headers)
        if body:
            headers["Content-Length"] = str(len(body))
        # CanonicalizedResource is "/" + account + FULL request URI path —
        # so for an Azurite-style endpoint (account as the first path
        # segment) the account name legitimately appears twice
        # ("/acct/acct/container/blob"); do NOT strip the base.
        headers["Authorization"] = self._signer.sign(
            method, urllib.parse.unquote(path), query, headers,
            len(body))
        qs = urllib.parse.urlencode(sorted(query))
        url = path + (f"?{qs}" if qs else "")
        return self._http.http_request(method, url, body, headers)

    def close(self) -> None:
        self._http.close()

    def _raise_for(self, status: int, method: str, what: str,
                   body: bytes) -> None:
        self._http.raise_for(status, method, what, body)

    # -- ObjectStoreClient protocol ---------------------------------------
    def put_object(self, key: str, data: bytes) -> None:
        status, _, body = self._request(
            "PUT", self._blob_path(key), body=data,
            extra_headers={"x-ms-blob-type": "BlockBlob"})
        self._raise_for(status, "PUT", key, body)

    def get_object(self, key: str) -> Optional[bytes]:
        status, _, body = self._request("GET", self._blob_path(key))
        if status == 404:
            return None
        self._raise_for(status, "GET", key, body)
        return body

    def head_object(self, key: str) -> Optional[int]:
        status, headers, body = self._request("HEAD", self._blob_path(key))
        if status == 404:
            return None
        self._raise_for(status, "HEAD", key, body)
        cl = {k.lower(): v for k, v in headers.items()}.get(
            "content-length")
        return int(cl) if cl is not None else 0

    def list_objects(self, prefix: str) -> List[str]:
        full_prefix = (f"{self.prefix}/{prefix}" if self.prefix
                       else prefix)
        keys: List[str] = []
        marker = ""
        while True:
            query = [("restype", "container"), ("comp", "list"),
                     ("prefix", full_prefix)]
            if marker:
                query.append(("marker", marker))
            status, _, body = self._request("GET", self._container_path(),
                                            query=query)
            self._raise_for(status, "LIST", prefix, body)
            root = ET.fromstring(body)
            for el in root.iter("Name"):
                k = el.text or ""
                if self.prefix and k.startswith(self.prefix + "/"):
                    k = k[len(self.prefix) + 1:]
                keys.append(k)
            nxt = root.find("NextMarker")
            if nxt is None or not (nxt.text or "").strip():
                break
            marker = nxt.text.strip()
        return sorted(keys)

    def delete_object(self, key: str) -> None:
        status, _, body = self._request("DELETE", self._blob_path(key))
        if status == 404:
            return
        self._raise_for(status, "DELETE", key, body)

    # -- multipart (block-blob mapping) ------------------------------------
    def create_multipart(self, key: str) -> str:
        # The id carries real entropy: block ids are namespaced by it, and
        # a deterministic id would let a retired-but-alive writer and its
        # replacement stage IDENTICAL block ids against the same blob —
        # last-write-wins per block id, silently interleaving the two
        # uploads.  uuid4 alone (no counter) keeps the width FIXED forever:
        # Azure requires equal-length block ids per blob, including stale
        # uncommitted blocks from crashed writers.
        return f"up{uuid.uuid4().hex[:16]}"

    @staticmethod
    def _block_id(upload_id: str, part_no: int) -> str:
        # Block ids must be base64, equal length within a blob.
        return base64.b64encode(
            f"{upload_id}:{part_no:06d}".encode("ascii")).decode("ascii")

    def upload_part(self, key: str, upload_id: str, part_no: int,
                    data: bytes) -> str:
        block_id = self._block_id(upload_id, part_no)
        status, _, body = self._request(
            "PUT", self._blob_path(key),
            query=[("comp", "block"), ("blockid", block_id)], body=data)
        self._raise_for(status, "PUT?comp=block", f"{key}#{part_no}", body)
        return block_id

    def complete_multipart(self, key: str, upload_id: str,
                           etags: List[str]) -> None:
        # ``etags`` are the block ids returned by upload_part, in part
        # order — commit exactly those (a retried part appears once).
        payload = ("<?xml version=\"1.0\" encoding=\"utf-8\"?><BlockList>"
                   + "".join(f"<Latest>{bid}</Latest>" for bid in etags)
                   + "</BlockList>").encode("utf-8")
        status, _, body = self._request(
            "PUT", self._blob_path(key),
            query=[("comp", "blocklist")], body=payload,
            extra_headers={"Content-Type": "application/xml"})
        self._raise_for(status, "PUT?comp=blocklist", key, body)

    def abort_multipart(self, key: str, upload_id: str) -> None:
        # Uncommitted blocks are GC'd by the service after 7 days; there
        # is no client-side state to drop.
        return None


def parse_azure_url(url: str) -> AzureBlobObjectClient:
    """``azure://account/container[/prefix]?endpoint=…`` → client.

    Query params: ``endpoint`` (Azurite/emulator base URL incl. the
    account path segment; empty = the public
    ``{account}.blob.core.windows.net``) and — FOR TESTS ONLY —
    ``account_key`` (production keys belong in ``AZURE_STORAGE_KEY``)."""
    u = urllib.parse.urlsplit(url)
    if u.scheme != "azure" or not u.netloc:
        raise ValueError(f"not an azure URL: {url}")
    parts = u.path.strip("/").split("/", 1)
    if not parts or not parts[0]:
        raise ValueError(f"azure URL needs a container: {url}")
    q = dict(urllib.parse.parse_qsl(u.query))
    return AzureBlobObjectClient(
        account=u.netloc,
        container=parts[0],
        prefix=parts[1] if len(parts) > 1 else "",
        endpoint=q.get("endpoint", ""),
        account_key=q.get("account_key", ""),
    )
