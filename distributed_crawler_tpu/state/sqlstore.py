"""SQL-backed random-walk graph + tandem validator store.

The reference kept this state in PostgreSQL reached through a Dapr `postgres`
output binding (`state/daprstate.go:3076-4391`, SQL DDL in `sql/*.sql`).  The
TPU build brings the store in-tree behind a thin `SqlBinding` seam:

- `SqliteBinding` (default): zero-dependency, serialized-writer engine whose
  BEGIN IMMEDIATE transactions give the same atomic-claim guarantees the
  reference got from `FOR UPDATE SKIP LOCKED` for in-process concurrency;
- any DB-API engine (e.g. psycopg) can be dropped in for multi-host
  deployments — the SQL sticks to the common subset plus RETURNING.

Tests assert at the binding boundary (recorded SQL + canned rows), mirroring
the reference's fake-Dapr-client strategy (`state/validator_db_test.go:17-60`).
"""

from __future__ import annotations

import logging
import os
import sqlite3
import threading
from datetime import datetime, timedelta
from typing import Any, Dict, List, Optional, Protocol, Sequence, Tuple

from ..datamodel.post import format_time, parse_time
from .datamodels import (
    BATCH_OPEN,
    EdgeRecord,
    Page,
    PendingEdge,
    PendingEdgeBatch,
    PendingEdgeUpdate,
    utcnow,
)

logger = logging.getLogger("dct.state.sql")

# Poison detection: batches claimed this many times are left in place
# (`state/daprstate.go` maxBatchAttempts analog, crawl/validator.go:319-331).
MAX_BATCH_ATTEMPTS = 3

_SCHEMA_PATH = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                            "sql", "schema.sql")


class SqlBinding(Protocol):
    """Minimal SQL surface the graph store needs."""

    #: Appended inside claim subselects: "" on sqlite (BEGIN IMMEDIATE
    #: serializes writers), " FOR UPDATE SKIP LOCKED" on PostgreSQL
    #: (`state/daprstate.go:3944,4016`).
    for_update_clause: str

    def query(self, sql: str, params: Sequence[Any] = ()) -> List[tuple]: ...

    def execute(self, sql: str, params: Sequence[Any] = ()) -> int:
        """Run a statement; returns affected row count."""

    def executemany(self, sql: str, seq_params: Sequence[Sequence[Any]]) -> int:
        """Run one statement for many parameter rows in a single transaction."""

    def execute_returning(self, sql: str, params: Sequence[Any] = ()) -> List[tuple]:
        """Run a mutating statement with RETURNING; returns rows."""

    def executescript(self, sql: str) -> None: ...


class SqliteBinding:
    """sqlite3-backed binding with serialized writers.

    Cross-process safe on one DB file: WAL + busy_timeout make concurrent
    readers cheap, and every claim runs as a single BEGIN IMMEDIATE
    transaction, so two processes (crawler pod + validator pod, the
    reference's deploy shape, `crawl/validator.go:53`) cannot double-claim —
    proven by `tests/test_state_multiprocess.py`.  The RLock only serializes
    threads within one process.
    """

    for_update_clause = ""
    dialect = "sqlite"

    def __init__(self, url: str = ":memory:"):
        self.url = url or ":memory:"
        if self.url != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(self.url)), exist_ok=True)
        self._conn = sqlite3.connect(self.url, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA busy_timeout=10000")
        self._lock = threading.RLock()

    def query(self, sql: str, params: Sequence[Any] = ()) -> List[tuple]:
        with self._lock:
            cur = self._conn.execute(sql, tuple(params))
            return cur.fetchall()

    def execute(self, sql: str, params: Sequence[Any] = ()) -> int:
        with self._lock:
            cur = self._conn.execute(sql, tuple(params))
            self._conn.commit()
            return cur.rowcount

    def executemany(self, sql: str, seq_params: Sequence[Sequence[Any]]) -> int:
        with self._lock:
            cur = self._conn.executemany(sql, [tuple(p) for p in seq_params])
            self._conn.commit()
            return cur.rowcount

    def execute_returning(self, sql: str, params: Sequence[Any] = ()) -> List[tuple]:
        # BEGIN IMMEDIATE grabs the write lock up front: the SELECT inside the
        # UPDATE and the UPDATE itself are atomic w.r.t. concurrent claimers —
        # the sqlite equivalent of FOR UPDATE SKIP LOCKED for our claim shapes.
        with self._lock:
            try:
                self._conn.execute("BEGIN IMMEDIATE")
            except sqlite3.OperationalError:
                pass  # already in a transaction
            try:
                cur = self._conn.execute(sql, tuple(params))
                rows = cur.fetchall()
                self._conn.commit()
                return rows
            except Exception:
                self._conn.rollback()
                raise

    def executescript(self, sql: str) -> None:
        with self._lock:
            self._conn.executescript(sql)
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class DbApiBinding:
    """Adapter over any DB-API 2.0 driver — the psycopg path for multi-host
    deployments (parity: the reference's Dapr `postgres` binding,
    `state/daprstate.go:3862-3893`).

    ``connection_factory``: zero-arg callable returning a DB-API connection
    (e.g. ``lambda: psycopg.connect(dsn)``).  The store's SQL is written
    qmark-style; ``paramstyle`` converts it for the driver ("format" for
    psycopg/pg8000, "qmark" passthrough).  ``dialect="postgres"`` turns on
    `FOR UPDATE SKIP LOCKED` in claim subselects — the exact concurrency
    device the reference used.
    """

    def __init__(self, connection_factory, paramstyle: str = "format",
                 dialect: str = "postgres"):
        self._conn = connection_factory()
        self._paramstyle = paramstyle
        self._lock = threading.RLock()
        self.dialect = dialect
        self.for_update_clause = (
            " FOR UPDATE SKIP LOCKED" if dialect == "postgres" else "")

    def _sql(self, sql: str) -> str:
        # The store's SQL contains no literal '?', so a plain replace is
        # exact for the format/pyformat drivers.
        if self._paramstyle in ("format", "pyformat"):
            return sql.replace("?", "%s")
        return sql

    def query(self, sql: str, params: Sequence[Any] = ()) -> List[tuple]:
        with self._lock, self._conn.cursor() as cur:
            cur.execute(self._sql(sql), tuple(params))
            rows = cur.fetchall()
        self._conn.commit()
        return rows

    def execute(self, sql: str, params: Sequence[Any] = ()) -> int:
        with self._lock, self._conn.cursor() as cur:
            cur.execute(self._sql(sql), tuple(params))
            count = cur.rowcount
        self._conn.commit()
        return count

    def executemany(self, sql: str,
                    seq_params: Sequence[Sequence[Any]]) -> int:
        with self._lock, self._conn.cursor() as cur:
            cur.executemany(self._sql(sql),
                            [tuple(p) for p in seq_params])
            count = cur.rowcount
        self._conn.commit()
        return count

    def execute_returning(self, sql: str,
                          params: Sequence[Any] = ()) -> List[tuple]:
        with self._lock:
            try:
                with self._conn.cursor() as cur:
                    cur.execute(self._sql(sql), tuple(params))
                    rows = cur.fetchall()
                self._conn.commit()
                return rows
            except Exception:
                self._conn.rollback()
                raise

    def executescript(self, sql: str) -> None:
        # DB-API cursors take one statement per execute() (sqlite3 raises
        # ProgrammingError on multi-statement strings; psycopg tolerates
        # them but PREPARE-based drivers do not) — split the DDL first.
        with self._lock:
            try:
                with self._conn.cursor() as cur:
                    for stmt in split_sql_statements(sql):
                        cur.execute(stmt)
                self._conn.commit()
            except Exception:
                self._conn.rollback()
                raise

    def close(self) -> None:
        self._conn.close()


def split_sql_statements(sql: str) -> List[str]:
    """Split a DDL/DML script on top-level semicolons, respecting single-
    and double-quoted literals and ``--`` line comments.  Sufficient for
    the in-tree schemas (no procedural BEGIN...END bodies)."""
    statements: List[str] = []
    buf: List[str] = []
    i, n = 0, len(sql)
    while i < n:
        ch = sql[i]
        if ch == "-" and sql[i:i + 2] == "--":
            nl = sql.find("\n", i)
            i = n if nl == -1 else nl + 1
            buf.append("\n")
            continue
        if ch in ("'", '"'):
            quote = ch
            buf.append(ch)
            i += 1
            while i < n:
                buf.append(sql[i])
                if sql[i] == quote:
                    # doubled quote = escaped quote inside the literal
                    if sql[i + 1:i + 2] == quote:
                        buf.append(quote)
                        i += 2
                        continue
                    i += 1
                    break
                i += 1
            continue
        if ch == ";":
            stmt = "".join(buf).strip()
            if stmt:
                statements.append(stmt)
            buf = []
            i += 1
            continue
        buf.append(ch)
        i += 1
    tail = "".join(buf).strip()
    if tail:
        statements.append(tail)
    return statements


def schema_for_dialect(dialect: str = "sqlite") -> str:
    """The graph-store DDL, translated for the target engine.  The source
    of truth is `sql/schema.sql` (sqlite-compatible); postgres swaps the
    rowid PKs for BIGSERIAL (`sql/random-walk-schema.sql` analog)."""
    with open(_SCHEMA_PATH, "r", encoding="utf-8") as f:
        ddl = f.read()
    if dialect == "postgres":
        ddl = ddl.replace("INTEGER PRIMARY KEY AUTOINCREMENT",
                          "BIGSERIAL PRIMARY KEY")
    return ddl


class RecordingBinding:
    """Test double: records every statement, feeds back canned rows — the
    analog of the reference's fake Dapr client (`state/export_test.go`)."""

    for_update_clause = ""

    def __init__(self):
        self.calls: List[Tuple[str, tuple]] = []
        self.canned: List[List[tuple]] = []
        self.rowcount: int = 1

    def _next_rows(self) -> List[tuple]:
        return self.canned.pop(0) if self.canned else []

    def query(self, sql: str, params: Sequence[Any] = ()) -> List[tuple]:
        self.calls.append((sql, tuple(params)))
        return self._next_rows()

    def execute(self, sql: str, params: Sequence[Any] = ()) -> int:
        self.calls.append((sql, tuple(params)))
        return self.rowcount

    def executemany(self, sql: str, seq_params: Sequence[Sequence[Any]]) -> int:
        for p in seq_params:
            self.calls.append((sql, tuple(p)))
        return self.rowcount * len(list(seq_params))

    def execute_returning(self, sql: str, params: Sequence[Any] = ()) -> List[tuple]:
        self.calls.append((sql, tuple(params)))
        return self._next_rows()

    def executescript(self, sql: str) -> None:
        self.calls.append((sql, ()))


def _ts(dt: Optional[datetime]) -> str:
    return format_time(dt or utcnow())


_EDGE_COLS = ("pending_id, batch_id, crawl_id, destination_channel, "
              "source_channel, sequence_id, discovery_time, source_type, "
              "validation_status, validation_reason")

_EDGE_RECORD_COLS = ("destination_channel, source_channel, walkback, skipped, "
                     "discovery_time, crawl_id, sequence_id")


def _row_to_edge_record(row: tuple) -> EdgeRecord:
    return EdgeRecord(destination_channel=row[0], source_channel=row[1],
                      walkback=bool(row[2]), skipped=bool(row[3]),
                      discovery_time=parse_time(row[4]), crawl_id=row[5],
                      sequence_id=row[6])

_BATCH_COLS = ("batch_id, crawl_id, source_channel, source_page_id, "
               "source_depth, sequence_id, status, attempt_count")


def _row_to_edge(row: tuple) -> PendingEdge:
    return PendingEdge(
        pending_id=int(row[0]), batch_id=row[1], crawl_id=row[2],
        destination_channel=row[3], source_channel=row[4], sequence_id=row[5],
        discovery_time=parse_time(row[6]), source_type=row[7],
        validation_status=row[8], validation_reason=row[9])


def _row_to_batch(row: tuple) -> PendingEdgeBatch:
    return PendingEdgeBatch(
        batch_id=row[0], crawl_id=row[1], source_channel=row[2],
        source_page_id=row[3], source_depth=int(row[4]), sequence_id=row[5],
        status=row[6], attempt_count=int(row[7]))


class SqlGraphStore:
    """All random-walk graph + tandem queue operations over a SqlBinding."""

    def __init__(self, binding: SqlBinding, crawl_id: str):
        self.binding = binding
        self.crawl_id = crawl_id

    def ensure_schema(self) -> None:
        self.binding.executescript(
            schema_for_dialect(getattr(self.binding, "dialect", "sqlite")))

    # ------------------------------------------------------------------
    # edge_records (`daprstate.go:3150-3279`)
    # ------------------------------------------------------------------
    def save_edge_records(self, edges: List[EdgeRecord]) -> None:
        if not edges:
            return
        self.binding.executemany(
            "INSERT INTO edge_records (destination_channel, source_channel, "
            "walkback, skipped, discovery_time, crawl_id, sequence_id) "
            "VALUES (?, ?, ?, ?, ?, ?, ?)",
            [(e.destination_channel, e.source_channel, int(e.walkback),
              int(e.skipped), _ts(e.discovery_time),
              e.crawl_id or self.crawl_id, e.sequence_id) for e in edges])

    def get_edge_record(self, sequence_id: str,
                        destination_channel: str) -> Optional[EdgeRecord]:
        rows = self.binding.query(
            f"SELECT {_EDGE_RECORD_COLS} FROM edge_records "
            "WHERE crawl_id = ? AND sequence_id = ? AND destination_channel = ? "
            "LIMIT 1",
            (self.crawl_id, sequence_id, destination_channel))
        return _row_to_edge_record(rows[0]) if rows else None

    def delete_edge_record(self, sequence_id: str, destination_channel: str) -> None:
        self.binding.execute(
            "DELETE FROM edge_records WHERE crawl_id = ? AND sequence_id = ? "
            "AND destination_channel = ?",
            (self.crawl_id, sequence_id, destination_channel))

    def get_random_skipped_edge(self, sequence_id: str,
                                source_channel: str) -> Optional[EdgeRecord]:
        rows = self.binding.query(
            f"SELECT {_EDGE_RECORD_COLS} FROM edge_records "
            "WHERE crawl_id = ? AND skipped = 1 AND sequence_id = ? "
            "AND source_channel = ? ORDER BY RANDOM() LIMIT 1",
            (self.crawl_id, sequence_id, source_channel))
        return _row_to_edge_record(rows[0]) if rows else None

    def promote_edge(self, sequence_id: str, destination_channel: str) -> None:
        self.binding.execute(
            "UPDATE edge_records SET skipped = 0 WHERE crawl_id = ? "
            "AND sequence_id = ? AND destination_channel = ?",
            (self.crawl_id, sequence_id, destination_channel))

    # ------------------------------------------------------------------
    # page_buffer (`daprstate.go:3619-3733`)
    # ------------------------------------------------------------------
    def add_page_to_page_buffer(self, page: Page) -> None:
        # Portable upsert (sqlite >= 3.24 and postgres share this syntax;
        # INSERT OR REPLACE is sqlite-only).
        self.binding.execute(
            "INSERT INTO page_buffer (page_id, parent_id, depth, "
            "url, crawl_id, sequence_id) VALUES (?, ?, ?, ?, ?, ?) "
            "ON CONFLICT(page_id) DO UPDATE SET parent_id = excluded.parent_id, "
            "depth = excluded.depth, url = excluded.url, "
            "crawl_id = excluded.crawl_id, sequence_id = excluded.sequence_id",
            (page.id, page.parent_id, page.depth, page.url,
             page.crawl_id or self.crawl_id, page.sequence_id))

    def get_pages_from_page_buffer(self, limit: int) -> List[Page]:
        rows = self.binding.query(
            "SELECT page_id, parent_id, depth, url, sequence_id FROM page_buffer "
            "WHERE crawl_id = ? LIMIT ?", (self.crawl_id, limit))
        return [Page(id=r[0], parent_id=r[1], depth=int(r[2]), url=r[3],
                     sequence_id=r[4]) for r in rows]

    def delete_page_buffer_pages(self, page_ids: List[str],
                                 page_urls: List[str]) -> None:
        """Delete only the processed pages — never wipe rows the validator
        wrote after the read (`state/interface.go:105-107`)."""
        if page_ids:
            self.binding.executemany(
                "DELETE FROM page_buffer WHERE crawl_id = ? AND page_id = ?",
                [(self.crawl_id, pid) for pid in page_ids])
        if page_urls:
            self.binding.executemany(
                "DELETE FROM page_buffer WHERE crawl_id = ? AND url = ?",
                [(self.crawl_id, url) for url in page_urls])

    # ------------------------------------------------------------------
    # seed_channels (`daprstate.go:3076-3578`)
    # ------------------------------------------------------------------
    def load_seed_channels(self, invalid_ttl_days: int = 30
                           ) -> List[Tuple[str, Optional[int]]]:
        """Rows (username, chat_id) excluding recently invalidated seeds."""
        cutoff = _ts(utcnow() - timedelta(days=invalid_ttl_days))
        rows = self.binding.query(
            "SELECT channel_username, chat_id FROM seed_channels "
            "WHERE invalidated_at IS NULL OR invalidated_at < ?", (cutoff,))
        return [(r[0], r[1]) for r in rows]

    def upsert_seed_channel_chat_id(self, username: str, chat_id: int) -> None:
        self.binding.execute(
            "INSERT INTO seed_channels (channel_username, chat_id, inserted_at) "
            "VALUES (?, ?, ?) ON CONFLICT(channel_username) "
            "DO UPDATE SET chat_id = excluded.chat_id",
            (username, chat_id, _ts(None)))

    def get_channel_last_crawled(self, username: str) -> Optional[datetime]:
        rows = self.binding.query(
            "SELECT last_crawled_at FROM seed_channels WHERE channel_username = ?",
            (username,))
        if not rows or rows[0][0] is None:
            return None
        return parse_time(rows[0][0])

    def mark_channel_crawled(self, username: str, chat_id: int) -> None:
        now = _ts(None)
        self.binding.execute(
            "INSERT INTO seed_channels (channel_username, chat_id, "
            "last_crawled_at, inserted_at) VALUES (?, ?, ?, ?) "
            "ON CONFLICT(channel_username) DO UPDATE SET "
            "chat_id = excluded.chat_id, last_crawled_at = excluded.last_crawled_at",
            (username, chat_id, now, now))

    def mark_seed_channel_invalid(self, username: str) -> None:
        self.binding.execute(
            "UPDATE seed_channels SET invalidated_at = ? WHERE channel_username = ?",
            (_ts(None), username))

    def get_random_seed_channel(self, invalid_ttl_days: int = 30) -> Optional[str]:
        cutoff = _ts(utcnow() - timedelta(days=invalid_ttl_days))
        rows = self.binding.query(
            "SELECT channel_username FROM seed_channels "
            "WHERE invalidated_at IS NULL OR invalidated_at < ? "
            "ORDER BY RANDOM() LIMIT 1", (cutoff,))
        return rows[0][0] if rows else None

    # ------------------------------------------------------------------
    # invalid_channels
    # ------------------------------------------------------------------
    def load_invalid_channels(self, ttl_days: int = 30) -> List[str]:
        cutoff = _ts(utcnow() - timedelta(days=ttl_days))
        rows = self.binding.query(
            "SELECT channel_username FROM invalid_channels WHERE invalidated_at >= ?",
            (cutoff,))
        return [r[0] for r in rows]

    def mark_channel_invalid(self, username: str, reason: str) -> None:
        self.binding.execute(
            "INSERT INTO invalid_channels (channel_username, reason, invalidated_at) "
            "VALUES (?, ?, ?) ON CONFLICT(channel_username) DO UPDATE SET "
            "reason = excluded.reason, invalidated_at = excluded.invalidated_at",
            (username, reason, _ts(None)))

    # ------------------------------------------------------------------
    # discovered_channels (`daprstate.go:3404-3578`)
    # ------------------------------------------------------------------
    def load_discovered_channels(self) -> List[str]:
        rows = self.binding.query(
            "SELECT channel_username FROM discovered_channels", ())
        return [r[0] for r in rows]

    def claim_discovered_channel(self, username: str, crawl_id: str) -> bool:
        """Atomic first-claim: the PK serializes inserts; rowcount tells us
        whether we won (`sql/validator-schema.sql` discovered_channels)."""
        affected = self.binding.execute(
            "INSERT INTO discovered_channels (channel_username, crawl_id, "
            "discovered_at) VALUES (?, ?, ?) "
            "ON CONFLICT(channel_username) DO NOTHING",
            (username, crawl_id or self.crawl_id, _ts(None)))
        return affected > 0

    def is_channel_discovered(self, username: str) -> bool:
        rows = self.binding.query(
            "SELECT 1 FROM discovered_channels WHERE channel_username = ? LIMIT 1",
            (username,))
        return bool(rows)

    def add_discovered_channel(self, username: str, crawl_id: str = "") -> None:
        self.claim_discovered_channel(username, crawl_id)

    # ------------------------------------------------------------------
    # tandem: pending_edge_batches + pending_edges (`daprstate.go:3944-4384`)
    # ------------------------------------------------------------------
    def create_pending_batch(self, batch: PendingEdgeBatch) -> None:
        self.binding.execute(
            "INSERT INTO pending_edge_batches (batch_id, crawl_id, "
            "source_channel, source_page_id, source_depth, sequence_id, "
            "status, attempt_count, created_at) VALUES (?, ?, ?, ?, ?, ?, ?, 0, ?)",
            (batch.batch_id, batch.crawl_id or self.crawl_id,
             batch.source_channel, batch.source_page_id, batch.source_depth,
             batch.sequence_id, BATCH_OPEN, _ts(None)))

    def insert_pending_edge(self, edge: PendingEdge) -> None:
        self.binding.execute(
            "INSERT INTO pending_edges (batch_id, crawl_id, destination_channel, "
            "source_channel, sequence_id, discovery_time, source_type, "
            "validation_status, validation_reason) "
            "VALUES (?, ?, ?, ?, ?, ?, ?, 'pending', '')",
            (edge.batch_id, edge.crawl_id or self.crawl_id,
             edge.destination_channel, edge.source_channel, edge.sequence_id,
             _ts(edge.discovery_time), edge.source_type))

    def close_pending_batch(self, batch_id: str) -> None:
        self.binding.execute(
            "UPDATE pending_edge_batches SET status = 'closed', closed_at = ? "
            "WHERE batch_id = ?", (_ts(None), batch_id))

    def claim_pending_edges(self, limit: int) -> List[PendingEdge]:
        """Atomically claim up to `limit` pending edges FIFO
        (`state/interface.go:148-152`)."""
        lock = getattr(self.binding, "for_update_clause", "")
        rows = self.binding.execute_returning(
            f"UPDATE pending_edges SET validation_status = 'validating', "
            f"validated_at = ? WHERE pending_id IN ("
            f"SELECT pending_id FROM pending_edges "
            f"WHERE validation_status = 'pending' "
            f"ORDER BY discovery_time, pending_id LIMIT ?{lock}) "
            f"RETURNING {_EDGE_COLS}",
            (_ts(None), limit))
        return [_row_to_edge(r) for r in rows]

    def update_pending_edge(self, update: PendingEdgeUpdate) -> None:
        self.binding.execute(
            "UPDATE pending_edges SET validation_status = ?, "
            "validation_reason = ?, validated_at = ? WHERE pending_id = ?",
            (update.validation_status, update.validation_reason, _ts(None),
             update.pending_id))

    def claim_walkback_batch(self) -> Tuple[Optional[PendingEdgeBatch],
                                            List[PendingEdge]]:
        """Claim the oldest closed batch whose edges are all final
        (`state/interface.go:158-161`, `daprstate.go:4017-4034`): edges still
        'pending' or 'validating' block the claim, and poison batches
        (attempt_count >= max) are never re-claimed."""
        lock = getattr(self.binding, "for_update_clause", "")
        rows = self.binding.execute_returning(
            f"UPDATE pending_edge_batches SET status = 'processing', "
            f"attempt_count = attempt_count + 1, claimed_at = ? "
            f"WHERE batch_id = (SELECT b.batch_id FROM pending_edge_batches b "
            f"WHERE b.status = 'closed' AND b.attempt_count < ? AND NOT EXISTS ("
            f"SELECT 1 FROM pending_edges e WHERE e.batch_id = b.batch_id "
            f"AND e.validation_status IN ('pending', 'validating')) "
            f"ORDER BY b.created_at LIMIT 1{lock}) "
            f"RETURNING {_BATCH_COLS}",
            (_ts(None), MAX_BATCH_ATTEMPTS))
        if not rows:
            return None, []
        batch = _row_to_batch(rows[0])
        edge_rows = self.binding.query(
            f"SELECT {_EDGE_COLS} FROM pending_edges WHERE batch_id = ?",
            (batch.batch_id,))
        return batch, [_row_to_edge(r) for r in edge_rows]

    def complete_pending_batch(self, batch_id: str) -> None:
        self.binding.execute(
            "UPDATE pending_edge_batches SET status = 'completed', "
            "completed_at = ? WHERE batch_id = ?", (_ts(None), batch_id))

    def count_incomplete_batches(self, crawl_id: str) -> int:
        rows = self.binding.query(
            "SELECT COUNT(*) FROM pending_edge_batches WHERE crawl_id = ? "
            "AND status <> 'completed'", (crawl_id or self.crawl_id,))
        return int(rows[0][0]) if rows else 0

    def recover_stale_batch_claims(self, stale_threshold_s: float) -> int:
        """Reset batches stuck 'processing' past the threshold back to
        'closed'; poison batches (attempt_count >= max) are logged and left
        (`daprstate.go:4300-4355`)."""
        cutoff = _ts(utcnow() - timedelta(seconds=stale_threshold_s))
        poison = self.binding.query(
            "SELECT batch_id, source_channel, attempt_count FROM "
            "pending_edge_batches WHERE status = 'processing' "
            "AND attempt_count >= ? AND claimed_at < ?",
            (MAX_BATCH_ATTEMPTS, cutoff))
        for batch_id, source_channel, attempts in poison:
            logger.error(
                "poison batch detected - stuck in processing after max attempts",
                extra={"batch_id": batch_id, "source_channel": source_channel,
                       "attempt_count": attempts, "log_tag": "validator_db"})
        return self.binding.execute(
            "UPDATE pending_edge_batches SET status = 'closed' "
            "WHERE status = 'processing' AND attempt_count < ? AND claimed_at < ?",
            (MAX_BATCH_ATTEMPTS, cutoff))

    def recover_stale_edge_claims(self, stale_threshold_s: float) -> int:
        """Reset edges stuck 'validating' back to 'pending'
        (`daprstate.go:4264-4294`)."""
        cutoff = _ts(utcnow() - timedelta(seconds=stale_threshold_s))
        return self.binding.execute(
            "UPDATE pending_edges SET validation_status = 'pending', "
            "validated_at = NULL WHERE validation_status = 'validating' "
            "AND validated_at < ?", (cutoff,))

    def recover_orphan_edges(self) -> int:
        """Delete edges whose batch already completed (validator crashed
        between complete and flush, `daprstate.go:4356-4384`)."""
        return self.binding.execute(
            "DELETE FROM pending_edges WHERE batch_id IN ("
            "SELECT batch_id FROM pending_edge_batches WHERE status = 'completed')")

    def flush_batch_stats(self, batch_id: str, crawl_id: str,
                          edges: List[PendingEdge]) -> None:
        """Upsert source_type_stats then delete the batch's edges
        (`state/interface.go:171-173`)."""
        stats: Dict[str, Dict[str, int]] = {}
        for e in edges:
            s = stats.setdefault(e.source_type or "", {
                "total": 0, "valid": 0, "not_channel": 0, "invalid": 0,
                "duplicate": 0})
            s["total"] += 1
            if e.validation_status in ("valid", "not_channel", "invalid", "duplicate"):
                s[e.validation_status] += 1
        for source_type, s in stats.items():
            self.binding.execute(
                "INSERT INTO source_type_stats (crawl_id, source_type, total, "
                "valid, not_channel, invalid, duplicate) "
                "VALUES (?, ?, ?, ?, ?, ?, ?) "
                "ON CONFLICT(crawl_id, source_type) DO UPDATE SET "
                "total = total + excluded.total, "
                "valid = valid + excluded.valid, "
                "not_channel = not_channel + excluded.not_channel, "
                "invalid = invalid + excluded.invalid, "
                "duplicate = duplicate + excluded.duplicate",
                (crawl_id or self.crawl_id, source_type, s["total"], s["valid"],
                 s["not_channel"], s["invalid"], s["duplicate"]))
        self.binding.execute(
            "DELETE FROM pending_edges WHERE batch_id = ?", (batch_id,))

    # ------------------------------------------------------------------
    # access_events (`daprstate.go:4385-4391`)
    # ------------------------------------------------------------------
    def insert_access_event(self, reason: str) -> None:
        self.binding.execute(
            "INSERT INTO access_events (reason, occurred_at) VALUES (?, ?)",
            (reason, _ts(None)))

    # ------------------------------------------------------------------
    def execute(self, sql_query: str, params: Sequence[Any] = ()) -> None:
        """Raw escape hatch (`state/interface.go:103`)."""
        self.binding.execute(sql_query, params)
