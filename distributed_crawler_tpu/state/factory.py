"""Config-driven state-manager selection.

Parity with `state/statefactory.go:11-52`: LocalConfig -> LocalStateManager,
SqlConfig (or default) -> CompositeStateManager.  The factory function is a
module-level variable so tests can swap it, exactly like the reference's
`NewStateManagerFactory` package var (`statefactory.go:11`) mocked in
`standalone/runner_test.go`.
"""

from __future__ import annotations

from typing import Callable

from .composite import CompositeStateManager
from .interface import StateConfig, StateManager
from .local import LocalStateManager


def _default_factory(config: StateConfig) -> StateManager:
    if config.local is not None and config.sql is None:
        return LocalStateManager(config)
    return CompositeStateManager(config)


_factory: Callable[[StateConfig], StateManager] = _default_factory


def create_state_manager(config: StateConfig) -> StateManager:
    return _factory(config)


def set_factory(factory: Callable[[StateConfig], StateManager]) -> None:
    """Swap the factory (test hook); pass `None` via reset_factory instead."""
    global _factory
    _factory = factory


def get_factory() -> Callable[[StateConfig], StateManager]:
    return _factory


def reset_factory() -> None:
    global _factory
    _factory = _default_factory
