"""Sharded media dedup cache.

Parity with the reference's sharded media cache (`state/daprstate.go:
1252-1680,2310-2668`): an index mapping media ID -> shard, bounded shards
(5000 items), a 30-day expiry sweep, and migration from a legacy single-blob
format.  Backed by any StorageProvider.
"""

from __future__ import annotations

import threading
from datetime import timedelta
from typing import Dict, List

from .datamodels import MediaCacheItem, utcnow
from .providers import StorageProvider

# Reference constants (`state/daprstate.go:170-171`).
MAX_SHARD_ITEMS = 5000
EXPIRY_DAYS = 30


class ShardedMediaCache:
    """Media-ID dedup cache with bounded shards and TTL expiry."""

    def __init__(self, provider: StorageProvider, root: str,
                 max_shard_items: int = MAX_SHARD_ITEMS,
                 expiry_days: int = EXPIRY_DAYS):
        self.provider = provider
        self.root = root.rstrip("/")
        self.max_shard_items = max_shard_items
        self.expiry_days = expiry_days
        self._lock = threading.RLock()
        # media ID -> shard ID
        self._index: Dict[str, str] = {}
        self._shards: Dict[str, Dict[str, MediaCacheItem]] = {}
        self._shard_order: List[str] = []
        self._dirty_shards: set = set()
        self._loaded = False

    # --- paths -----------------------------------------------------------
    def _index_path(self) -> str:
        return f"{self.root}/media-cache-index.json"

    def _shard_path(self, shard_id: str) -> str:
        return f"{self.root}/media-cache-{shard_id}.json"

    def _legacy_path(self) -> str:
        return f"{self.root}/media-cache.json"

    # --- persistence ------------------------------------------------------
    def load(self) -> None:
        """Load index + shards; migrate legacy single-blob format if present
        (`state/daprstate.go:2310-2430`)."""
        with self._lock:
            self._loaded = True
            raw = self.provider.load_json(self._index_path())
            if raw:
                self._shard_order = list(raw.get("shards") or [])
                self._index = dict(raw.get("mediaIndex") or {})
                for shard_id in self._shard_order:
                    shard_raw = self.provider.load_json(self._shard_path(shard_id)) or {}
                    items = {
                        mid: MediaCacheItem.from_dict(item)
                        for mid, item in (shard_raw.get("items") or {}).items()
                    }
                    self._shards[shard_id] = items
                self._expire_old()
                return
            # Legacy migration: one flat {media_id: item} blob.
            legacy = self.provider.load_json(self._legacy_path())
            if legacy:
                items = legacy.get("items", legacy)
                for mid, item in items.items():
                    if isinstance(item, dict):
                        self._put(MediaCacheItem.from_dict(item) if "id" in item
                                  else MediaCacheItem(id=mid, first_seen=utcnow()))
                    else:
                        self._put(MediaCacheItem(id=mid, first_seen=utcnow()))
                self.save()

    def save(self) -> None:
        with self._lock:
            if not self._loaded:
                # Nothing was read or written this run; saving now would
                # overwrite the persisted index with an empty one.
                return
            for shard_id in list(self._dirty_shards):
                shard = self._shards.get(shard_id, {})
                self.provider.save_json(self._shard_path(shard_id), {
                    "cacheId": shard_id,
                    "updateTime": utcnow().isoformat(),
                    "items": {mid: item.to_dict() for mid, item in shard.items()},
                })
            self._dirty_shards.clear()
            self.provider.save_json(self._index_path(), {
                "shards": self._shard_order,
                "mediaIndex": self._index,
                "updateTime": utcnow().isoformat(),
            })

    # --- cache operations -------------------------------------------------
    def has(self, media_id: str) -> bool:
        with self._lock:
            if not self._loaded:
                self.load()
            shard_id = self._index.get(media_id)
            if shard_id is None:
                return False
            item = self._shards.get(shard_id, {}).get(media_id)
            if item is None:
                return False
            if self._expired(item):
                self._remove(media_id)
                return False
            return True

    def mark(self, media_id: str, platform: str = "") -> None:
        with self._lock:
            if not self._loaded:
                self.load()
            shard_id = self._index.get(media_id)
            if shard_id is not None:
                item = self._shards.get(shard_id, {}).get(media_id)
                if item is not None and not self._expired(item):
                    return
                # Expired (or dangling) entry: remove so the re-mark refreshes
                # first_seen instead of silently no-oping.
                self._remove(media_id)
            self._put(MediaCacheItem(id=media_id, first_seen=utcnow(),
                                     platform=platform))

    def _put(self, item: MediaCacheItem) -> None:
        shard_id = self._writable_shard()
        self._shards[shard_id][item.id] = item
        self._index[item.id] = shard_id
        self._dirty_shards.add(shard_id)

    def _writable_shard(self) -> str:
        if self._shard_order:
            last = self._shard_order[-1]
            if len(self._shards.get(last, {})) < self.max_shard_items:
                return last
        shard_id = f"shard-{len(self._shard_order):05d}"
        self._shard_order.append(shard_id)
        self._shards[shard_id] = {}
        return shard_id

    def _remove(self, media_id: str) -> None:
        shard_id = self._index.pop(media_id, None)
        if shard_id and media_id in self._shards.get(shard_id, {}):
            del self._shards[shard_id][media_id]
            self._dirty_shards.add(shard_id)

    def _expired(self, item: MediaCacheItem) -> bool:
        if item.first_seen is None:
            return False
        return utcnow() - item.first_seen > timedelta(days=self.expiry_days)

    def _expire_old(self) -> None:
        for mid in [m for sid in self._shard_order
                    for m, item in self._shards.get(sid, {}).items()
                    if self._expired(item)]:
            self._remove(mid)

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)
