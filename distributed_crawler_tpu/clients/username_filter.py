"""Username plausibility pre-filter.

Parity with `telegramhelper/username_filter.go:26-81`: Telegram's documented
username rules (5-32 chars, ASCII alphanumeric + underscore, starts with an
ASCII letter, doesn't end with underscore) plus heuristics for known
false-positive patterns (bot suffixes, path-like strings).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class UsernameFilterResult:
    valid: bool
    reason: str = ""  # empty if valid


def _is_valid_char(ch: str) -> bool:
    return ch.isascii() and (ch.isalnum() or ch == "_")


def filter_username(username: str) -> UsernameFilterResult:
    """`username_filter.go:26-81`."""
    if len(username) < 5:
        return UsernameFilterResult(False, "too_short")
    if len(username) > 32:
        return UsernameFilterResult(False, "too_long")
    first = username[0]
    if not (first.isascii() and first.isalpha()):
        return UsernameFilterResult(False, "invalid_start_char")
    if username.endswith("_"):
        return UsernameFilterResult(False, "ends_with_underscore")
    if not all(_is_valid_char(c) for c in username):
        return UsernameFilterResult(False, "invalid_char")
    if any(c in username for c in "/\\~."):
        return UsernameFilterResult(False, "looks_like_path")
    lower = username.lower()
    if lower.endswith("_bot") or lower.endswith("bot"):
        # Bots are never supergroups.
        return UsernameFilterResult(False, "bot_suffix")
    return UsernameFilterResult(True)
