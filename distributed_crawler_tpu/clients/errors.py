"""Telegram client error taxonomy.

Parity with the reference's error handling (`crawl/runner.go:32-113`):
FLOOD_WAIT parsing for both TDLib ("FLOOD_WAIT_N") and HTTP-429
("retry after N") formats, 400 detection, and the retire threshold.
"""

from __future__ import annotations

import re
from typing import Optional, Tuple

# FLOOD_WAITs at or above this many seconds permanently retire the connection
# (`crawl/runner.go:49`).
FLOOD_WAIT_RETIRE_THRESHOLD_S = 300


class TelegramError(Exception):
    """An error returned by the Telegram client boundary."""

    def __init__(self, code: int, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code
        self.message = message


class FloodWaitError(TelegramError):
    """A 429 FLOOD_WAIT with a retry-after duration."""

    def __init__(self, retry_after_s: int):
        super().__init__(429, f"FLOOD_WAIT_{retry_after_s}")
        self.retry_after_s = retry_after_s


_FLOOD_RE = re.compile(r"FLOOD_WAIT_(\d+)")
_RETRY_RE = re.compile(r"retry after (\d+)")


def parse_flood_wait_seconds(err: Optional[BaseException]) -> Tuple[int, bool]:
    """Returns (seconds, is_flood_wait) (`crawl/runner.go:55-97`).

    (0, True) means a FLOOD_WAIT whose duration couldn't be parsed — treat as
    a short ban (skip, don't retire).
    """
    if err is None:
        return 0, False
    if isinstance(err, FloodWaitError):
        return err.retry_after_s, True
    s = str(err)
    if "FLOOD_WAIT_" in s:
        m = _FLOOD_RE.search(s)
        return (int(m.group(1)), True) if m else (0, True)
    if "retry after " in s:
        m = _RETRY_RE.search(s)
        return (int(m.group(1)), True) if m else (0, True)
    return 0, False


_MIGRATE_RE = re.compile(r"(?:PHONE|NETWORK|USER)_MIGRATE_(\d+)")


def parse_migrate_dc(err: Optional[BaseException]) -> Optional[int]:
    """Telegram's 303 DC-redirect family (PHONE/NETWORK/USER_MIGRATE_X):
    returns the target DC id, or None if this isn't a migrate error.
    TDLib consumes these internally; this framework's client surfaces them
    through the same taxonomy (`clients/native.py` follows the redirect)."""
    if err is None:
        return None
    m = _MIGRATE_RE.search(str(err))
    return int(m.group(1)) if m else None


def is_telegram_400(err: Optional[BaseException]) -> bool:
    """Permanently-invalid channel detection (`crawl/runner.go:104-113`)."""
    if err is None:
        return False
    if isinstance(err, TelegramError) and err.code == 400:
        return True
    s = str(err)
    return ("[400]" in s
            or "400 USERNAME_NOT_OCCUPIED" in s
            or "400 USERNAME_INVALID" in s
            or "no messages found in the chat" in s)
