"""Account-free Telegram channel validation by scraping https://t.me/<user>.

Parity with `telegramhelper/channelvalidator.go` + `validator_rate_limiter.go`:
- title/robots-meta parsing rules (`channelvalidator.go:130-192`)
- transient-vs-blocked error taxonomy (`:27-47`)
- rotating Chromium UA pool (`:18-23`)
- token-bucket + jitter request limiter (`validator_rate_limiter.go:23-55`)

Transport note: the reference used uTLS to present a Chrome JA3 fingerprint
(`utlstransport.go:19-57`).  Python's ssl stack can't reshape its
ClientHello, so the fingerprint-matched transport lives in the C++ native
layer (`native/net.h`: Chrome cipher ordering, X25519-first groups, SNI) —
select it with ``make_transport("chrome")`` / config
``validator_transport: chrome``.  The ``transport`` parameter accepts any
callable ``(url, headers) -> (status_code, body_bytes)``, so tests use
fixtures and other stacks can slot in.
"""

from __future__ import annotations

import logging
import random
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

from .rate_limiter import Clock, SystemClock, TokenBucket

logger = logging.getLogger("dct.clients.validator")

# Chromium-only UA pool — mixing engines would mismatch the TLS fingerprint
# (`channelvalidator.go:18-23`).
BROWSER_USER_AGENTS = [
    "Mozilla/5.0 (Windows NT 10.0; Win64; x64) AppleWebKit/537.36 (KHTML, like Gecko) Chrome/124.0.0.0 Safari/537.36",
    "Mozilla/5.0 (Macintosh; Intel Mac OS X 10_15_7) AppleWebKit/537.36 (KHTML, like Gecko) Chrome/124.0.0.0 Safari/537.36",
    "Mozilla/5.0 (X11; Linux x86_64) AppleWebKit/537.36 (KHTML, like Gecko) Chrome/124.0.0.0 Safari/537.36",
    "Mozilla/5.0 (Windows NT 10.0; Win64; x64) AppleWebKit/537.36 (KHTML, like Gecko) Chrome/124.0.0.0 Safari/537.36 Edg/124.0.0.0",
]

MAX_READ_BYTES = 64 * 1024  # signals live in <head> (`channelvalidator.go:107`)

# Error kinds (`channelvalidator.go:27-40`).
TRANSIENT = "transient"  # retry the edge later
BLOCKED = "blocked"  # IP-level block / soft block: pause validation


class ValidationHTTPError(Exception):
    def __init__(self, kind: str, message: str):
        super().__init__(message)
        self.kind = kind


@dataclass
class ChannelValidationResult:
    """`channelvalidator.go:50-54`."""

    status: str = ""  # valid | not_channel | invalid
    reason: str = ""  # "" | not_supergroup | not_found


Transport = Callable[[str, dict], Tuple[int, bytes]]


def urllib_transport(url: str, headers: dict) -> Tuple[int, bytes]:
    """Default stdlib transport (no fingerprint shaping — see module note)."""
    req = urllib.request.Request(url, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, resp.read(MAX_READ_BYTES)
    except urllib.error.HTTPError as e:
        return e.code, e.read(MAX_READ_BYTES) if e.fp else b""


def chrome_transport(url: str, headers: dict, *,
                     tls_insecure: bool = False,
                     port: int = 0,
                     max_redirects: int = 5) -> Tuple[int, bytes]:
    """Fingerprint-matched transport: the native Chrome-shaped TLS stack
    (`native/net.h`), so t.me sees browser-like ciphers/SNI instead of a
    Python stack — the property the reference's uTLS leg existed for.
    Follows up to ``max_redirects`` 3xx hops, matching urllib_transport's
    behavior so the selectable transports classify identically."""
    from urllib.parse import urljoin, urlsplit

    from .native import native_https_get

    for _ in range(max_redirects + 1):
        parts = urlsplit(url)
        host = parts.hostname or ""
        use_port = port or parts.port or \
            (80 if parts.scheme == "http" else 443)
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query
        out = native_https_get(
            host, path=path, port=use_port, headers=headers, sni=host,
            tls_insecure=tls_insecure, plain=(parts.scheme == "http"),
            max_body=MAX_READ_BYTES)
        if out["status"] in (301, 302, 303, 307, 308) and \
                out.get("location"):
            url = urljoin(url, out["location"])
            continue
        return out["status"], out["body"]
    raise ValidationHTTPError(TRANSIENT,
                              f"redirect loop after {max_redirects} hops")


def make_transport(kind: str = "urllib", **kw) -> Transport:
    """Selectable validator transport: ``urllib`` (stdlib, default) or
    ``chrome`` (native Chrome-shaped TLS)."""
    if kind in ("", "urllib"):
        return urllib_transport
    if kind == "chrome":
        return lambda url, headers: chrome_transport(url, headers, **kw)
    raise ValueError(f"unknown validator transport {kind!r}; "
                     f"expected 'urllib' or 'chrome'")


def _extract_title(html: str) -> str:
    """First <title> content (`channelvalidator.go:160-174`)."""
    lower = html.lower()
    start = lower.find("<title>")
    if start == -1:
        return ""
    start += len("<title>")
    end = lower.find("</title>", start)
    if end == -1:
        return ""
    return html[start:end].strip()


def _has_robots_noindex(html: str) -> bool:
    """`channelvalidator.go:177-192`."""
    lower = html.lower()
    idx = lower.find('name="robots"')
    if idx == -1:
        return False
    tag_start = lower.rfind("<", 0, idx)
    tag_end = lower.find(">", idx)
    if tag_start == -1 or tag_end == -1:
        return False
    return "noindex" in lower[tag_start:tag_end + 1]


def parse_channel_html(html: str) -> ChannelValidationResult:
    """Parsing rules derived from saved t.me responses
    (`channelvalidator.go:130-158`):

    - title contains "Telegram: View @"        -> valid channel/supergroup
    - title contains "Telegram: Contact @":
        robots noindex -> username not occupied (invalid/not_found)
        otherwise      -> user/bot/basic group (not_channel/not_supergroup)
    - title "Telegram Messenger" (reserved-path redirect) -> invalid/not_found

    Raises ValueError on unrecognised titles (caller treats as soft-block).
    """
    title = _extract_title(html)
    if "Telegram: View @" in title:
        return ChannelValidationResult(status="valid")
    if "Telegram: Contact @" in title:
        if _has_robots_noindex(html):
            return ChannelValidationResult(status="invalid", reason="not_found")
        return ChannelValidationResult(status="not_channel", reason="not_supergroup")
    if title == "Telegram Messenger":
        return ChannelValidationResult(status="invalid", reason="not_found")
    raise ValueError(f"unrecognised title pattern: {title!r}")


def validate_channel_http(username: str,
                          transport: Transport = urllib_transport,
                          rng: Optional[random.Random] = None,
                          base_url: str = "https://t.me"
                          ) -> ChannelValidationResult:
    """Fetch {base_url}/<username> and classify (`channelvalidator.go:64-127`).

    ``base_url`` defaults to the real t.me; operators can point it at a
    mirror/forward proxy (config ``validator_base_url``), and tests drive
    the full pod against an in-tree HTTPS server."""
    rng = rng or random
    url = f"{base_url.rstrip('/')}/{username}"
    headers = {
        "User-Agent": rng.choice(BROWSER_USER_AGENTS),
        "Accept": "text/html,application/xhtml+xml,application/xml;q=0.9,"
                  "image/webp,*/*;q=0.8",
        "Accept-Language": "en-US,en;q=0.9",
        "Upgrade-Insecure-Requests": "1",
        "Sec-Fetch-Dest": "document",
        "Sec-Fetch-Mode": "navigate",
        "Sec-Fetch-Site": "none",
    }
    try:
        status_code, body = transport(url, headers)
    except Exception as e:
        raise ValidationHTTPError(
            TRANSIENT, f"HTTP request failed for {username}: {e}") from e

    if status_code != 200:
        # 5xx transient; 403/429/other 4xx treated as block (`:95-105`).
        kind = TRANSIENT if status_code >= 500 else BLOCKED
        raise ValidationHTTPError(
            kind, f"unexpected status {status_code} for {username}")

    html = body[:MAX_READ_BYTES].decode("utf-8", errors="replace")
    try:
        return parse_channel_html(html)
    except ValueError as e:
        # Unrecognised 200 response: soft-block, not definitive invalid.
        logger.warning("unrecognised HTML response",
                       extra={"channel": username})
        raise ValidationHTTPError(
            BLOCKED, f"failed to parse response for {username}: {e}") from e


class ValidatorRateLimiter:
    """Token-bucket + jitter limiter for validator HTTP requests
    (`validator_rate_limiter.go:23-55`)."""

    def __init__(self, requests_per_minute: float = 6.0, jitter_ms: int = 200,
                 clock: Optional[Clock] = None,
                 rng: Optional[random.Random] = None):
        self.clock = clock or SystemClock()
        self._bucket = TokenBucket(requests_per_minute, self.clock)
        self.jitter_ms = jitter_ms
        self._rng = rng or random.Random()

    def wait(self) -> float:
        waited = self._bucket.wait()
        jitter = (self._rng.randint(0, self.jitter_ms) / 1000.0
                  if self.jitter_ms > 0 else 0.0)
        self.clock.sleep(jitter)
        return waited + jitter
