"""In-process Telegram network simulation.

The test-double half of the client boundary (reference analog:
`crawl/mocks_test.go` MockTDLibClient, 553 LoC) — but promoted to a
first-class backend: a `SimNetwork` holds channels/messages/files, and any
number of `SimTelegramClient`s connect to it.  Supports fault injection
(FLOOD_WAIT, 400s, connection errors) and latency modelling so the reactive
GetMessage limiter and cache-attribution paths are exercised realistically.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .errors import FloodWaitError, TelegramError
from .rate_limiter import Clock
from .telegram import (
    TLBasicGroupFullInfo,
    TLChat,
    TLFile,
    TLMessage,
    TLMessageLink,
    TLMessages,
    TLMessageThreadInfo,
    TLSupergroup,
    TLSupergroupFullInfo,
    TLUser,
)


@dataclass
class SimChannel:
    """A public supergroup/channel in the simulated network."""

    username: str
    chat_id: int
    title: str = ""
    description: str = ""
    member_count: int = 1000
    is_channel: bool = True
    is_supergroup: bool = True
    messages: List[TLMessage] = field(default_factory=list)
    # Assigned by SimNetwork; always nonzero so it never collides with the
    # TLChat "no supergroup" default of 0.
    supergroup_id: int = 0


class SimNetwork:
    """Shared simulated Telegram backend."""

    def __init__(self, cache_latency_s: float = 0.001,
                 server_latency_s: float = 0.02):
        self.cache_latency_s = cache_latency_s
        self.server_latency_s = server_latency_s
        self._lock = threading.RLock()
        self.channels: Dict[str, SimChannel] = {}
        self.by_chat_id: Dict[int, SimChannel] = {}
        self.files: Dict[str, bytes] = {}
        self.comments: Dict[Tuple[int, int], List[TLMessage]] = {}
        # method -> list of pending injected errors (popped per call)
        self._faults: Dict[str, List[BaseException]] = {}
        self._next_chat_id = 1_000_000_000_000
        self._next_supergroup_id = 1

    # --- topology ---------------------------------------------------------
    def add_channel(self, username: str, messages: Optional[List[TLMessage]] = None,
                    **kw) -> SimChannel:
        with self._lock:
            chat_id = kw.pop("chat_id", None)
            if chat_id is None:
                while self._next_chat_id in self.by_chat_id:
                    self._next_chat_id += 1
                chat_id = self._next_chat_id
                self._next_chat_id += 1
            supergroup_id = kw.pop("supergroup_id", None)
            if supergroup_id is None:
                used = {c.supergroup_id for c in self.channels.values()}
                while self._next_supergroup_id in used:
                    self._next_supergroup_id += 1
                supergroup_id = self._next_supergroup_id
                self._next_supergroup_id += 1
            ch = SimChannel(username=username.lower(), chat_id=chat_id,
                            title=kw.pop("title", username),
                            supergroup_id=supergroup_id, **kw)
            for i, m in enumerate(messages or []):
                m.chat_id = chat_id
                if not m.id:
                    m.id = (i + 1) * 1048576  # TDLib-style message IDs
            ch.messages = list(messages or [])
            self.channels[ch.username] = ch
            self.by_chat_id[chat_id] = ch
            return ch

    def add_file(self, remote_id: str, content: bytes) -> None:
        with self._lock:
            self.files[remote_id] = content

    def add_comments(self, chat_id: int, message_id: int,
                     comments: List[TLMessage]) -> None:
        with self._lock:
            self.comments[(chat_id, message_id)] = list(comments)

    # --- fault injection --------------------------------------------------
    def inject_fault(self, method: str, error: BaseException, count: int = 1) -> None:
        with self._lock:
            self._faults.setdefault(method, []).extend([error] * count)

    def inject_flood_wait(self, method: str, seconds: int, count: int = 1) -> None:
        self.inject_fault(method, FloodWaitError(seconds), count)

    def _check_fault(self, method: str) -> None:
        with self._lock:
            pending = self._faults.get(method)
            if pending:
                raise pending.pop(0)


class SimTelegramClient:
    """A client connected to a SimNetwork, implementing the 16-method surface.

    Maintains a per-client local message cache: the first fetch of a message
    is a "server" call (server latency), repeats are cache hits — mirroring
    TDLib's local SQLite DB and driving the reactive GetMessage limiter.
    """

    def __init__(self, network: SimNetwork, conn_id: str = "conn0",
                 clock: Optional[Clock] = None):
        self.network = network
        self.conn_id = conn_id
        self.clock = clock
        self.closed = False
        self.calls: List[Tuple[str, tuple]] = []
        self._message_cache: Set[Tuple[int, int]] = set()
        self._downloaded: Dict[int, TLFile] = {}
        self._next_file_id = 1

    # --- internals --------------------------------------------------------
    def _call(self, method: str, *args, server: bool = True) -> None:
        if self.closed:
            raise TelegramError(500, "client closed")
        self.calls.append((method, args))
        self.network._check_fault(method)
        if self.clock is not None:
            self.clock.sleep(self.network.server_latency_s if server
                             else self.network.cache_latency_s)

    def _chat(self, chat_id: int) -> "SimChannel":
        ch = self.network.by_chat_id.get(chat_id)
        if ch is None:
            raise TelegramError(400, "CHANNEL_INVALID")
        return ch

    # --- the 16 methods ---------------------------------------------------
    def get_message(self, chat_id: int, message_id: int) -> TLMessage:
        cached = (chat_id, message_id) in self._message_cache
        self._call("GetMessage", chat_id, message_id, server=not cached)
        ch = self._chat(chat_id)
        for m in ch.messages:
            if m.id == message_id:
                self._message_cache.add((chat_id, message_id))
                return m
        raise TelegramError(404, "message not found")

    def get_message_link(self, chat_id: int, message_id: int) -> TLMessageLink:
        self._call("GetMessageLink", chat_id, message_id, server=False)
        ch = self._chat(chat_id)
        return TLMessageLink(link=f"https://t.me/{ch.username}/{message_id // 1048576}",
                             is_public=True)

    def get_message_thread_history(self, chat_id: int, message_id: int,
                                   from_message_id: int = 0,
                                   limit: int = 100) -> TLMessages:
        self._call("GetMessageThreadHistory", chat_id, message_id)
        comments = self.network.comments.get((chat_id, message_id), [])
        return TLMessages(total_count=len(comments), messages=comments[:limit])

    def get_message_thread(self, chat_id: int, message_id: int) -> TLMessageThreadInfo:
        self._call("GetMessageThread", chat_id, message_id)
        comments = self.network.comments.get((chat_id, message_id), [])
        if not comments:
            raise TelegramError(400, "message thread not found")
        return TLMessageThreadInfo(chat_id=chat_id, message_thread_id=message_id,
                                   reply_count=len(comments))

    def get_remote_file(self, remote_file_id: str) -> TLFile:
        self._call("GetRemoteFile", remote_file_id, server=False)
        if remote_file_id not in self.network.files:
            raise TelegramError(400, "file not found")
        file_id = self._next_file_id
        self._next_file_id += 1
        f = TLFile(id=file_id, remote_id=remote_file_id,
                   size=len(self.network.files[remote_file_id]))
        self._downloaded[file_id] = f
        return f

    def download_file(self, file_id: int) -> TLFile:
        self._call("DownloadFile", file_id)
        f = self._downloaded.get(file_id)
        if f is None:
            raise TelegramError(400, "unknown file id")
        import os
        import tempfile
        fd, path = tempfile.mkstemp(prefix=f"sim_{self.conn_id}_")
        with os.fdopen(fd, "wb") as out:
            out.write(self.network.files[f.remote_id])
        f.local_path = path
        f.downloaded = True
        return f

    def get_chat_history(self, chat_id: int, from_message_id: int = 0,
                         offset: int = 0, limit: int = 100) -> TLMessages:
        self._call("GetChatHistory", chat_id, from_message_id, limit)
        ch = self._chat(chat_id)
        # TDLib returns newest-first, strictly older than from_message_id
        # (0 = from the latest).
        ordered = sorted(ch.messages, key=lambda m: -m.id)
        if from_message_id:
            ordered = [m for m in ordered if m.id < from_message_id]
        page = ordered[:limit]
        for m in page:
            self._message_cache.add((chat_id, m.id))
        return TLMessages(total_count=len(ch.messages), messages=page)

    def search_public_chat(self, username: str) -> TLChat:
        self._call("SearchPublicChat", username)
        ch = self.network.channels.get(username.lower())
        if ch is None:
            raise TelegramError(400, "USERNAME_NOT_OCCUPIED")
        return TLChat(id=ch.chat_id, title=ch.title,
                      type="supergroup" if ch.is_supergroup else "private",
                      supergroup_id=ch.supergroup_id)

    def get_chat(self, chat_id: int) -> TLChat:
        self._call("GetChat", chat_id, server=False)
        ch = self._chat(chat_id)
        return TLChat(id=ch.chat_id, title=ch.title,
                      type="supergroup" if ch.is_supergroup else "private",
                      supergroup_id=ch.supergroup_id)

    def get_supergroup(self, supergroup_id: int) -> TLSupergroup:
        self._call("GetSupergroup", supergroup_id, server=False)
        for ch in self.network.channels.values():
            if ch.supergroup_id == supergroup_id:
                return TLSupergroup(id=supergroup_id, username=ch.username,
                                    member_count=ch.member_count,
                                    is_channel=ch.is_channel)
        raise TelegramError(400, "SUPERGROUP_INVALID")

    def get_supergroup_full_info(self, supergroup_id: int) -> TLSupergroupFullInfo:
        self._call("GetSupergroupFullInfo", supergroup_id)
        for ch in self.network.channels.values():
            if ch.supergroup_id == supergroup_id:
                return TLSupergroupFullInfo(description=ch.description,
                                            member_count=ch.member_count)
        raise TelegramError(400, "SUPERGROUP_INVALID")

    def close(self) -> None:
        self.closed = True

    def get_me(self) -> TLUser:
        self._call("GetMe", server=False)
        return TLUser(id=1, username=f"sim_{self.conn_id}")

    def get_basic_group_full_info(self, basic_group_id: int) -> TLBasicGroupFullInfo:
        self._call("GetBasicGroupFullInfo", basic_group_id)
        raise TelegramError(400, "BASIC_GROUP_INVALID")

    def get_user(self, user_id: int) -> TLUser:
        self._call("GetUser", user_id, server=False)
        return TLUser(id=user_id, username=f"user{user_id}")

    def delete_file(self, file_id: int) -> None:
        self._call("DeleteFile", file_id, server=False)
        f = self._downloaded.pop(file_id, None)
        if f is not None and f.local_path:
            import os
            try:
                os.remove(f.local_path)
            except OSError:
                pass
