"""YouTube Data API v3 client.

Parity with the reference's `client/youtube_client.go` (1931 LoC):
- channel info (`:195`), paged video listing via the uploads playlist
  (`:319-878`), batched video lookup with a stats cache (`:1077-1112,
  1899-1912`);
- random sampling via 5-char lowercase prefix generation + batch verification
  ("Dialing for Videos", McGrady et al. 2023; `:886-910,1109-...`,
  `model/youtube/types.go:58-60`);
- snowball discovery via channel IDs extracted from video descriptions
  (`:1547,1856`);
- API-key transport seam (`:59-75`) — injectable here, so tests run against
  `FakeYouTubeTransport` and production supplies an HTTP transport.
"""

from __future__ import annotations

import logging
import random
import re
import threading
from datetime import datetime
from typing import Any, Callable, Dict, List, Optional, Protocol, Tuple

from ..datamodel.post import parse_time
from ..datamodel.youtube import YouTubeChannel, YouTubeVideo

logger = logging.getLogger("dct.clients.youtube")

# transport(endpoint, params) -> parsed JSON dict.  Endpoints mirror the Data
# API: "channels", "playlistItems", "videos", "search".
YouTubeTransport = Callable[[str, Dict[str, Any]], Dict[str, Any]]

PREFIX_LEN = 5
MAX_RANDOM_ATTEMPTS = 50  # youtube_client.go:1137
VIDEO_BATCH = 50  # API max ids per videos.list call
SNOWBALL_MIN_VIDEOS = 10  # channels with > 10 videos (types.go:62)

_CHANNEL_ID_RE = re.compile(r"(UC[A-Za-z0-9_-]{22})")

DATA_API_BASE = "https://www.googleapis.com/youtube/v3"


class HttpYouTubeTransport:
    """Production transport: urllib against the Data API v3
    (`client/youtube_client.go:59-75` used an API-key http.RoundTripper).
    Tests and offline runs inject `FakeYouTubeTransport` instead."""

    def __init__(self, base_url: str = DATA_API_BASE, timeout_s: float = 30.0):
        self.base_url = base_url
        self.timeout_s = timeout_s

    def __call__(self, endpoint: str, params: Dict[str, Any]) -> Dict[str, Any]:
        import json as _json
        import urllib.parse
        import urllib.request
        url = (f"{self.base_url}/{endpoint}?"
               + urllib.parse.urlencode(params, doseq=True))
        req = urllib.request.Request(url, headers={
            "Accept": "application/json",
            "User-Agent": "dct-crawler/1.0"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return _json.loads(resp.read().decode("utf-8"))


class YouTubeClient(Protocol):
    """`model/youtube/types.go:39-64`."""

    def connect(self) -> None: ...

    def disconnect(self) -> None: ...

    def get_channel_info(self, channel_id: str) -> YouTubeChannel: ...

    def get_videos(self, channel_id: str, from_time: Optional[datetime],
                   to_time: Optional[datetime], limit: int) -> List[YouTubeVideo]: ...

    def get_videos_from_channel(self, channel_id: str,
                                from_time: Optional[datetime],
                                to_time: Optional[datetime],
                                limit: int) -> List[YouTubeVideo]: ...

    def get_videos_by_ids(self, video_ids: List[str]) -> List[YouTubeVideo]: ...

    def get_random_videos(self, from_time: Optional[datetime],
                          to_time: Optional[datetime],
                          limit: int) -> List[YouTubeVideo]: ...

    def get_snowball_videos(self, seed_channel_ids: List[str],
                            from_time: Optional[datetime],
                            to_time: Optional[datetime],
                            limit: int) -> List[YouTubeVideo]: ...


def generate_random_prefix(rng: random.Random, length: int = PREFIX_LEN) -> str:
    """5-char lowercase alphabetic prefix query (`youtube_client.go:886-910`).

    Only a-z: YouTube search is case-insensitive for letters, so one query
    covers all 2^5 case permutations; digits would corrupt the coverage term.
    The search token is "watch?v=<prefix>" — the indexer splits video URLs on
    '-', so IDs shaped <PREFIX>-xxxxx are returned for the query.
    """
    letters = "abcdefghijklmnopqrstuvwxyz"
    return "watch?v=" + "".join(rng.choice(letters) for _ in range(length))


def _parse_video(item: Dict[str, Any]) -> YouTubeVideo:
    snippet = item.get("snippet") or {}
    stats = item.get("statistics") or {}
    content = item.get("contentDetails") or {}
    return YouTubeVideo(
        id=item.get("id", ""),
        channel_id=snippet.get("channelId", ""),
        title=snippet.get("title", ""),
        description=snippet.get("description", ""),
        published_at=parse_time(snippet.get("publishedAt")),
        view_count=int(stats.get("viewCount") or 0),
        like_count=int(stats.get("likeCount") or 0),
        comment_count=int(stats.get("commentCount") or 0),
        duration=content.get("duration", ""),
        thumbnails={k: v.get("url", "") for k, v in
                    (snippet.get("thumbnails") or {}).items()},
        tags=list(snippet.get("tags") or []),
        language=snippet.get("defaultAudioLanguage")
        or snippet.get("defaultLanguage") or "",
    )


class YouTubeDataClient:
    """Data API client over an injectable transport."""

    def __init__(self, api_key: str, transport: YouTubeTransport,
                 rng: Optional[random.Random] = None):
        self.api_key = api_key
        self.transport = transport
        self._rng = rng or random.Random()
        self._rng_lock = threading.Lock()
        self._connected = False
        # video-stats cache (`youtube_client.go:1899-1912`)
        self._video_cache: Dict[str, YouTubeVideo] = {}
        # full-channel cache: conversion does a lookup per video, so each
        # distinct channel must cost one channels.list call, not N
        # (`youtube_crawler.go:548` "improved cache")
        self._channel_cache: Dict[str, YouTubeChannel] = {}
        self._cache_lock = threading.Lock()

    # --- lifecycle --------------------------------------------------------
    def connect(self) -> None:
        if not self.api_key:
            raise ValueError("YouTube API key is required")
        self._connected = True

    def disconnect(self) -> None:
        self._connected = False

    def _call(self, endpoint: str, params: Dict[str, Any]) -> Dict[str, Any]:
        if not self._connected:
            raise RuntimeError("client not connected")
        params = dict(params)
        params["key"] = self.api_key
        return self.transport(endpoint, params)

    # --- channels ---------------------------------------------------------
    def get_channel_info(self, channel_id: str) -> YouTubeChannel:
        """`youtube_client.go:195`; cached per channel ID.

        Accepts a UC... id, an ``@handle`` (Data API ``forHandle``), or a
        legacy ``user/Name`` (``forUsername``)."""
        with self._cache_lock:
            cached = self._channel_cache.get(channel_id)
        if cached is not None:
            return cached
        if channel_id.startswith("@"):
            selector = {"forHandle": channel_id}
        elif channel_id.startswith("user/"):
            selector = {"forUsername": channel_id[len("user/"):]}
        else:
            selector = {"id": channel_id}
        resp = self._call("channels", {
            "part": "snippet,statistics,contentDetails", **selector})
        items = resp.get("items") or []
        if not items:
            raise LookupError(f"channel not found: {channel_id}")
        item = items[0]
        snippet = item.get("snippet") or {}
        stats = item.get("statistics") or {}
        channel = YouTubeChannel(
            id=item.get("id", channel_id),
            title=snippet.get("title", ""),
            description=snippet.get("description", ""),
            thumbnails={k: v.get("url", "") for k, v in
                        (snippet.get("thumbnails") or {}).items()},
            subscriber_count=int(stats.get("subscriberCount") or 0),
            view_count=int(stats.get("viewCount") or 0),
            video_count=int(stats.get("videoCount") or 0),
            country=snippet.get("country", ""),
            published_at=parse_time(snippet.get("publishedAt")),
        )
        with self._cache_lock:
            self._channel_cache[channel_id] = channel
        return channel

    # --- videos -----------------------------------------------------------
    def get_videos_from_channel(self, channel_id: str,
                                from_time: Optional[datetime] = None,
                                to_time: Optional[datetime] = None,
                                limit: int = 50) -> List[YouTubeVideo]:
        """Paged uploads-playlist walk (`youtube_client.go:319-878`)."""
        if not channel_id.startswith("UC"):
            # @handle / user/Name: resolve to the canonical UC id first.
            channel_id = self.get_channel_info(channel_id).id
        uploads = "UU" + channel_id[2:] if channel_id.startswith("UC") else channel_id
        videos: List[YouTubeVideo] = []
        page_token = ""
        # Filter by window per page and keep paginating until `limit` in-window
        # videos are found or the playlist ends (reference behavior:
        # youtube_client.go GetVideosFromChannel filters inside the page loop).
        # limit <= 0 means "all uploads".
        while True:
            params = {"part": "contentDetails", "playlistId": uploads,
                      "maxResults": 50}
            if page_token:
                params["pageToken"] = page_token
            resp = self._call("playlistItems", params)
            page_ids = [vid for item in resp.get("items") or []
                        if (vid := (item.get("contentDetails") or {})
                            .get("videoId", ""))]
            for video in self.get_videos_by_ids(page_ids):
                if _in_window(video, from_time, to_time):
                    videos.append(video)
            if 0 < limit <= len(videos):
                break
            page_token = resp.get("nextPageToken", "")
            if not page_token:
                break
        # Sort on epoch floats: avoids naive/aware datetime comparison when a
        # video lacks publishedAt.
        videos.sort(key=lambda v: v.published_at.timestamp()
                    if v.published_at else float("-inf"), reverse=True)
        return videos[:limit] if limit > 0 else videos

    # Alias per the reference's duplicated surface (types.go:50-53).
    def get_videos(self, channel_id: str, from_time: Optional[datetime] = None,
                   to_time: Optional[datetime] = None,
                   limit: int = 50) -> List[YouTubeVideo]:
        return self.get_videos_from_channel(channel_id, from_time, to_time, limit)

    def get_videos_by_ids(self, video_ids: List[str]) -> List[YouTubeVideo]:
        """Batched lookup with stats cache (`youtube_client.go:1077-1112`)."""
        out: List[YouTubeVideo] = []
        missing: List[str] = []
        with self._cache_lock:
            for vid in video_ids:
                cached = self._video_cache.get(vid)
                if cached is not None:
                    out.append(cached)
                else:
                    missing.append(vid)
        for i in range(0, len(missing), VIDEO_BATCH):
            chunk = missing[i:i + VIDEO_BATCH]
            resp = self._call("videos", {
                "part": "snippet,statistics,contentDetails",
                "id": ",".join(chunk)})
            for item in resp.get("items") or []:
                video = _parse_video(item)
                with self._cache_lock:
                    self._video_cache[video.id] = video
                out.append(video)
        return out

    # --- random sampling ---------------------------------------------------
    def get_random_videos(self, from_time: Optional[datetime] = None,
                          to_time: Optional[datetime] = None,
                          limit: int = 10) -> List[YouTubeVideo]:
        """Prefix random sampling (`youtube_client.go:1109-1260`): search for
        "watch?v=<prefix>", keep only IDs whose first 5 chars match the prefix
        case-insensitively with '-' at index 5 (true random hits), then verify
        via batched videos.list."""
        collected: Dict[str, YouTubeVideo] = {}
        seen_prefixes = set()
        for _ in range(MAX_RANDOM_ATTEMPTS):
            if len(collected) >= limit:
                break
            with self._rng_lock:
                query = generate_random_prefix(self._rng)
            prefix = query[len("watch?v="):]
            if prefix in seen_prefixes:
                continue
            seen_prefixes.add(prefix)
            resp = self._call("search", {"part": "id", "q": query,
                                         "type": "video", "maxResults": 50})
            candidate_ids = []
            for item in resp.get("items") or []:
                vid = item.get("id", {}).get("videoId", "") \
                    if isinstance(item.get("id"), dict) else item.get("id", "")
                # Valid random hits: prefix matches (case-insensitive) and
                # '-' at position 5 (`youtube_client.go:894-897,1230`).
                if len(vid) == 11 and vid[:5].lower() == prefix and vid[5] == "-":
                    candidate_ids.append(vid)
            for video in self.get_videos_by_ids(candidate_ids):
                if _in_window(video, from_time, to_time):
                    collected[video.id] = video
        return list(collected.values())[:limit]

    # --- snowball ----------------------------------------------------------
    def get_snowball_videos(self, seed_channel_ids: List[str],
                            from_time: Optional[datetime] = None,
                            to_time: Optional[datetime] = None,
                            limit: int = 50) -> List[YouTubeVideo]:
        """Seed expansion via channel IDs found in video descriptions
        (`youtube_client.go:1547,1856`); only channels with more than
        SNOWBALL_MIN_VIDEOS videos are expanded (`types.go:62`).
        limit <= 0 means unlimited, matching get_videos_from_channel."""
        if limit <= 0:
            limit = 10 ** 9
        queue = list(seed_channel_ids)
        visited = set()
        out: List[YouTubeVideo] = []
        while queue and len(out) < limit:
            channel_id = queue.pop(0)
            if channel_id in visited:
                continue
            visited.add(channel_id)
            try:
                info = self.get_channel_info(channel_id)
            except LookupError:
                continue
            if info.video_count <= SNOWBALL_MIN_VIDEOS and \
                    channel_id not in seed_channel_ids:
                continue
            videos = self.get_videos_from_channel(channel_id, from_time,
                                                  to_time,
                                                  limit - len(out))
            out.extend(videos)
            for v in videos:
                for found in _CHANNEL_ID_RE.findall(v.description):
                    if found not in visited:
                        queue.append(found)
        return out[:limit]


def _in_window(video: YouTubeVideo, from_time: Optional[datetime],
               to_time: Optional[datetime]) -> bool:
    if video.published_at is None:
        return True
    if from_time is not None and video.published_at < from_time:
        return False
    if to_time is not None and video.published_at > to_time:
        return False
    return True


class FakeYouTubeTransport:
    """In-memory Data API backend for tests (the reference mocks at the same
    seam, `client/youtube_client_test.go`)."""

    def __init__(self):
        self.channels: Dict[str, Dict[str, Any]] = {}
        self.videos: Dict[str, Dict[str, Any]] = {}
        self.handles: Dict[str, str] = {}    # "@handle" -> channel id
        self.usernames: Dict[str, str] = {}  # legacy username -> channel id
        self.calls: List[Tuple[str, Dict[str, Any]]] = []
        self.quota_used = 0

    def add_channel(self, channel_id: str, title: str = "", video_count: int = 0,
                    subscriber_count: int = 0, description: str = "",
                    country: str = "", handle: str = "",
                    username: str = "") -> None:
        if handle:
            self.handles[handle] = channel_id
        if username:
            self.usernames[username] = channel_id
        self.channels[channel_id] = {
            "id": channel_id,
            "snippet": {"title": title or channel_id, "description": description,
                        "publishedAt": "2020-01-01T00:00:00Z",
                        "country": country, "thumbnails": {}},
            "statistics": {"subscriberCount": str(subscriber_count),
                           "viewCount": "0", "videoCount": str(video_count)},
        }

    def add_video(self, video_id: str, channel_id: str, title: str = "",
                  description: str = "", published_at: str = "2025-01-01T00:00:00Z",
                  view_count: int = 0, like_count: int = 0,
                  comment_count: int = 0, duration: str = "PT1M",
                  tags: Optional[List[str]] = None) -> None:
        self.videos[video_id] = {
            "id": video_id,
            "snippet": {"channelId": channel_id, "title": title or video_id,
                        "description": description, "publishedAt": published_at,
                        "thumbnails": {"default": {"url": f"https://i.ytimg/{video_id}.jpg"}},
                        "tags": tags or []},
            "statistics": {"viewCount": str(view_count),
                           "likeCount": str(like_count),
                           "commentCount": str(comment_count)},
            "contentDetails": {"duration": duration},
        }
        if channel_id not in self.channels:
            self.add_channel(channel_id)

    def __call__(self, endpoint: str, params: Dict[str, Any]) -> Dict[str, Any]:
        self.calls.append((endpoint, params))
        self.quota_used += 100 if endpoint == "search" else 1
        if endpoint == "channels":
            cid = params.get("id", "")
            if not cid and "forHandle" in params:
                cid = self.handles.get(params["forHandle"], "")
            if not cid and "forUsername" in params:
                cid = self.usernames.get(params["forUsername"], "")
            item = self.channels.get(cid)
            return {"items": [item] if item else []}
        if endpoint == "playlistItems":
            playlist = params.get("playlistId", "")
            channel_id = "UC" + playlist[2:] if playlist.startswith("UU") else playlist
            items = [{"contentDetails": {"videoId": vid}}
                     for vid, v in self.videos.items()
                     if v["snippet"]["channelId"] == channel_id]
            return {"items": items[:int(params.get("maxResults", 50))]}
        if endpoint == "videos":
            ids = params.get("id", "").split(",")
            return {"items": [self.videos[v] for v in ids if v in self.videos]}
        if endpoint == "search":
            q = params.get("q", "")
            prefix = q[len("watch?v="):] if q.startswith("watch?v=") else q
            items = [{"id": {"videoId": vid}} for vid in self.videos
                     if vid[:len(prefix)].lower() == prefix.lower()]
            return {"items": items[:int(params.get("maxResults", 50))]}
        raise ValueError(f"unknown endpoint: {endpoint}")
