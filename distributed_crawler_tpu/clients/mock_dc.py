"""In-tree mock DC server: the test peer for the native transport seam.

Since round 4 the protocol/server core lives in `clients/dc_gateway.py`
(the DEPLOYABLE `dct --mode dc-gateway` process); this module keeps the
test-facing name and defaults.  `MockDcServer` IS a `DcGateway` — tests
exercising the mock exercise the production wire path (TLS, auth ladder,
engine proxying) byte for byte.

Reference parity context: the reference authenticated TDLib against real
Telegram data centers with a 30 s init timeout
(`telegramhelper/client.go:319-377`) and bootstrapped auth codes via
GenCode (`standalone/runner.go:77-192`); the gateway is this build's
server side of that seam.
"""

from __future__ import annotations

from .dc_gateway import (  # noqa: F401  (re-exported test helpers)
    DcGateway,
    make_self_signed_cert,
)


class MockDcServer(DcGateway):
    """Test-configured gateway: one global expected code/password, inline
    seed JSON, ephemeral self-signed TLS.  Kept as a distinct name so test
    intent stays readable; all behavior is `DcGateway`'s."""
