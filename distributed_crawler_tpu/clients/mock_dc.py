"""In-tree mock DC server: the test peer for the native transport seam.

The reference authenticated TDLib against real Telegram data centers with a
30 s init timeout (`telegramhelper/client.go:319-377`) and bootstrapped auth
codes via GenCode (`standalone/runner.go:77-192`).  This server lets the
C++ client exercise the SAME lifecycle — TCP (or TLS) connect, handshake,
TDLib-style auth ladder, then the 16-method surface — over a real socket
without egress:

- speaks the DCT wire protocol v1 (4-byte big-endian length ‖ JSON frame,
  `native/net.h`),
- drives the auth ladder per connection: handshake → WaitTdlibParameters →
  WaitPhoneNumber → WaitCode [→ WaitPassword] → Ready, validating the
  configured code/password,
- once Ready, proxies every request to an embedded OFFLINE native engine
  (`dct_client_execute` on a seed-loaded client), so all 16 methods work
  over the wire with zero duplicated routing logic,
- optional TLS: a self-signed cert is minted at start via the `openssl`
  binary, exercising the client's Chrome-shaped TLS leg end to end.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import ssl
import struct
import subprocess
import tempfile
import threading
from typing import Any, Dict, Optional

from .native import NativeTelegramClient, load_library

logger = logging.getLogger("dct.mockdc")

_HEADER = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024


def send_frame(sock, payload: bytes) -> None:
    sock.sendall(_HEADER.pack(len(payload)) + payload)


def recv_frame(sock) -> Optional[bytes]:
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (n,) = _HEADER.unpack(header)
    if n > MAX_FRAME:
        raise ValueError("oversized frame")
    return _recv_exact(sock, n)


def _recv_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except (ConnectionResetError, OSError):
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def make_self_signed_cert(directory: str, cn: str = "localhost") -> tuple:
    """Mint a throwaway self-signed cert with the system openssl binary
    (no key material is committed to the repo)."""
    cert = os.path.join(directory, "dc.crt")
    key = os.path.join(directory, "dc.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "2", "-subj",
         f"/CN={cn}", "-addext", f"subjectAltName=DNS:{cn},IP:127.0.0.1"],
        check=True, capture_output=True)
    return cert, key


class MockDcServer:
    """Socket server speaking the wire protocol; one thread per connection.

    ``expected_code`` / ``expected_password`` configure the auth ladder
    (password = the 2FA leg).  ``tls=True`` wraps every connection in TLS
    with a freshly minted self-signed cert (clients connect with
    ``tls_insecure``)."""

    def __init__(self, seed_json: str = "", expected_code: str = "13579",
                 expected_password: str = "", tls: bool = False,
                 host: str = "127.0.0.1", port: int = 0,
                 lib_path: Optional[str] = None):
        self.seed_json = seed_json or '{"channels": []}'
        self.expected_code = expected_code
        self.expected_password = expected_password
        self._lib_path = lib_path
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self.host = host
        self._ssl_ctx = None
        self._tmpdir = None
        if tls:
            self._tmpdir = tempfile.TemporaryDirectory(prefix="dct-dc-")
            cert, key = make_self_signed_cert(self._tmpdir.name)
            self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            self._ssl_ctx.load_cert_chain(cert, key)
        self._stop = threading.Event()
        self._threads: list = []
        self._live_conns: list = []
        self._stats_mu = threading.Lock()
        self.connections = 0
        self.auth_successes = 0
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="dct-mockdc-accept")

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "MockDcServer":
        self._accept_thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for conn in self._live_conns:  # kill live sessions, not just accept
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)
        if self._tmpdir is not None:
            self._tmpdir.cleanup()

    # -- internals ---------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return  # socket closed
            self.connections += 1
            self._live_conns.append(conn)
            t = threading.Thread(target=self._serve_conn,
                                 args=(conn, addr), daemon=True,
                                 name=f"dct-mockdc-{addr[1]}")
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket, addr) -> None:
        engine = None
        try:
            if self._ssl_ctx is not None:
                conn = self._ssl_ctx.wrap_socket(conn, server_side=True)
            # 1. Handshake frame first, always.
            first = recv_frame(conn)
            if first is None:
                return
            hello = json.loads(first.decode("utf-8"))
            if hello.get("@type") != "handshake":
                send_frame(conn, self._err(400, "handshake expected"))
                return
            send_frame(conn, json.dumps({
                "@type": "handshake_ack",
                "session_id": f"sess-{addr[1]}",
                "transport_version": 1}).encode("utf-8"))

            # 2. Auth ladder, server-driven via updates.
            state = "waitTdlibParameters"
            self._push_auth(conn, "authorizationStateWaitTdlibParameters")
            while not self._stop.is_set():
                raw = recv_frame(conn)
                if raw is None:
                    return
                req = json.loads(raw.decode("utf-8"))
                rtype = req.get("@type", "")
                if state != "ready":
                    state = self._auth_step(conn, state, rtype, req)
                    if state == "ready":
                        # 3. Ready: spin the offline engine for this
                        # session (per-connection store isolation, like
                        # per-connection TDLib databases).
                        engine = NativeTelegramClient(
                            seed_json=self.seed_json,
                            lib_path=self._lib_path,
                            conn_id=f"dc-{addr[1]}")
                        with self._stats_mu:
                            self.auth_successes += 1
                    continue
                if rtype == "close":
                    self._reply(conn, req, {"@type": "ok"})
                    return
                resp = json.loads(engine.execute_raw(json.dumps(req)))
                send_frame(conn,
                           json.dumps(resp).encode("utf-8"))
        except (ValueError, ssl.SSLError, OSError) as e:
            logger.info("mock dc connection %s dropped: %s", addr, e)
        finally:
            if engine is not None:
                engine.close()
            try:
                conn.close()
            except OSError:
                pass

    def _auth_step(self, conn, state: str, rtype: str,
                   req: Dict[str, Any]) -> str:
        if rtype == "setTdlibParameters" and state == "waitTdlibParameters":
            self._reply(conn, req, {"@type": "ok"})
            self._push_auth(conn, "authorizationStateWaitPhoneNumber")
            return "waitPhoneNumber"
        if rtype == "setAuthenticationPhoneNumber" and \
                state == "waitPhoneNumber":
            if not req.get("phone_number"):
                self._reply(conn, req,
                            self._err_obj(400, "PHONE_NUMBER_INVALID"))
                return state
            self._reply(conn, req, {"@type": "ok"})
            self._push_auth(conn, "authorizationStateWaitCode")
            return "waitCode"
        if rtype == "checkAuthenticationCode" and state == "waitCode":
            if req.get("code") != self.expected_code:
                self._reply(conn, req,
                            self._err_obj(400, "PHONE_CODE_INVALID"))
                return state
            self._reply(conn, req, {"@type": "ok"})
            if self.expected_password:
                self._push_auth(conn, "authorizationStateWaitPassword")
                return "waitPassword"
            self._push_auth(conn, "authorizationStateReady")
            return "ready"
        if rtype == "checkAuthenticationPassword" and \
                state == "waitPassword":
            if req.get("password") != self.expected_password:
                self._reply(conn, req,
                            self._err_obj(400, "PASSWORD_HASH_INVALID"))
                return state
            self._reply(conn, req, {"@type": "ok"})
            self._push_auth(conn, "authorizationStateReady")
            return "ready"
        self._reply(conn, req, self._err_obj(
            401, f"UNAUTHORIZED: {rtype} not valid in state {state}"))
        return state

    def _push_auth(self, conn, state: str) -> None:
        send_frame(conn, json.dumps({
            "@type": "updateAuthorizationState",
            "authorization_state": {"@type": state}}).encode("utf-8"))

    @staticmethod
    def _err_obj(code: int, message: str) -> Dict[str, Any]:
        return {"@type": "error", "code": code, "message": message}

    def _err(self, code: int, message: str) -> bytes:
        return json.dumps(self._err_obj(code, message)).encode("utf-8")

    @staticmethod
    def _reply(conn, req: Dict[str, Any], body: Dict[str, Any]) -> None:
        if "@extra" in req:
            body = dict(body)
            body["@extra"] = req["@extra"]
        send_frame(conn, json.dumps(body).encode("utf-8"))
