"""The Telegram client boundary: typed objects + the 16-method protocol.

Parity with the reference's `crawler.TDLibClient` interface
(`crawler/crawler.go:109-126`).  The reference reached TDLib (C++) through
cgo; this build's equivalents are:

- `native.NativeTelegramClient` — ctypes binding to the in-tree C++ client
  (`native/` directory), the TDLib-class native boundary;
- `sim.SimTelegramClient` — in-process network simulation for tests and
  offline runs.

Python method names are snake_case versions of the reference's; requests are
plain kwargs instead of request structs, returns are the light TL dataclasses
below (only the fields the crawl engine consumes).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Protocol, runtime_checkable


@dataclass
class TLFile:
    """A file handle (local + remote state)."""

    id: int = 0
    remote_id: str = ""
    local_path: str = ""
    size: int = 0
    downloaded: bool = False


@dataclass
class TLMessage:
    """A message.  `content` is a tagged dict: {"@type": "messageText",
    "text": ..., ...} mirroring TDLib's content-type union (12+ types,
    `telegramhelper/tdutils.go:380-720`)."""

    id: int = 0
    chat_id: int = 0
    date: int = 0  # unix seconds
    content: Dict[str, Any] = field(default_factory=dict)
    view_count: int = 0
    forward_count: int = 0
    reply_count: int = 0
    reactions: Dict[str, int] = field(default_factory=dict)
    message_thread_id: int = 0
    reply_to_message_id: int = 0
    sender_id: int = 0
    sender_username: str = ""
    is_channel_post: bool = False


@dataclass
class TLMessages:
    total_count: int = 0
    messages: List[TLMessage] = field(default_factory=list)


@dataclass
class TLChat:
    id: int = 0
    title: str = ""
    type: str = "supergroup"  # supergroup | basic_group | private | secret
    supergroup_id: int = 0
    basic_group_id: int = 0
    photo_remote_id: str = ""


@dataclass
class TLSupergroup:
    id: int = 0
    username: str = ""
    member_count: int = 0
    is_channel: bool = True
    date: int = 0
    is_verified: bool = False


@dataclass
class TLSupergroupFullInfo:
    description: str = ""
    member_count: int = 0
    photo_remote_id: str = ""


@dataclass
class TLBasicGroupFullInfo:
    description: str = ""
    members_count: int = 0


@dataclass
class TLUser:
    id: int = 0
    username: str = ""
    first_name: str = ""
    last_name: str = ""


@dataclass
class TLMessageLink:
    link: str = ""
    is_public: bool = True


@dataclass
class TLMessageThreadInfo:
    chat_id: int = 0
    message_thread_id: int = 0
    reply_count: int = 0


@runtime_checkable
class TelegramClient(Protocol):
    """The 16-method client surface (`crawler/crawler.go:109-126`)."""

    def get_message(self, chat_id: int, message_id: int) -> TLMessage: ...

    def get_message_link(self, chat_id: int, message_id: int) -> TLMessageLink: ...

    def get_message_thread_history(self, chat_id: int, message_id: int,
                                   from_message_id: int = 0,
                                   limit: int = 100) -> TLMessages: ...

    def get_message_thread(self, chat_id: int, message_id: int) -> TLMessageThreadInfo: ...

    def get_remote_file(self, remote_file_id: str) -> TLFile: ...

    def download_file(self, file_id: int) -> TLFile: ...

    def get_chat_history(self, chat_id: int, from_message_id: int = 0,
                         offset: int = 0, limit: int = 100) -> TLMessages: ...

    def search_public_chat(self, username: str) -> TLChat: ...

    def get_chat(self, chat_id: int) -> TLChat: ...

    def get_supergroup(self, supergroup_id: int) -> TLSupergroup: ...

    def get_supergroup_full_info(self, supergroup_id: int) -> TLSupergroupFullInfo: ...

    def close(self) -> None: ...

    def get_me(self) -> TLUser: ...

    def get_basic_group_full_info(self, basic_group_id: int) -> TLBasicGroupFullInfo: ...

    def get_user(self, user_id: int) -> TLUser: ...

    def delete_file(self, file_id: int) -> None: ...
