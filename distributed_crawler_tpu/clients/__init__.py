"""Platform clients: the Telegram client boundary, pools, limiters, validators,
and the YouTube Data client.

Parity with the reference's layer 5 (SURVEY.md §1): `crawler.TDLibClient`
(16 methods, `crawler/crawler.go:109-126`), the per-method rate limiter
(`telegramhelper/rate_limiter.go`), the connection pool
(`telegramhelper/connection_pool.go`), the account-free t.me HTTP validator
(`telegramhelper/channelvalidator.go` + `username_filter.go`), and the YouTube
Data API client (`client/youtube_client.go`).

The real MTProto transport is the C++ native client in `native/` (the
reference's TDLib analog), loaded via ctypes in `clients/native.py`; `sim.py`
is the in-process network simulation used by tests and available as an
explicit backend.
"""

from .errors import FloodWaitError, TelegramError, parse_flood_wait_seconds
from .http_validator import (
    BLOCKED,
    TRANSIENT,
    ChannelValidationResult,
    ValidationHTTPError,
    ValidatorRateLimiter,
    parse_channel_html,
    validate_channel_http,
)
from .dc_gateway import DcGateway, load_accounts
from .native import (
    NativeTelegramClient,
    find_library as find_native_library,
    generate_pcode,
    load_credentials,
    native_client_factory,
)
from .pool import ConnectionPool, PooledConnection
from .rate_limiter import (
    Clock,
    FakeClock,
    RateLimitedTelegramClient,
    SystemClock,
    TokenBucket,
    detect_cache_or_server,
)
from .sim import SimChannel, SimNetwork, SimTelegramClient
from .telegram import (
    TelegramClient,
    TLChat,
    TLFile,
    TLMessage,
    TLMessageLink,
    TLMessages,
    TLMessageThreadInfo,
    TLSupergroup,
    TLSupergroupFullInfo,
    TLUser,
)
from .username_filter import UsernameFilterResult, filter_username
from .youtube import (
    FakeYouTubeTransport,
    YouTubeClient,
    YouTubeDataClient,
    generate_random_prefix,
)

__all__ = [
    "NativeTelegramClient", "native_client_factory", "find_native_library",
    "generate_pcode", "load_credentials",
    "DcGateway", "load_accounts",
    "TelegramClient", "TelegramError", "FloodWaitError",
    "parse_flood_wait_seconds",
    "TLMessage", "TLMessages", "TLChat", "TLSupergroup",
    "TLSupergroupFullInfo", "TLUser", "TLFile", "TLMessageLink",
    "TLMessageThreadInfo",
    "TokenBucket", "RateLimitedTelegramClient", "detect_cache_or_server",
    "Clock", "SystemClock", "FakeClock",
    "ConnectionPool", "PooledConnection",
    "SimTelegramClient", "SimNetwork", "SimChannel",
    "filter_username", "UsernameFilterResult",
    "validate_channel_http", "parse_channel_html", "ChannelValidationResult",
    "ValidationHTTPError", "ValidatorRateLimiter", "TRANSIENT", "BLOCKED",
    "YouTubeClient", "YouTubeDataClient", "FakeYouTubeTransport",
    "generate_random_prefix",
]
