"""MTProto 2.0 wire protocol — the reference's TDLib transport, in-tree.

The reference links TDLib, whose transport to Telegram's data centers is
MTProto: an auth-key DH handshake in plaintext TL messages, then
AES-256-IGE-encrypted messages keyed per-message from the shared
``auth_key`` (reference boundary: `Dockerfile.tdlib:19-36`,
`telegramhelper/client.go:319-377` drives the ladder over it).  This
module implements the protocol faithfully at the transport and crypto
layers so the framework's native client can speak real MTProto to the
in-tree DC gateway:

- **intermediate transport framing** (``0xeeeeeeee`` init, 4-byte LE
  length prefix);
- **the creating-an-auth-key handshake** with the published TL schema
  constructors (req_pq_multi/resPQ/req_DH_params/server_DH_params_ok/
  set_client_DH_params/dh_gen_ok), RSA(SHA1+data+pad) for
  p_q_inner_data, SHA1-derived tmp AES-IGE keys for the DH answer, and
  a 2048-bit DH over the RFC 3526 MODP group;
- **MTProto 2.0 message encryption**: msg_key = middle 16 bytes of
  SHA256(auth_key[88+x:120+x] ‖ padded_plaintext), SHA256-based key/iv
  derivation (x=0 client→server, x=8 server→client), AES-256-IGE.

The payload riding INSIDE the encrypted envelope is a TL API constructor
layer (`tl_api.py` / `native/tl_api.h`): typed TL functions for the hot
crawl RPCs, a schema-declared DataJSON-style fallback for the long tail,
and responses in the published ``rpc_result#f35c6d01`` envelope
correlated by MTProto msg_id.  The schema covers the framework's
16-method surface rather than Telegram's ~3000 TDLib constructors —
those serve TDLib's client database, which this framework replaces with
the gateway-side store.  The transport, handshake, and per-message
crypto are the MTProto 2.0 spec.

Remaining honest delta: the wire terminates at the in-tree DC gateway
(with its own long-lived RSA keys, DC table, and PHONE_MIGRATE_X
redirects), not at Telegram's production DCs.

Both sides live here (client for tests/parity, server for the gateway);
`native/mtproto.h` is the C++ client twin — the cross-implementation
handshake in tests/test_mtproto.py is the parity proof.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import secrets
import socket
import struct
import time
from dataclasses import dataclass
from typing import Optional, Tuple

try:
    from cryptography.hazmat.primitives.ciphers import (
        Cipher,
        algorithms,
        modes,
    )
    _CRYPTO_IMPORT_ERROR = None
except ImportError as _exc:  # gated dep: TL parsing stays importable —
    # only the AES-IGE paths (ige_encrypt/ige_decrypt, i.e. the actual
    # MTProto transport) need the cryptography package.
    Cipher = algorithms = modes = None  # type: ignore[assignment]
    _CRYPTO_IMPORT_ERROR = _exc

# -- TL constructor ids (public MTProto schema) -----------------------------
REQ_PQ_MULTI = 0xBE7E8EF1
RES_PQ = 0x05162463
P_Q_INNER_DATA = 0x83C95AEC
REQ_DH_PARAMS = 0xD712E4BE
SERVER_DH_PARAMS_OK = 0xD0E8075C
SERVER_DH_INNER_DATA = 0xB5890DBA
CLIENT_DH_INNER_DATA = 0x6643B654
SET_CLIENT_DH_PARAMS = 0xF5045F1F
DH_GEN_OK = 0x3BCBF734
VECTOR = 0x1CB5C415

# RFC 3526 MODP-2048 safe prime (the DH group the gateway serves; Telegram
# production uses its own 2048-bit safe prime of identical shape).
DH_PRIME = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A637ED6B0BFF5CB6F406B7ED"
    "EE386BFB5A899FA5AE9F24117C4B1FE649286651ECE45B3DC2007CB8A163BF05"
    "98DA48361C55D39A69163FA8FD24CF5F83655D23DCA3AD961C62F356208552BB"
    "9ED529077096966D670C354E4ABC9804F1746C08CA18217C32905E462E36CE3B"
    "E39E772C180E86039B2783A2EC07A28FB5C55DF06F4C52C9DE2BCBF695581718"
    "3995497CEA956AE515D2261898FA051015728E5A8AACAA68FFFFFFFFFFFFFFFF",
    16)
DH_G = 2

INTERMEDIATE_INIT = b"\xee\xee\xee\xee"
MAX_PACKET = 64 * 1024 * 1024


# -- small helpers ----------------------------------------------------------
def sha1(b: bytes) -> bytes:
    return hashlib.sha1(b).digest()


def sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


def ige_encrypt(key: bytes, iv: bytes, data: bytes) -> bytes:
    """AES-256-IGE (key 32B; iv 32B = iv1‖iv2; len(data) % 16 == 0)."""
    if Cipher is None:
        raise ImportError(
            "MTProto AES-IGE needs the 'cryptography' package"
        ) from _CRYPTO_IMPORT_ERROR
    if len(data) % 16:
        raise ValueError("IGE needs 16-byte-aligned input")
    enc = Cipher(algorithms.AES(key), modes.ECB()).encryptor()
    iv1, iv2 = iv[:16], iv[16:32]
    out = bytearray()
    for i in range(0, len(data), 16):
        blk = data[i:i + 16]
        c = xor(enc.update(xor(blk, iv1)), iv2)
        out += c
        iv1, iv2 = c, blk
    return bytes(out)


def ige_decrypt(key: bytes, iv: bytes, data: bytes) -> bytes:
    if Cipher is None:
        raise ImportError(
            "MTProto AES-IGE needs the 'cryptography' package"
        ) from _CRYPTO_IMPORT_ERROR
    if len(data) % 16:
        raise ValueError("IGE needs 16-byte-aligned input")
    dec = Cipher(algorithms.AES(key), modes.ECB()).decryptor()
    iv1, iv2 = iv[:16], iv[16:32]
    out = bytearray()
    for i in range(0, len(data), 16):
        blk = data[i:i + 16]
        p = xor(dec.update(xor(blk, iv2)), iv1)
        out += p
        iv1, iv2 = blk, p
    return bytes(out)


def tl_bytes(b: bytes) -> bytes:
    """TL `bytes`/`string` serialization (1- or 4-byte length, pad to 4).

    The TL long form carries a 3-byte length — payloads must stay under
    2**24 (the format's own limit; real MTProto moves bigger blobs via
    chunked file methods).  Raise loudly rather than let int.to_bytes
    OverflowError (or a silent wrap) corrupt the frame; >=16 MiB
    payloads belong on the DCT-v1 wire, whose 4-byte frames carry 64 MiB
    (documented wire-choice delta)."""
    if len(b) >= 1 << 24:
        raise ValueError(
            f"payload of {len(b)} bytes exceeds the TL bytes limit "
            f"(2^24-1); use the dct wire for >=16 MiB frames")
    if len(b) < 254:
        out = bytes([len(b)]) + b
    else:
        out = b"\xfe" + len(b).to_bytes(3, "little") + b
    pad = (-len(out)) % 4
    return out + b"\x00" * pad


class TlReader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def _take(self, n: int) -> bytes:
        if self.off + n > len(self.data):
            raise ValueError("TL underrun")
        b = self.data[self.off:self.off + n]
        self.off += n
        return b

    def uint32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def int64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def raw(self, n: int) -> bytes:
        return self._take(n)

    def tl_bytes(self) -> bytes:
        n = self._take(1)[0]
        if n == 254:
            n = int.from_bytes(self._take(3), "little")
            b = self._take(n)
            self._take((-n) % 4)
        else:
            b = self._take(n)
            self._take((-(n + 1)) % 4)
        return b


def u32(v: int) -> bytes:
    return struct.pack("<I", v & 0xFFFFFFFF)


def i32(v: int) -> bytes:
    return struct.pack("<i", v)


def i64(v: int) -> bytes:
    return struct.pack("<q", v)


def int_to_bytes(v: int) -> bytes:
    return v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")


# -- RSA (the gateway's "server public key") --------------------------------
@dataclass
class RsaKey:
    """Raw-RSA key in the MTProto style.  The PUBLIC half {n, e} is what
    clients load (Telegram bakes its DC keys into clients; the gateway
    writes ``<address_file>.pubkey.json`` for the same role)."""

    n: int
    e: int
    d: Optional[int] = None  # server side only

    @property
    def fingerprint(self) -> int:
        """Lower 8 bytes of SHA1 over the TL-serialized public key — the
        exact fingerprint rule of the MTProto spec."""
        data = tl_bytes(int_to_bytes(self.n)) + tl_bytes(int_to_bytes(self.e))
        return int.from_bytes(sha1(data)[-8:], "little", signed=True)

    def encrypt_with_hash(self, data: bytes) -> bytes:
        """data_with_hash = SHA1(data) ‖ data ‖ random pad to 255; raw RSA."""
        if len(data) > 255 - 20:
            raise ValueError("RSA payload too large")
        dwh = sha1(data) + data
        dwh += secrets.token_bytes(255 - len(dwh))
        c = pow(int.from_bytes(dwh, "big"), self.e, self.n)
        return c.to_bytes(256, "big")

    def decrypt_with_hash(self, cipher: bytes) -> Tuple[bytes, bytes]:
        """Raw-RSA decrypt → (sha1_digest, payload_with_padding).

        The caller TL-parses the payload (which knows its true length)
        and THEN verifies the SHA1 prefix — cheaper than testing every
        feasible split here (see the server handshake)."""
        assert self.d is not None, "no private exponent"
        m = pow(int.from_bytes(cipher, "big"), self.d, self.n)
        try:
            dwh = m.to_bytes(255, "big")
        except OverflowError:
            # Adversarial/garbage ciphertext decrypts to ~n-sized values;
            # surface it as the protocol error the session loop handles.
            raise ValueError("RSA decryption out of range") from None
        return dwh[:20], dwh[20:]


def generate_rsa_key(bits: int = 2048) -> RsaKey:
    from cryptography.hazmat.primitives.asymmetric import rsa

    k = rsa.generate_private_key(public_exponent=65537, key_size=bits)
    pub = k.public_key().public_numbers()
    return RsaKey(n=pub.n, e=pub.e, d=k.private_numbers().d)


# -- pq ---------------------------------------------------------------------
def _small_prime(bits: int = 31) -> int:
    """Random prime around 2^bits (pq must fit 63 bits as a TL bytes)."""
    while True:
        c = secrets.randbits(bits) | (1 << (bits - 1)) | 1
        if _is_prime(c):
            return c


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    for p in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        if n % p == 0:
            return n == p
    d, r = n - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for a in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def factor_pq(pq: int) -> Tuple[int, int]:
    """Pollard's rho — the client-side factorization step (also used by
    tests to cross-check the C++ implementation)."""
    if pq % 2 == 0:
        return 2, pq // 2
    import math
    import random

    rnd = random.Random(0xDC7)
    while True:
        x = rnd.randrange(2, pq)
        y, c, d = x, rnd.randrange(1, pq), 1
        while d == 1:
            x = (x * x + c) % pq
            y = (y * y + c) % pq
            y = (y * y + c) % pq
            d = math.gcd(abs(x - y), pq)
        if d != pq:
            p, q = sorted((d, pq // d))
            return p, q


# -- MTProto 2.0 message crypto --------------------------------------------
def kdf(auth_key: bytes, msg_key: bytes, to_server: bool) -> Tuple[bytes,
                                                                   bytes]:
    """MTProto 2.0 key derivation (x=0 client→server, x=8 server→client)."""
    x = 0 if to_server else 8
    a = sha256(msg_key + auth_key[x:x + 36])
    b = sha256(auth_key[40 + x:76 + x] + msg_key)
    aes_key = a[0:8] + b[8:24] + a[24:32]
    aes_iv = b[0:8] + a[8:24] + b[24:32]
    return aes_key, aes_iv


def compute_msg_key(auth_key: bytes, padded_plain: bytes,
                    to_server: bool) -> bytes:
    x = 0 if to_server else 8
    return sha256(auth_key[88 + x:120 + x] + padded_plain)[8:24]


@dataclass
class Session:
    """One side of an established MTProto session: encrypt/decrypt the
    framework's payloads as MTProto 2.0 encrypted messages."""

    auth_key: bytes
    server_salt: bytes
    session_id: bytes
    is_client: bool
    seq: int = 0
    _last_msg_id: int = 0
    _peer_last_msg_id: int = 0
    # Correlation handles for the TL API layer (tl_api.py): the msg_id the
    # last encrypt() assigned / the last decrypt() validated — rpc_result's
    # req_msg_id, exactly real MTProto's request/response correlation.
    last_sent_msg_id: int = 0
    last_recv_msg_id: int = 0

    @property
    def auth_key_id(self) -> bytes:
        return sha1(self.auth_key)[12:20]

    def _next_msg_id(self) -> int:
        # unixtime<<32, low 2 bits 0 for client originals, 3 for server
        # originals/pushes (per spec); strictly increasing.
        mid = (int(time.time()) << 32) | secrets.randbits(22) << 2
        mid |= 0 if self.is_client else 3
        if mid <= self._last_msg_id:
            mid = self._last_msg_id + 4
        self._last_msg_id = mid
        return mid

    def encrypt(self, payload: bytes) -> bytes:
        # seq_no = 2*count_of_content_messages_before + 1 (spec): the FIRST
        # content-related message carries 1, so read seq before bumping it.
        seq_no = self.seq * 2 + 1
        self.seq += 1
        self.last_sent_msg_id = self._next_msg_id()
        inner = (self.server_salt + self.session_id +
                 i64(self.last_sent_msg_id) + u32(seq_no) +
                 u32(len(payload)) + payload)
        # Padding: ≥12 random bytes, total length % 16 == 0 (spec).
        inner += secrets.token_bytes(12 + (-(len(inner) + 12)) % 16)
        to_server = self.is_client
        msg_key = compute_msg_key(self.auth_key, inner, to_server)
        key, iv = kdf(self.auth_key, msg_key, to_server)
        return self.auth_key_id + msg_key + ige_encrypt(key, iv, inner)

    def decrypt(self, packet: bytes) -> bytes:
        if len(packet) < 24 + 32:
            raise ValueError("short encrypted message")
        if packet[:8] != self.auth_key_id:
            raise ValueError("unknown auth_key_id")
        msg_key = packet[8:24]
        to_server = not self.is_client  # we decrypt what the peer sent
        key, iv = kdf(self.auth_key, msg_key, to_server)
        inner = ige_decrypt(key, iv, packet[24:])
        # msg_key check BEFORE trusting any field (2.0 requires the check
        # over the padded plaintext; a mismatch is a forged/corrupt frame).
        # compare_digest: a forged frame's rejection time must not leak how
        # many MAC bytes matched.
        if not hmac.compare_digest(
                compute_msg_key(self.auth_key, inner, to_server), msg_key):
            raise ValueError("msg_key mismatch")
        r = TlReader(inner)
        r.raw(8)  # salt
        sid = r.raw(8)
        if not self.is_client and not self.session_id:
            # The client mints the session id (per spec); the server
            # adopts it from the first VALIDATED message.
            self.session_id = sid
        elif sid != self.session_id:
            raise ValueError("session_id mismatch")
        msg_id = r.int64()
        # Replay protection (spec rule): peer msg_ids must be strictly
        # increasing within a session — a recorded encrypted request
        # replayed verbatim fails here instead of re-executing.
        if msg_id <= self._peer_last_msg_id:
            raise ValueError("msg_id not increasing (replay?)")
        self._peer_last_msg_id = msg_id
        self.last_recv_msg_id = msg_id
        r.uint32()  # seq_no
        n = r.uint32()
        if n > len(inner) - 32:
            raise ValueError("bad inner length")
        return r.raw(n)


# -- intermediate transport -------------------------------------------------
class Transport:
    """MTProto intermediate framing over a socket (0xeeeeeeee init from
    the client, then 4-byte LE length-prefixed packets)."""

    def __init__(self, sock: socket.socket, is_server: bool):
        self.sock = sock
        if is_server:
            init = self._recv_exact(4)
            if init != INTERMEDIATE_INIT:
                raise ValueError("not an intermediate-transport client")
        else:
            sock.sendall(INTERMEDIATE_INIT)

    def send(self, payload: bytes) -> None:
        self.sock.sendall(struct.pack("<I", len(payload)) + payload)

    def recv(self) -> bytes:
        n = struct.unpack("<I", self._recv_exact(4))[0]
        if n > MAX_PACKET:
            raise ValueError("oversized packet")
        return self._recv_exact(n)

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk
        return buf


def plain_message(body: bytes, msg_id: int) -> bytes:
    return b"\x00" * 8 + i64(msg_id) + u32(len(body)) + body


def parse_plain(packet: bytes) -> bytes:
    r = TlReader(packet)
    if r.int64() != 0:
        raise ValueError("expected plain message (auth_key_id=0)")
    r.int64()  # msg_id
    n = r.uint32()
    return r.raw(n)


# -- handshake: server side -------------------------------------------------
@dataclass
class ServerHandshake:
    """Drives the creating-an-auth-key exchange from the gateway side."""

    rsa: RsaKey
    dh_prime: int = DH_PRIME
    g: int = DH_G
    nonce: bytes = b""
    server_nonce: bytes = b""
    new_nonce: bytes = b""
    _p: int = 0
    _q: int = 0
    _a: int = 0
    auth_key: bytes = b""
    server_salt: bytes = b""

    def handle(self, packet: bytes) -> Tuple[Optional[bytes], bool]:
        """Feed one plain packet; returns (reply, done)."""
        body = parse_plain(packet)
        r = TlReader(body)
        ctor = r.uint32()
        if ctor == REQ_PQ_MULTI:
            return self._on_req_pq(r), False
        if ctor == REQ_DH_PARAMS:
            return self._on_req_dh(r), False
        if ctor == SET_CLIENT_DH_PARAMS:
            return self._on_set_dh(r), True
        raise ValueError(f"unexpected handshake ctor {ctor:#x}")

    def _reply(self, body: bytes) -> bytes:
        # Server handshake replies carry msg_id = unixtime<<32 | 1.
        return plain_message(body, (int(time.time()) << 32) | 1)

    def _on_req_pq(self, r: TlReader) -> bytes:
        self.nonce = r.raw(16)
        self.server_nonce = secrets.token_bytes(16)
        self._p, self._q = sorted((_small_prime(), _small_prime()))
        pq = self._p * self._q
        body = (u32(RES_PQ) + self.nonce + self.server_nonce +
                tl_bytes(int_to_bytes(pq)) + u32(VECTOR) + u32(1) +
                i64(self.rsa.fingerprint))
        return self._reply(body)

    def _on_req_dh(self, r: TlReader) -> bytes:
        nonce = r.raw(16)
        server_nonce = r.raw(16)
        if nonce != self.nonce or server_nonce != self.server_nonce:
            raise ValueError("nonce mismatch in req_DH_params")
        p = int.from_bytes(r.tl_bytes(), "big")
        q = int.from_bytes(r.tl_bytes(), "big")
        if (p, q) != (self._p, self._q):
            raise ValueError("wrong factorization")
        fp = r.int64()
        if fp != self.rsa.fingerprint:
            raise ValueError("unknown RSA fingerprint")
        encrypted = r.tl_bytes()
        digest, rest = self.rsa.decrypt_with_hash(encrypted)
        ir = TlReader(rest)
        if ir.uint32() != P_Q_INNER_DATA:
            raise ValueError("bad p_q_inner_data")
        inner_pq = ir.tl_bytes()
        ir.tl_bytes()  # p
        ir.tl_bytes()  # q
        if ir.raw(16) != self.nonce:
            raise ValueError("inner nonce mismatch")
        if ir.raw(16) != self.server_nonce:
            raise ValueError("inner server_nonce mismatch")
        self.new_nonce = ir.raw(32)
        if sha1(rest[:ir.off]) != digest:
            raise ValueError("inner data SHA1 mismatch")
        if int.from_bytes(inner_pq, "big") != self._p * self._q:
            raise ValueError("inner pq mismatch")
        # DH answer, encrypted with the SHA1-derived tmp key/iv.
        self._a = secrets.randbits(2048) % self.dh_prime
        g_a = pow(self.g, self._a, self.dh_prime)
        answer = (u32(SERVER_DH_INNER_DATA) + self.nonce +
                  self.server_nonce + i32(self.g) +
                  tl_bytes(self.dh_prime.to_bytes(256, "big")) +
                  tl_bytes(int_to_bytes(g_a)) + i32(int(time.time())))
        key, iv = dh_tmp_key_iv(self.new_nonce, self.server_nonce)
        awh = sha1(answer) + answer
        awh += secrets.token_bytes((-len(awh)) % 16)
        body = (u32(SERVER_DH_PARAMS_OK) + self.nonce + self.server_nonce +
                tl_bytes(ige_encrypt(key, iv, awh)))
        return self._reply(body)

    def _on_set_dh(self, r: TlReader) -> bytes:
        nonce = r.raw(16)
        server_nonce = r.raw(16)
        if nonce != self.nonce or server_nonce != self.server_nonce:
            raise ValueError("nonce mismatch in set_client_DH_params")
        encrypted = r.tl_bytes()
        key, iv = dh_tmp_key_iv(self.new_nonce, self.server_nonce)
        plain = ige_decrypt(key, iv, encrypted)
        digest, inner = plain[:20], plain[20:]
        ir = TlReader(inner)
        if ir.uint32() != CLIENT_DH_INNER_DATA:
            raise ValueError("bad client_DH_inner_data")
        if ir.raw(16) != self.nonce or ir.raw(16) != self.server_nonce:
            raise ValueError("client_DH nonce mismatch")
        ir.int64()  # retry_id
        g_b = int.from_bytes(ir.tl_bytes(), "big")
        if sha1(inner[:ir.off]) != digest:
            raise ValueError("client_DH SHA1 mismatch")
        if not 1 < g_b < self.dh_prime - 1:
            raise ValueError("g_b out of range")
        auth_key_int = pow(g_b, self._a, self.dh_prime)
        self.auth_key = auth_key_int.to_bytes(256, "big")
        self.server_salt = xor(self.new_nonce[:8], self.server_nonce[:8])
        aux = sha1(self.auth_key)[:8]
        nnh1 = sha1(self.new_nonce + b"\x01" + aux)[-16:]
        body = (u32(DH_GEN_OK) + self.nonce + self.server_nonce + nnh1)
        return self._reply(body)


def dh_tmp_key_iv(new_nonce: bytes, server_nonce: bytes) -> Tuple[bytes,
                                                                  bytes]:
    """SHA1-derived tmp AES key/iv protecting the DH answer (spec rule)."""
    k = sha1(new_nonce + server_nonce) + sha1(server_nonce + new_nonce)[:12]
    iv = (sha1(server_nonce + new_nonce)[12:20] +
          sha1(new_nonce + new_nonce) + new_nonce[:4])
    return k, iv


# -- handshake: client side (tests / parity with native/mtproto.h) ----------
def client_handshake(transport: Transport, pub) -> Session:
    """``pub`` is one RsaKey or a keyring (sequence of RsaKey): real
    Telegram clients ship several pinned DC keys and select whichever
    fingerprint the server offers in resPQ — same rule here."""
    pubs = [pub] if isinstance(pub, RsaKey) else list(pub)
    if not pubs:
        raise ValueError("empty RSA keyring")
    nonce = secrets.token_bytes(16)
    transport.send(plain_message(u32(REQ_PQ_MULTI) + nonce,
                                 _client_msg_id()))
    r = TlReader(parse_plain(transport.recv()))
    if r.uint32() != RES_PQ:
        raise ValueError("expected resPQ")
    if r.raw(16) != nonce:
        raise ValueError("resPQ nonce mismatch")
    server_nonce = r.raw(16)
    pq = int.from_bytes(r.tl_bytes(), "big")
    if r.uint32() != VECTOR:
        raise ValueError("expected fingerprint vector")
    fps = [r.int64() for _ in range(r.uint32())]
    pub = next((k for k in pubs if k.fingerprint in fps), None)
    if pub is None:
        raise ValueError("server offered no known RSA fingerprint")
    p, q = factor_pq(pq)
    new_nonce = secrets.token_bytes(32)
    inner = (u32(P_Q_INNER_DATA) + tl_bytes(int_to_bytes(pq)) +
             tl_bytes(int_to_bytes(p)) + tl_bytes(int_to_bytes(q)) +
             nonce + server_nonce + new_nonce)
    req = (u32(REQ_DH_PARAMS) + nonce + server_nonce +
           tl_bytes(int_to_bytes(p)) + tl_bytes(int_to_bytes(q)) +
           i64(pub.fingerprint) + tl_bytes(pub.encrypt_with_hash(inner)))
    transport.send(plain_message(req, _client_msg_id()))
    r = TlReader(parse_plain(transport.recv()))
    if r.uint32() != SERVER_DH_PARAMS_OK:
        raise ValueError("expected server_DH_params_ok")
    if r.raw(16) != nonce or r.raw(16) != server_nonce:
        raise ValueError("DH params nonce mismatch")
    key, iv = dh_tmp_key_iv(new_nonce, server_nonce)
    awh = ige_decrypt(key, iv, r.tl_bytes())
    digest, answer = awh[:20], awh[20:]
    ar = TlReader(answer)
    if ar.uint32() != SERVER_DH_INNER_DATA:
        raise ValueError("bad server_DH_inner_data")
    if ar.raw(16) != nonce or ar.raw(16) != server_nonce:
        raise ValueError("server_DH nonce mismatch")
    g = struct.unpack("<i", ar.raw(4))[0]
    dh_prime = int.from_bytes(ar.tl_bytes(), "big")
    g_a = int.from_bytes(ar.tl_bytes(), "big")
    ar.raw(4)  # server_time
    if not hmac.compare_digest(sha1(answer[:ar.off]), digest):
        raise ValueError("server_DH SHA1 mismatch")
    # The spec mandates verifying dh_prime is a known safe prime (primality
    # checks are too slow to run per-handshake, so production clients pin a
    # cached set).  We pin the one group the gateway serves — RFC 3526
    # MODP-2048 — which also subsumes the 2048-bit length check.
    if dh_prime != DH_PRIME:
        raise ValueError("dh_prime is not the pinned RFC 3526 group")
    if not 1 < g_a < dh_prime - 1:
        raise ValueError("bad DH group")
    b = secrets.randbits(2048) % dh_prime
    g_b = pow(g, b, dh_prime)
    auth_key_int = pow(g_a, b, dh_prime)
    auth_key = auth_key_int.to_bytes(256, "big")
    inner = (u32(CLIENT_DH_INNER_DATA) + nonce + server_nonce + i64(0) +
             tl_bytes(int_to_bytes(g_b)))
    iwh = sha1(inner) + inner
    iwh += secrets.token_bytes((-len(iwh)) % 16)
    transport.send(plain_message(
        u32(SET_CLIENT_DH_PARAMS) + nonce + server_nonce +
        tl_bytes(ige_encrypt(key, iv, iwh)), _client_msg_id()))
    r = TlReader(parse_plain(transport.recv()))
    if r.uint32() != DH_GEN_OK:
        raise ValueError("expected dh_gen_ok")
    if r.raw(16) != nonce or r.raw(16) != server_nonce:
        raise ValueError("dh_gen nonce mismatch")
    aux = sha1(auth_key)[:8]
    if r.raw(16) != sha1(new_nonce + b"\x01" + aux)[-16:]:
        raise ValueError("new_nonce_hash1 mismatch")
    return Session(auth_key=auth_key,
                   server_salt=xor(new_nonce[:8], server_nonce[:8]),
                   session_id=secrets.token_bytes(8), is_client=True)


def _client_msg_id() -> int:
    return (int(time.time()) << 32) | (secrets.randbits(20) << 2)


# -- server session over a socket ------------------------------------------
class MtprotoServerSession:
    """Gateway-side wire session: intermediate transport + server handshake,
    then encrypted payload exchange with the same recv()/send() shape the
    DCT-v1 session loop uses."""

    def __init__(self, sock: socket.socket, rsa: RsaKey):
        self.transport = Transport(sock, is_server=True)
        hs = ServerHandshake(rsa=rsa)
        done = False
        while not done:
            reply, done = hs.handle(self.transport.recv())
            if reply:
                self.transport.send(reply)
        self.session = Session(auth_key=hs.auth_key,
                               server_salt=hs.server_salt,
                               session_id=b"", is_client=False)

    def recv(self) -> Optional[bytes]:
        """One decrypted raw TL payload (a tl_api constructor frame);
        ``session.last_recv_msg_id`` then identifies it for rpc_result
        correlation."""
        try:
            packet = self.transport.recv()
        except TimeoutError:
            raise  # the session loop's auth deadline relies on this
        except ConnectionError:
            return None
        # Session.decrypt adopts the client's session_id from the first
        # validated message (the client mints it, per spec).
        return self.session.decrypt(packet)

    def send(self, payload: bytes) -> None:
        self.transport.send(self.session.encrypt(payload))


def save_pubkey(path: str, key: RsaKey) -> None:
    import json

    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"n": hex(key.n), "e": key.e,
                   "fingerprint": key.fingerprint}, f)
    os.replace(tmp, path)


def load_keyring(path: str) -> list:
    """Load one-or-many pinned server keys: accepts the single-key
    `save_pubkey` format, a bare list, or ``{"keys": [...]}`` — the
    client-side analog of the several long-lived DC public keys a real
    Telegram client ships."""
    import json

    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict) and "keys" in data:
        entries = data["keys"]
    elif isinstance(data, list):
        entries = data
    else:
        entries = [data]
    keys = [RsaKey(n=int(d["n"], 16), e=int(d["e"])) for d in entries]
    if not keys:
        raise ValueError(f"no keys in keyring {path}")
    return keys


def load_pubkey(path: str) -> RsaKey:
    import json

    with open(path, "r", encoding="utf-8") as f:
        d = json.load(f)
    return RsaKey(n=int(d["n"], 16), e=int(d["e"]))
