"""TL API constructor layer for the MTProto wire.

Closes the last fidelity delta vs the reference's TDLib transport
(VERDICT r04 missing #3): the payload riding inside the MTProto 2.0
encrypted envelope is no longer the framework's JSON wrapped in one TL
``bytes`` value — it is real TL: every frame is a TL constructor from the
schema below, serialized with the standard TL binary conventions
(little-endian int/long, TL-padded byte strings, ``Vector``/``Bool``
published constructor ids), and responses ride the published
``rpc_result#f35c6d01 req_msg_id:long result:Object`` envelope correlated
by the MTProto message id — the same correlation real Telegram uses
(TDLib's ``@extra`` is client-local, exactly as here).

Schema design notes:
- Constructor ids are CRC32 of the canonical declaration line — the TL
  standard's id rule.  `native/tl_api.h` embeds the identical lines, so
  both sides derive identical ids by construction.
- Extensible sub-objects (message content, reactions) ride a
  ``dct.dataJSON`` field — the design Telegram's own schema uses for
  extensible payloads (``json_data#7d748d04 data:string = DataJSON``).
- ``dct.rawRequest``/``dct.rawResult`` are schema-declared fallbacks for
  the long tail (auth ladder, close, deletes): still TL constructors on
  the wire, carrying one DataJSON-style string.
- Server pushes (auth-state updates) are ``dct.update`` frames with no
  rpc_result wrapper — the shape of Telegram's unsolicited updates.

Reference boundary: `Dockerfile.tdlib:19-36` (the reference links TDLib,
whose ~3000 generated constructors serve its client database; this
framework's store lives gateway-side, so the schema covers the 16-method
crawl surface + the raw fallback).
"""

from __future__ import annotations

import json
import struct
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

# The TL binary primitives are mtproto_wire's: same framing, same 2^24
# long-form guard, bounds-checked reads that raise ValueError (the class
# the gateway session loop catches) on truncated frames.
from .mtproto_wire import TlReader, i32, i64, tl_bytes, u32

# Published TL constructor ids (the real MTProto/TL constants).
RPC_RESULT = 0xF35C6D01
BOOL_TRUE = 0x997275B5
BOOL_FALSE = 0xBC799737
VECTOR = 0x1CB5C415

# Canonical schema — CRC32 of each line IS the constructor id (TL rule).
# native/tl_api.h embeds these exact strings; edits must change both.
SCHEMA_TYPES = [
    "dct.error code:int message:string = dct.Object",
    "dct.ok = dct.Object",
    "dct.chat id:long title:string type:string supergroup_id:long"
    " basic_group_id:long photo_remote_id:string = dct.Object",
    "dct.message id:long chat_id:long date:long view_count:long"
    " forward_count:long reply_count:long message_thread_id:long"
    " reply_to_message_id:long sender_id:long sender_username:string"
    " is_channel_post:Bool content:DataJSON reactions:DataJSON"
    " = dct.Object",
    "dct.messages total_count:long messages:Vector<dct.message>"
    " = dct.Object",
    "dct.messageLink link:string is_public:Bool = dct.Object",
    "dct.messageThreadInfo chat_id:long message_thread_id:long"
    " reply_count:long = dct.Object",
    "dct.supergroup id:long username:string member_count:long"
    " is_channel:Bool date:long is_verified:Bool = dct.Object",
    "dct.supergroupFullInfo description:string member_count:long"
    " photo_remote_id:string = dct.Object",
    "dct.basicGroupFullInfo description:string members_count:long"
    " = dct.Object",
    "dct.file id:long remote_id:string local_path:string size:long"
    " downloaded:Bool = dct.Object",
    "dct.rawResult data:string = dct.Object",
    "dct.update data:string = dct.Update",
]

SCHEMA_FUNCTIONS = [
    "dct.searchPublicChat username:string = dct.Object",
    "dct.getChat chat_id:long = dct.Object",
    "dct.getChatHistory chat_id:long from_message_id:long offset:int"
    " limit:int = dct.Object",
    "dct.getMessage chat_id:long message_id:long = dct.Object",
    "dct.getMessageLink chat_id:long message_id:long = dct.Object",
    "dct.getMessageThread chat_id:long message_id:long = dct.Object",
    "dct.getMessageThreadHistory chat_id:long message_id:long"
    " from_message_id:long limit:int = dct.Object",
    "dct.getSupergroup supergroup_id:long = dct.Object",
    "dct.getSupergroupFullInfo supergroup_id:long = dct.Object",
    "dct.getBasicGroupFullInfo basic_group_id:long = dct.Object",
    "dct.getRemoteFile remote_file_id:string = dct.Object",
    "dct.downloadFile file_id:long = dct.Object",
    "dct.rawRequest data:string = dct.Object",
]


class Constructor:
    __slots__ = ("name", "json_type", "cid", "fields", "is_function")

    def __init__(self, line: str, is_function: bool):
        self.cid = zlib.crc32(line.encode("ascii")) & 0xFFFFFFFF
        decl = line.split(" = ")[0]
        parts = decl.split()
        self.name = parts[0]
        # JSON @type: the bare name without the "dct." namespace.
        self.json_type = self.name.split(".", 1)[1]
        self.fields: List[Tuple[str, str]] = [
            tuple(p.split(":", 1)) for p in parts[1:]]
        self.is_function = is_function


BY_NAME: Dict[str, Constructor] = {}
BY_ID: Dict[int, Constructor] = {}
FUNC_BY_JSON_TYPE: Dict[str, Constructor] = {}
TYPE_BY_JSON_TYPE: Dict[str, Constructor] = {}
for _line in SCHEMA_TYPES:
    _c = Constructor(_line, is_function=False)
    BY_NAME[_c.name] = _c
    BY_ID[_c.cid] = _c
    TYPE_BY_JSON_TYPE[_c.json_type] = _c
for _line in SCHEMA_FUNCTIONS:
    _c = Constructor(_line, is_function=True)
    BY_NAME[_c.name] = _c
    BY_ID[_c.cid] = _c
    FUNC_BY_JSON_TYPE[_c.json_type] = _c


# -- TL writers over mtproto_wire's primitives ------------------------------
def _w_int(v: Any) -> bytes:
    return i32(int(v or 0))


def _w_long(v: Any) -> bytes:
    return i64(int(v or 0))


def _w_string(v: Any) -> bytes:
    return tl_bytes(("" if v is None else str(v)).encode("utf-8"))


def _w_bool(v: Any) -> bytes:
    return u32(BOOL_TRUE if v else BOOL_FALSE)


def _r_i32(r: TlReader) -> int:
    v = r.uint32()
    return v - (1 << 32) if v >= (1 << 31) else v


def _r_bool(r: TlReader) -> bool:
    v = r.uint32()
    if v == BOOL_TRUE:
        return True
    if v == BOOL_FALSE:
        return False
    raise ValueError(f"bad Bool constructor {v:#x}")


# -- generic constructor <-> JSON codec -------------------------------------
def _serialize_fields(c: Constructor, obj: Dict[str, Any]) -> bytes:
    out = struct.pack("<I", c.cid)
    for fname, ftype in c.fields:
        v = obj.get(fname)
        if ftype == "int":
            out += _w_int(v)
        elif ftype == "long":
            out += _w_long(v)
        elif ftype == "string":
            out += _w_string(v)
        elif ftype == "Bool":
            out += _w_bool(v)
        elif ftype == "DataJSON":
            out += _w_string(json.dumps(v) if v is not None else "null")
        elif ftype.startswith("Vector<"):
            inner = BY_NAME[ftype[len("Vector<"):-1]]
            items = v or []
            out += struct.pack("<I", VECTOR) + struct.pack("<i", len(items))
            for item in items:
                out += _serialize_fields(inner, item)
        else:
            raise ValueError(f"unknown TL field type {ftype}")
    return out


def _deserialize_fields(c: Constructor, r: TlReader) -> Dict[str, Any]:
    obj: Dict[str, Any] = {"@type": c.json_type}
    for fname, ftype in c.fields:
        if ftype == "int":
            obj[fname] = _r_i32(r)
        elif ftype == "long":
            obj[fname] = r.int64()
        elif ftype == "string":
            obj[fname] = r.tl_bytes().decode("utf-8")
        elif ftype == "Bool":
            obj[fname] = _r_bool(r)
        elif ftype == "DataJSON":
            obj[fname] = json.loads(r.tl_bytes().decode("utf-8"))
        elif ftype.startswith("Vector<"):
            inner = BY_NAME[ftype[len("Vector<"):-1]]
            if r.uint32() != VECTOR:
                raise ValueError("expected Vector")
            n = _r_i32(r)
            if n < 0:
                # A forged negative count must fail loudly, not parse as an
                # empty vector and leave the element bytes as garbage.
                raise ValueError(f"negative TL vector count {n}")
            items = []
            for _ in range(n):
                cid = r.uint32()
                if cid != inner.cid:
                    raise ValueError(
                        f"vector element {cid:#x} != {inner.name}")
                items.append(_deserialize_fields(inner, r))
            obj[fname] = items
        else:
            raise ValueError(f"unknown TL field type {ftype}")
    return obj


def serialize_request(req: Dict[str, Any]) -> bytes:
    """JSON request -> TL function frame.  ``@extra`` must already be
    stripped (it is client-local; correlation is req_msg_id)."""
    rtype = req.get("@type", "")
    c = FUNC_BY_JSON_TYPE.get(rtype)
    if c is not None and rtype != "rawRequest":
        return _serialize_fields(c, req)
    raw = BY_NAME["dct.rawRequest"]
    return _serialize_fields(raw, {"data": json.dumps(req)})


# Observability: how much of the traffic rides typed constructors vs the
# declared raw fallback (tests assert the hot RPCs are TYPED on the wire).
# Guarded by a lock: concurrent gateway sessions share this dict, and the
# bare read-modify-write undercounts under contention.
STATS = {"typed_requests": 0, "raw_requests": 0}
_STATS_LOCK = threading.Lock()


def _count(key: str) -> None:
    with _STATS_LOCK:
        STATS[key] += 1


def _expect_consumed(r: TlReader) -> None:
    """A well-formed frame is EXACTLY its constructor: trailing bytes mean
    a forged or corrupted frame and must raise (ValueError is the class
    the gateway session loop catches), never parse silently."""
    if r.off != len(r.data):
        raise ValueError(
            f"{len(r.data) - r.off} trailing bytes after TL frame")


def deserialize_request(data: bytes) -> Dict[str, Any]:
    """TL function frame -> JSON request (gateway side)."""
    r = TlReader(data)
    cid = r.uint32()
    c = BY_ID.get(cid)
    if c is None or not c.is_function:
        raise ValueError(f"unknown TL function {cid:#x}")
    obj = _deserialize_fields(c, r)
    _expect_consumed(r)
    if c.name == "dct.rawRequest":
        _count("raw_requests")
        return json.loads(obj["data"])
    _count("typed_requests")
    return obj


def serialize_result(resp: Dict[str, Any], req_msg_id: int) -> bytes:
    """JSON response -> rpc_result(req_msg_id, typed-or-raw object)."""
    return (struct.pack("<I", RPC_RESULT) + struct.pack("<q", req_msg_id) +
            _serialize_object(resp))


def serialize_update(update: Dict[str, Any]) -> bytes:
    """JSON push -> dct.update frame (no rpc_result: unsolicited)."""
    return _serialize_fields(BY_NAME["dct.update"],
                             {"data": json.dumps(update)})


def _serialize_object(resp: Dict[str, Any]) -> bytes:
    c = TYPE_BY_JSON_TYPE.get(resp.get("@type", ""))
    if c is not None and c.name not in ("dct.rawResult", "dct.update"):
        return _serialize_fields(c, resp)
    return _serialize_fields(BY_NAME["dct.rawResult"],
                             {"data": json.dumps(resp)})


def deserialize_frame(data: bytes) -> Tuple[Optional[int], Dict[str, Any]]:
    """Wire frame -> (req_msg_id | None, JSON object).

    ``req_msg_id`` is set for rpc_result frames (the client reattaches its
    local ``@extra`` from its msg_id map); None for updates."""
    r = TlReader(data)
    cid = r.uint32()
    if cid == RPC_RESULT:
        req_msg_id = r.int64()
        inner_cid = r.uint32()
        c = BY_ID.get(inner_cid)
        if c is None or c.is_function:
            raise ValueError(f"unknown TL result {inner_cid:#x}")
        obj = _deserialize_fields(c, r)
        _expect_consumed(r)
        if c.name == "dct.rawResult":
            obj = json.loads(obj["data"])
        return req_msg_id, obj
    c = BY_ID.get(cid)
    if c is None:
        raise ValueError(f"unknown TL frame {cid:#x}")
    obj = _deserialize_fields(c, r)
    _expect_consumed(r)
    if c.name in ("dct.update", "dct.rawResult"):
        obj = json.loads(obj["data"])
    return None, obj
