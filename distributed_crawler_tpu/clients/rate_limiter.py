"""Per-method rate limiting for the Telegram client.

Parity with `telegramhelper/rate_limiter.go`:
- independent per-method token buckets + jitter; proactive waits for
  GetChatHistory / SearchPublicChat / supergroup info (`:100-138`);
- **reactive** GetMessage limiting: a token is consumed only when the call
  misses the client's local cache, detected by latency (`:145-169`);
- latency-based cache attribution (<5 ms = cache, `telegramutils.go:855-879`).

Clocks are injectable so tests can assert inter-call spacing without sleeping
(the reference's rate_limiter_test.go asserts real spacing; we do both).
"""

from __future__ import annotations

import logging
import random
import threading
import time as _time
from typing import Callable, Optional

from ..config.crawler import TelegramRateLimitConfig
from .telegram import (
    TelegramClient,
    TLBasicGroupFullInfo,
    TLChat,
    TLFile,
    TLMessage,
    TLMessageLink,
    TLMessages,
    TLMessageThreadInfo,
    TLSupergroup,
    TLSupergroupFullInfo,
    TLUser,
)

logger = logging.getLogger("dct.clients.ratelimit")

# Latency thresholds for cache attribution (`telegramutils.go:855-879`).
CACHE_HIT_THRESHOLD_S = 0.005
SERVER_HIT_THRESHOLD_S = 0.015


class Clock:
    """Injectable time source."""

    def time(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError


class SystemClock(Clock):
    def time(self) -> float:
        return _time.monotonic()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            _time.sleep(seconds)


class FakeClock(Clock):
    """Deterministic clock for tests; sleep() advances time instantly."""

    def __init__(self, start: float = 0.0):
        self.now = start
        self.sleeps: list = []

    def time(self) -> float:
        return self.now

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            self.sleeps.append(seconds)
            self.now += seconds

    def advance(self, seconds: float) -> None:
        self.now += seconds


def detect_cache_or_server(elapsed_s: float, api_call: str = "") -> bool:
    """True if the call latency indicates a local-cache hit; logs the
    attribution for observability (`telegramutils.go:855-879`)."""
    cache_hit = elapsed_s < CACHE_HIT_THRESHOLD_S
    if api_call:
        logger.debug("call attribution", extra={
            "api_call": api_call, "elapsed_ms": int(elapsed_s * 1000),
            "source": "cache" if cache_hit else (
                "server" if elapsed_s > SERVER_HIT_THRESHOLD_S else "unknown")})
    return cache_hit


class TokenBucket:
    """calls-per-minute token bucket, burst 1 (x/time/rate analog)."""

    def __init__(self, calls_per_minute: float, clock: Optional[Clock] = None):
        self.clock = clock or SystemClock()
        self._lock = threading.Lock()
        if calls_per_minute <= 0:
            self.interval_s = 0.0  # unlimited (`rate_limiter.go:38-44`)
        else:
            self.interval_s = 60.0 / calls_per_minute
        self._next_free = self.clock.time()

    def reserve(self) -> float:
        """Consume a token; returns the delay the caller should wait."""
        with self._lock:
            if self.interval_s == 0.0:
                return 0.0
            now = self.clock.time()
            delay = max(0.0, self._next_free - now)
            self._next_free = max(self._next_free, now) + self.interval_s
            return delay

    def wait(self) -> float:
        """Block until a token is available; returns the time waited."""
        delay = self.reserve()
        self.clock.sleep(delay)
        return delay


class RateLimitedTelegramClient:
    """Decorator enforcing per-method limits over any TelegramClient
    (`rate_limiter.go:23-213`).  Each instance owns its buckets, so pooled
    connections never share quota."""

    def __init__(self, inner: TelegramClient,
                 config: Optional[TelegramRateLimitConfig] = None,
                 clock: Optional[Clock] = None,
                 rng: Optional[random.Random] = None):
        self.inner = inner
        cfg = config or TelegramRateLimitConfig()
        self.config = cfg
        self.clock = clock or SystemClock()
        self._rng = rng or random.Random()
        self._chat_history = TokenBucket(cfg.get_chat_history_rate, self.clock)
        self._search_chat = TokenBucket(cfg.search_public_chat_rate, self.clock)
        self._supergroup = TokenBucket(cfg.get_supergroup_info_rate, self.clock)
        self._get_message = TokenBucket(cfg.get_message_server_hit_rate, self.clock)

    # --- helpers ----------------------------------------------------------
    def _jitter_s(self, max_ms: int) -> float:
        return self._rng.randint(0, max_ms) / 1000.0 if max_ms > 0 else 0.0

    def _wait_with_jitter(self, bucket: TokenBucket, jitter_ms: int,
                          api_call: str) -> None:
        """`rate_limiter.go:78-90`."""
        bucket.wait()
        jitter = self._jitter_s(jitter_ms)
        logger.debug("rate limit wait", extra={"api_call": api_call,
                                               "jitter_ms": int(jitter * 1000)})
        self.clock.sleep(jitter)

    def _timed(self, api_call: str, fn: Callable):
        start = self.clock.time()
        result = fn()
        detect_cache_or_server(self.clock.time() - start, api_call)
        return result

    # --- proactively limited methods (`rate_limiter.go:100-138`) ----------
    def get_chat_history(self, chat_id: int, from_message_id: int = 0,
                         offset: int = 0, limit: int = 100) -> TLMessages:
        self._wait_with_jitter(self._chat_history,
                               self.config.get_chat_history_jitter_ms,
                               "GetChatHistory")
        return self._timed("GetChatHistory", lambda: self.inner.get_chat_history(
            chat_id, from_message_id, offset, limit))

    def search_public_chat(self, username: str) -> TLChat:
        self._wait_with_jitter(self._search_chat,
                               self.config.search_public_chat_jitter_ms,
                               "SearchPublicChat")
        return self._timed("SearchPublicChat",
                           lambda: self.inner.search_public_chat(username))

    def get_supergroup_full_info(self, supergroup_id: int) -> TLSupergroupFullInfo:
        self._wait_with_jitter(self._supergroup,
                               self.config.get_supergroup_info_jitter_ms,
                               "GetSupergroupFullInfo")
        return self._timed("GetSupergroupFullInfo",
                           lambda: self.inner.get_supergroup_full_info(supergroup_id))

    def get_basic_group_full_info(self, basic_group_id: int) -> TLBasicGroupFullInfo:
        self._wait_with_jitter(self._supergroup,
                               self.config.get_supergroup_info_jitter_ms,
                               "GetBasicGroupFullInfo")
        return self._timed("GetBasicGroupFullInfo",
                           lambda: self.inner.get_basic_group_full_info(basic_group_id))

    # --- reactive GetMessage (`rate_limiter.go:145-169`) -------------------
    def get_message(self, chat_id: int, message_id: int) -> TLMessage:
        start = self.clock.time()
        error: Optional[BaseException] = None
        result = None
        try:
            result = self.inner.get_message(chat_id, message_id)
        except BaseException as e:
            error = e
        cache_hit = detect_cache_or_server(self.clock.time() - start, "GetMessage")
        if not cache_hit:
            delay = self._get_message.reserve()
            total = delay + self._jitter_s(self.config.get_message_server_hit_jitter_ms)
            if total > 0:
                logger.debug("reactive throttle (server hit)",
                             extra={"api_call": "GetMessage",
                                    "throttle_delay_ms": int(delay * 1000)})
                self.clock.sleep(total)
        if error is not None:
            raise error
        return result

    # --- pass-through (`rate_limiter.go:171-213`) --------------------------
    def get_message_link(self, chat_id: int, message_id: int) -> TLMessageLink:
        return self.inner.get_message_link(chat_id, message_id)

    def get_message_thread_history(self, chat_id: int, message_id: int,
                                   from_message_id: int = 0,
                                   limit: int = 100) -> TLMessages:
        return self.inner.get_message_thread_history(chat_id, message_id,
                                                     from_message_id, limit)

    def get_message_thread(self, chat_id: int, message_id: int) -> TLMessageThreadInfo:
        return self.inner.get_message_thread(chat_id, message_id)

    def get_remote_file(self, remote_file_id: str) -> TLFile:
        return self.inner.get_remote_file(remote_file_id)

    def download_file(self, file_id: int) -> TLFile:
        return self.inner.download_file(file_id)

    def get_chat(self, chat_id: int) -> TLChat:
        return self.inner.get_chat(chat_id)

    def get_supergroup(self, supergroup_id: int) -> TLSupergroup:
        return self.inner.get_supergroup(supergroup_id)

    def close(self) -> None:
        return self.inner.close()

    def get_me(self) -> TLUser:
        return self.inner.get_me()

    def get_user(self, user_id: int) -> TLUser:
        return self.inner.get_user(user_id)

    def delete_file(self, file_id: int) -> None:
        return self.inner.delete_file(file_id)
