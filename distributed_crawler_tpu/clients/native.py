"""ctypes binding to the in-tree C++ client (the native TDLib-class boundary).

The reference reached TDLib through cgo (`Dockerfile:28`,
`go.mod: zelenin/go-tdlib`); this build binds `native/libdct_client.so`
through ctypes over the same td_json_client-style ABI:

    create(config_json) / send(request_json) / receive(timeout) /
    execute(request_json) / destroy

Requests carry ``@type`` + ``@extra`` correlation ids; the binding offers a
synchronous call helper that sends and waits for the matching response,
converting ``{"@type": "error"}`` into the crawl engine's error taxonomy
(`clients/errors.py`): code 429 + "retry after N" -> FloodWaitError, other
4xx -> TelegramError(400) which `crawl.errors.is_telegram_400` recognizes.

`NativeTelegramClient` implements the full 16-method `TelegramClient`
protocol (`crawler/crawler.go:109-126`), so the pool, rate limiter and crawl
engine run unchanged over the native core.
"""

from __future__ import annotations

import collections
import ctypes
import itertools
import json
import logging
import os
import subprocess
import threading
from typing import Any, Dict, Optional

logger = logging.getLogger("dct.clients.native")

from .errors import FloodWaitError, TelegramError, parse_migrate_dc
from .telegram import (
    TLBasicGroupFullInfo,
    TLChat,
    TLFile,
    TLMessage,
    TLMessageLink,
    TLMessages,
    TLMessageThreadInfo,
    TLSupergroup,
    TLSupergroupFullInfo,
    TLUser,
)

DEFAULT_LIB_BASENAME = "libdct_client.so"
_REPO_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native")

_lib_lock = threading.Lock()
_lib_cache: Dict[str, ctypes.CDLL] = {}


def find_library(path: Optional[str] = None) -> str:
    """Locate (building if necessary) the native client library."""
    candidates = [path] if path else []
    candidates += [
        os.environ.get("DCT_NATIVE_LIB", ""),
        os.path.join(_REPO_NATIVE_DIR, DEFAULT_LIB_BASENAME),
    ]
    for c in candidates:
        if c and os.path.exists(c):
            return c
    # Build in-tree if the sources are present and a compiler exists.
    makefile = os.path.join(_REPO_NATIVE_DIR, "Makefile")
    if os.path.exists(makefile):
        subprocess.run(["make", "-C", _REPO_NATIVE_DIR], check=True,
                       capture_output=True)
        built = os.path.join(_REPO_NATIVE_DIR, DEFAULT_LIB_BASENAME)
        if os.path.exists(built):
            return built
    raise FileNotFoundError(
        f"native client library not found (searched {candidates}); "
        f"build it with `make -C native`")


def load_library(path: Optional[str] = None) -> ctypes.CDLL:
    resolved = find_library(path)
    with _lib_lock:
        lib = _lib_cache.get(resolved)
        if lib is not None:
            return lib
        lib = ctypes.CDLL(resolved)
        lib.dct_client_create.restype = ctypes.c_void_p
        lib.dct_client_create.argtypes = [ctypes.c_char_p]
        lib.dct_client_send.restype = None
        lib.dct_client_send.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.dct_client_receive.restype = ctypes.c_char_p
        lib.dct_client_receive.argtypes = [ctypes.c_void_p, ctypes.c_double]
        lib.dct_client_execute.restype = ctypes.c_char_p
        lib.dct_client_execute.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.dct_client_destroy.restype = None
        lib.dct_client_destroy.argtypes = [ctypes.c_void_p]
        lib.dct_https_get.restype = ctypes.c_char_p
        lib.dct_https_get.argtypes = [ctypes.c_char_p]
        _lib_cache[resolved] = lib
        return lib


def native_https_get(host: str, path: str = "/", port: int = 443,
                     headers: Optional[Dict[str, str]] = None,
                     sni: str = "", tls_insecure: bool = False,
                     plain: bool = False, max_body: int = 1 << 20,
                     lib_path: Optional[str] = None) -> Dict[str, Any]:
    """One GET through the native Chrome-shaped TLS stack
    (`native/net.h`; fingerprint parity target `utlstransport.go:19-57`).
    Returns {"status": int, "body": bytes[, "alpn": str]}; raises
    NativeClientError on transport failure."""
    import base64

    lib = load_library(lib_path)
    cfg = {"host": host, "port": port, "path": path, "max_body": max_body}
    if headers:
        cfg["headers"] = dict(headers)
    if sni:
        cfg["sni"] = sni
    if tls_insecure:
        cfg["tls_insecure"] = True
    if plain:
        cfg["plain"] = True
    raw = lib.dct_https_get(json.dumps(cfg).encode("utf-8"))
    out = json.loads(raw.decode("utf-8"))
    if "error" in out:
        raise NativeClientError(500, out["error"])
    result = {"status": int(out["status"]),
              "body": base64.b64decode(out.get("body_b64", "")),
              "alpn": out.get("alpn", "")}
    if "location" in out:
        result["location"] = out["location"]
    return result


class NativeClientError(TelegramError):
    pass


def _raise_for_error(resp: Dict[str, Any]) -> None:
    if resp.get("@type") != "error":
        return
    code = int(resp.get("code") or 0)
    message = str(resp.get("message") or "")
    if code == 429 and "retry after" in message.lower():
        try:
            secs = int(message.lower().rsplit("retry after", 1)[1].strip())
        except (ValueError, IndexError):
            secs = 0
        raise FloodWaitError(secs)
    raise TelegramError(code, message)


class NativeTelegramClient:
    """The 16-method client over the C++ core."""

    def __init__(self, seed_db: str = "", seed_json: str = "",
                 lib_path: Optional[str] = None,
                 receive_timeout_s: float = 10.0, conn_id: str = "native0",
                 require_auth: bool = False, expected_code: str = "",
                 expected_password: str = "", server_addr: str = "",
                 tls: bool = False, tls_insecure: bool = False,
                 sni: str = "", wire: str = "",
                 server_pubkey_file: str = "",
                 dc_table: Optional[Dict[Any, Dict[str, str]]] = None):
        """Offline mode (default): the C++ engine serves from a seed store.

        Remote mode (``server_addr="host:port"``): every request rides the
        wire protocol over a real socket — plain TCP or, with ``tls=True``,
        a TLS stream whose ClientHello is Chrome-shaped (`native/net.h`).
        The server then owns the store and the auth ladder
        (``authenticate()`` drives it, as the reference's CLI interactor
        drove TDLib's, `telegramhelper/client.go:319-377`).

        ``wire="mtproto"`` selects the MTProto 2.0 envelope
        (`native/mtproto.h`): auth-key DH handshake on connect, AES-IGE
        message encryption after — the reference's TDLib↔DC protocol.
        Requires the server's RSA public key(s): ``server_pubkey_file``
        points at the JSON the gateway writes (`mtproto_wire.save_pubkey`)
        or a keyring (`mtproto_wire.load_keyring`).

        ``dc_table`` maps DC id -> ``{"address": "host:port",
        "pubkey_file": "..."}`` — the analog of Telegram's config
        dcOptions.  With it set, ``authenticate()`` follows
        ``PHONE_MIGRATE_X`` redirects to the account's home DC the way
        TDLib does internally."""
        self._lib = load_library(lib_path)
        self.conn_id = conn_id
        self.receive_timeout_s = receive_timeout_s
        self.dc_table = {str(k): dict(v)
                         for k, v in (dc_table or {}).items()}
        self.current_dc: Optional[int] = None
        self._remote_opts: Optional[Dict[str, Any]] = None
        config: Dict[str, Any] = {}
        if server_addr:
            self._remote_opts = dict(
                server_addr=server_addr, tls=tls,
                tls_insecure=tls_insecure, sni=sni, wire=wire,
                server_pubkey_file=server_pubkey_file)
            config = self._build_remote_config(self._remote_opts)
        elif seed_json:
            config["seed_json"] = seed_json
        elif seed_db:
            config["seed_db"] = seed_db
        if require_auth and not server_addr:
            config["require_auth"] = True
            if expected_code:
                config["expected_code"] = expected_code
            if expected_password:
                config["expected_password"] = expected_password
        self._handle = self._lib.dct_client_create(
            json.dumps(config).encode("utf-8"))
        if not self._handle:
            raise NativeClientError(
                500, "failed to create native client" +
                (f" (connect {server_addr} refused?)" if server_addr
                 else ""))
        self._extra = itertools.count(1)
        self._mu = threading.Lock()
        self._pending: Dict[str, Dict[str, Any]] = {}
        # Bounded: extra-less frames (auth state, events); a multi-day
        # remote client must not accumulate these without limit.
        self.updates: "collections.deque" = collections.deque(maxlen=256)
        self._transport_error: Optional[Dict[str, Any]] = None
        self._closed = False
        if not require_auth and not server_addr:
            self.wait_ready()

    @staticmethod
    def _build_remote_config(opts: Dict[str, Any]) -> Dict[str, Any]:
        config: Dict[str, Any] = {"server_addr": opts["server_addr"]}
        if opts.get("tls"):
            config["tls"] = True
        if opts.get("tls_insecure"):
            config["tls_insecure"] = True
        if opts.get("sni"):
            config["sni"] = opts["sni"]
        if opts.get("wire"):
            config["wire"] = opts["wire"]
        if opts.get("server_pubkey_file"):
            # Keyring semantics (real clients pin several DC keys and
            # select by the resPQ fingerprint): the file may hold one
            # key, a list, or {"keys": [...]}.
            from .mtproto_wire import load_keyring

            config["server_pubkeys"] = [
                {"n": hex(k.n), "e": k.e}
                for k in load_keyring(opts["server_pubkey_file"])]
        return config

    # -- auth (the TDLib ladder, `telegramhelper/client.go:319-377`) -------
    def authenticate(self, phone_number: str, phone_code: str,
                     api_id: str = "", api_hash: str = "",
                     password: str = "",
                     database_directory: str = ".tdlib/database") -> None:
        """Walk WaitTdlibParameters -> WaitPhoneNumber -> WaitCode
        [-> WaitPassword] -> Ready (the flow the reference's CLI interactor
        drives; password is the 2FA leg of `standalone/runner.go:77-192`).

        DC migration: a ``PHONE_MIGRATE_X`` (Telegram's 303 redirect to the
        account's home DC) reconnects to ``dc_table[X]`` and restarts the
        ladder there — the behavior TDLib performs internally, surfaced here
        because this client owns the connection."""
        max_hops = 3  # bound redirect chains (cyclic tables misconfigure)
        for hop in range(max_hops):
            self._call({"@type": "setTdlibParameters",
                        "api_id": api_id, "api_hash": api_hash,
                        "database_directory": database_directory})
            try:
                self._call({"@type": "setAuthenticationPhoneNumber",
                            "phone_number": phone_number})
                break
            except TelegramError as e:
                dc = parse_migrate_dc(e)
                if dc is None or str(dc) not in self.dc_table:
                    raise
                if hop == max_hops - 1:
                    # Budget exhausted: don't tear down a live connection
                    # for a DC we'd never actually try.
                    raise NativeClientError(
                        500, f"too many DC migrations (last: {e.message})"
                    ) from e
                logger.info("DC migration: %s -> dc %d", e.message, dc,
                            extra={"conn_id": self.conn_id})
                self._reconnect_to_dc(dc)
        self._call({"@type": "checkAuthenticationCode",
                    "code": phone_code})
        if password:
            self._call({"@type": "checkAuthenticationPassword",
                        "password": password})

    def _reconnect_to_dc(self, dc: int) -> None:
        """Tear down the wire connection and rebuild it against the DC-table
        entry (address + that DC's pinned pubkey), resetting session state —
        the client half of Telegram's migrate flow."""
        if self._remote_opts is None:
            raise NativeClientError(500, "DC migration needs remote mode")
        entry = self.dc_table[str(dc)]
        opts = dict(self._remote_opts)
        opts["server_addr"] = entry["address"]
        if entry.get("pubkey_file"):
            opts["server_pubkey_file"] = entry["pubkey_file"]
        config = self._build_remote_config(opts)
        with self._mu:
            handle = self._lib.dct_client_create(
                json.dumps(config).encode("utf-8"))
            if not handle:
                raise NativeClientError(
                    500, f"failed to connect to dc {dc} "
                         f"({entry['address']} refused?)")
            self._lib.dct_client_destroy(self._handle)
            self._handle = handle
            self._pending.clear()
            self.updates.clear()
            self._transport_error = None
            self._remote_opts = opts
            self.current_dc = dc

    # -- plumbing ----------------------------------------------------------
    @staticmethod
    def _is_ready_update(resp: Dict[str, Any]) -> bool:
        return resp.get("@type") == "updateAuthorizationState" and \
            resp.get("authorization_state", {}).get("@type") == \
            "authorizationStateReady"

    def wait_ready(self, timeout_s: float = 10.0) -> None:
        """Drain updates until authorizationStateReady (the TDLib auth
        terminal state the reference waits for,
        `telegramhelper/client.go:319-377`).  Updates already swallowed by
        an in-flight `_call` are checked first."""
        if any(self._is_ready_update(u) for u in self.updates):
            return
        resp = self._receive(timeout_s)
        while resp is not None:
            if self._is_ready_update(resp):
                self.updates.append(resp)
                return
            resp = self._receive(timeout_s)
        raise NativeClientError(500, "native client never became ready")

    def _receive(self, timeout_s: float) -> Optional[Dict[str, Any]]:
        raw = self._lib.dct_client_receive(self._handle,
                                           ctypes.c_double(timeout_s))
        if raw is None:
            return None
        return json.loads(raw.decode("utf-8"))

    def _call(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """Send + wait for the correlated response (serialized per client,
        like the reference's one-outstanding-call-per-connection usage)."""
        extra = f"req{next(self._extra)}"
        request = dict(request)
        request["@extra"] = extra
        with self._mu:
            if self._closed:
                raise NativeClientError(500, "client is closed")
            if self._transport_error is not None:
                _raise_for_error(self._transport_error)
            self._lib.dct_client_send(self._handle,
                                      json.dumps(request).encode("utf-8"))
            deadline_attempts = max(1, int(self.receive_timeout_s / 0.5))
            for _ in range(deadline_attempts):
                resp = self._pending.pop(extra, None)
                if resp is None:
                    got = self._receive(0.5)
                    if got is None:
                        continue
                    if got.get("@extra") != extra:
                        key = got.get("@extra")
                        if key is not None:
                            self._pending[key] = got
                        elif got.get("@type") == "error" and \
                                got.get("transport"):
                            # Connection-level failure: fail THIS call now
                            # and every later one immediately.
                            self._transport_error = got
                            _raise_for_error(got)
                        else:
                            self.updates.append(got)  # auth-state etc.
                        continue  # an update or an older response
                    resp = got
                _raise_for_error(resp)
                return resp
        raise NativeClientError(500, "timed out waiting for native response")

    # -- the 16 methods ----------------------------------------------------
    def get_message(self, chat_id: int, message_id: int) -> TLMessage:
        r = self._call({"@type": "getMessage", "chat_id": chat_id,
                        "message_id": message_id})
        return self._message(r)

    def get_message_link(self, chat_id: int, message_id: int) -> TLMessageLink:
        r = self._call({"@type": "getMessageLink", "chat_id": chat_id,
                        "message_id": message_id})
        return TLMessageLink(link=r.get("link", ""),
                             is_public=bool(r.get("is_public", True)))

    def get_message_thread_history(self, chat_id: int, message_id: int,
                                   from_message_id: int = 0,
                                   limit: int = 100) -> TLMessages:
        r = self._call({"@type": "getMessageThreadHistory",
                        "chat_id": chat_id, "message_id": message_id,
                        "from_message_id": from_message_id, "limit": limit})
        return self._messages(r)

    def get_message_thread(self, chat_id: int,
                           message_id: int) -> TLMessageThreadInfo:
        r = self._call({"@type": "getMessageThread", "chat_id": chat_id,
                        "message_id": message_id})
        return TLMessageThreadInfo(
            chat_id=int(r.get("chat_id", 0)),
            message_thread_id=int(r.get("message_thread_id", 0)),
            reply_count=int(r.get("reply_count", 0)))

    def get_remote_file(self, remote_file_id: str) -> TLFile:
        r = self._call({"@type": "getRemoteFile",
                        "remote_file_id": remote_file_id})
        return self._file(r)

    def download_file(self, file_id: int) -> TLFile:
        r = self._call({"@type": "downloadFile", "file_id": file_id})
        return self._file(r)

    def get_chat_history(self, chat_id: int, from_message_id: int = 0,
                         offset: int = 0, limit: int = 100) -> TLMessages:
        r = self._call({"@type": "getChatHistory", "chat_id": chat_id,
                        "from_message_id": from_message_id,
                        "offset": offset, "limit": limit})
        return self._messages(r)

    def search_public_chat(self, username: str) -> TLChat:
        r = self._call({"@type": "searchPublicChat", "username": username})
        return self._chat(r)

    def get_chat(self, chat_id: int) -> TLChat:
        r = self._call({"@type": "getChat", "chat_id": chat_id})
        return self._chat(r)

    def get_supergroup(self, supergroup_id: int) -> TLSupergroup:
        r = self._call({"@type": "getSupergroup",
                        "supergroup_id": supergroup_id})
        return TLSupergroup(
            id=int(r.get("id", 0)), username=r.get("username", ""),
            member_count=int(r.get("member_count", 0)),
            is_channel=bool(r.get("is_channel", True)),
            date=int(r.get("date", 0)),
            is_verified=bool(r.get("is_verified", False)))

    def get_supergroup_full_info(self,
                                 supergroup_id: int) -> TLSupergroupFullInfo:
        r = self._call({"@type": "getSupergroupFullInfo",
                        "supergroup_id": supergroup_id})
        return TLSupergroupFullInfo(
            description=r.get("description", ""),
            member_count=int(r.get("member_count", 0)),
            photo_remote_id=r.get("photo_remote_id", ""))

    def execute_raw(self, request_json: str) -> str:
        """Synchronous local execute on the C++ engine (offline mode only);
        used by the mock DC server to proxy wire requests."""
        raw = self._lib.dct_client_execute(
            self._handle, request_json.encode("utf-8"))
        return raw.decode("utf-8") if raw else "{}"

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            self._closed = True
        handle, self._handle = self._handle, None
        if handle:
            self._lib.dct_client_destroy(handle)

    def get_me(self) -> TLUser:
        r = self._call({"@type": "getMe"})
        return self._user(r)

    def get_basic_group_full_info(self,
                                  basic_group_id: int) -> TLBasicGroupFullInfo:
        r = self._call({"@type": "getBasicGroupFullInfo",
                        "basic_group_id": basic_group_id})
        return TLBasicGroupFullInfo(
            description=r.get("description", ""),
            members_count=int(r.get("members_count", 0)))

    def get_user(self, user_id: int) -> TLUser:
        r = self._call({"@type": "getUser", "user_id": user_id})
        return self._user(r)

    def delete_file(self, file_id: int) -> None:
        self._call({"@type": "deleteFile", "file_id": file_id})

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- converters --------------------------------------------------------
    @staticmethod
    def _message(r: Dict[str, Any]) -> TLMessage:
        return TLMessage(
            id=int(r.get("id", 0)), chat_id=int(r.get("chat_id", 0)),
            date=int(r.get("date", 0)), content=r.get("content") or {},
            view_count=int(r.get("view_count", 0)),
            forward_count=int(r.get("forward_count", 0)),
            reply_count=int(r.get("reply_count", 0)),
            reactions={k: int(v) for k, v in
                       (r.get("reactions") or {}).items()},
            message_thread_id=int(r.get("message_thread_id", 0)),
            reply_to_message_id=int(r.get("reply_to_message_id", 0)),
            sender_id=int(r.get("sender_id", 0)),
            sender_username=r.get("sender_username", ""),
            is_channel_post=bool(r.get("is_channel_post", False)))

    @classmethod
    def _messages(cls, r: Dict[str, Any]) -> TLMessages:
        return TLMessages(
            total_count=int(r.get("total_count", 0)),
            messages=[cls._message(m) for m in r.get("messages") or []])

    @staticmethod
    def _chat(r: Dict[str, Any]) -> TLChat:
        return TLChat(
            id=int(r.get("id", 0)), title=r.get("title", ""),
            type=r.get("type", "supergroup"),
            supergroup_id=int(r.get("supergroup_id", 0)),
            basic_group_id=int(r.get("basic_group_id", 0)),
            photo_remote_id=r.get("photo_remote_id", ""))

    @staticmethod
    def _file(r: Dict[str, Any]) -> TLFile:
        return TLFile(
            id=int(r.get("id", 0)), remote_id=r.get("remote_id", ""),
            local_path=r.get("local_path", ""),
            size=int(r.get("size", 0)),
            downloaded=bool(r.get("downloaded", False)))

    @staticmethod
    def _user(r: Dict[str, Any]) -> TLUser:
        return TLUser(
            id=int(r.get("id", 0)), username=r.get("username", ""),
            first_name=r.get("first_name", ""),
            last_name=r.get("last_name", ""))


def generate_pcode(tdlib_dir: str = ".tdlib",
                   env: Optional[Dict[str, str]] = None,
                   client: Optional[NativeTelegramClient] = None) -> str:
    """Auth bootstrap writing credentials.json
    (`standalone/runner.go:77-192`): reads TG_API_ID / TG_API_HASH /
    TG_PHONE_NUMBER / TG_PHONE_CODE, drives the auth ladder on a native
    client, and persists the credentials with restrictive permissions.
    Returns the credentials path."""
    env = env if env is not None else dict(os.environ)
    api_id = env.get("TG_API_ID", "")
    api_hash = env.get("TG_API_HASH", "")
    phone = env.get("TG_PHONE_NUMBER", "")
    code = env.get("TG_PHONE_CODE", "")
    password = env.get("TG_PASSWORD", "")  # the 2FA leg
    if not api_id or not phone:
        raise ValueError("TG_API_ID and TG_PHONE_NUMBER are required")
    int(api_id)  # parity with the reference's strconv check

    os.makedirs(tdlib_dir, exist_ok=True)
    owns_client = client is None
    if client is None:
        client = NativeTelegramClient(require_auth=True)
    try:
        client.authenticate(
            phone, code, api_id=api_id, api_hash=api_hash,
            password=password,
            database_directory=os.path.join(tdlib_dir, "database"))
        me = client.get_me()
        logger.info("authenticated", extra={
            "me": f"{me.first_name} {me.last_name}".strip()})
    finally:
        if owns_client:
            client.close()

    creds_path = os.path.join(tdlib_dir, "credentials.json")
    creds = {"api_id": api_id, "api_hash": api_hash,
             "phone_number": phone, "phone_code": code}
    if password:
        creds["password"] = password  # pools replay the 2FA leg too
    with open(creds_path, "w", encoding="utf-8") as f:
        json.dump(creds, f, indent=2)
    os.chmod(creds_path, 0o600)
    return creds_path


def load_credentials(tdlib_dir: str = ".tdlib",
                     env: Optional[Dict[str, str]] = None
                     ) -> Optional[Dict[str, str]]:
    """Credentials for the auth ladder: ``{tdlib_dir}/credentials.json``
    (written by `generate_pcode` / `dct --mode gen-code`) first, TG_* env
    fallback — the same two sources, same order, the reference's client
    used (`telegramhelper/client.go:121-142,278-298`).  Returns None when
    neither is present (offline stores need no auth)."""
    path = os.path.join(tdlib_dir, "credentials.json")
    try:
        with open(path, "r", encoding="utf-8") as f:
            creds = json.load(f)
        if creds.get("phone_number"):
            return {k: str(creds.get(k, "")) for k in
                    ("api_id", "api_hash", "phone_number", "phone_code",
                     "password")}
    except (OSError, ValueError):
        pass
    env = env if env is not None else dict(os.environ)
    if env.get("TG_PHONE_NUMBER"):
        return {"api_id": env.get("TG_API_ID", ""),
                "api_hash": env.get("TG_API_HASH", ""),
                "phone_number": env.get("TG_PHONE_NUMBER", ""),
                "phone_code": env.get("TG_PHONE_CODE", ""),
                "password": env.get("TG_PASSWORD", "")}
    return None


def fnv32(s: str) -> int:
    """FNV-1a 32-bit — the hash the reference used to derive unique
    per-connection database dirs (`telegramhelper/client.go:252`)."""
    h = 0x811C9DC5
    for b in s.encode("utf-8"):
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h


def acquire_seed_db(source: str, base_dir: str, conn_id: str) -> str:
    """Materialize a pre-seeded client DB for one connection, parity with
    the reference's tarball download/extract flow
    (`telegramhelper/client.go:232-260,433-533`):

    - ``source``: a ``file://`` URL or local path to a ``.tar.gz``/
      ``.tgz``/``.tar`` archive, a directory, or a bare seed ``.json``;
    - extracts/copies into ``{base_dir}/conn_{fnv32(conn_id):08x}/`` so
      concurrent connections never share a database directory;
    - returns the path to the seed JSON inside (``seed.json`` preferred,
      else the single ``*.json``); idempotent per connection dir.

    HTTP(S) sources belong to the deployment layer (no egress here); a
    non-file scheme raises with that guidance."""
    import shutil
    import tarfile
    from urllib.parse import urlsplit

    if "://" in source:
        parts = urlsplit(source)
        if parts.scheme != "file":
            raise NativeClientError(
                400, f"unsupported seed-db scheme {parts.scheme!r}: "
                     f"mirror the tarball locally and pass a file:// URL")
        source = parts.path
    if not os.path.exists(source):
        raise NativeClientError(400, f"seed db source not found: {source}")

    conn_dir = os.path.join(base_dir, f"conn_{fnv32(conn_id):08x}")

    # Reuse is keyed on the SOURCE's identity too: a replaced/updated
    # tarball (same path, new content) or a different --tdlib-database-urls
    # entry must re-extract, not silently serve the stale copy.  Directory
    # sources fingerprint their CONTENTS (POSIX dir mtime doesn't change
    # when a contained file is edited in place).
    if os.path.isdir(source):
        entries = []
        for dirpath, _dn, filenames in os.walk(source):
            for name in sorted(filenames):
                fst = os.stat(os.path.join(dirpath, name))
                entries.append((os.path.relpath(
                    os.path.join(dirpath, name), source),
                    getattr(fst, "st_mtime_ns", int(fst.st_mtime * 1e9)),
                    fst.st_size))
        ident = {"entries": sorted(entries)}
    else:
        st = os.stat(source)
        ident = {"mtime_ns": getattr(st, "st_mtime_ns",
                                     int(st.st_mtime * 1e9)),
                 "size": st.st_size}
    source_tag = json.dumps({"source": os.path.abspath(source), **ident},
                            sort_keys=True)
    tag_path = os.path.join(conn_dir, ".seed_source.json")

    def _find_seed(root: str) -> str:
        preferred = None
        candidates = []
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in filenames:
                if name == "seed.json":
                    preferred = os.path.join(dirpath, name)
                elif name.endswith(".json") and not name.startswith("."):
                    # dotfiles (.seed_source.json marker) are metadata
                    candidates.append(os.path.join(dirpath, name))
        if preferred:
            return preferred
        if len(candidates) == 1:
            return candidates[0]
        raise NativeClientError(
            400, f"no unambiguous seed JSON under {root}: "
                 f"{len(candidates)} candidates")

    if os.path.isdir(conn_dir):
        try:
            with open(tag_path, "r", encoding="utf-8") as f:
                fresh = f.read() == source_tag
        except OSError:
            fresh = False  # pre-tag extraction or tampered dir: re-extract
        if fresh:
            return _find_seed(conn_dir)  # already acquired for this conn
        logger.info("seed db source changed for %s; re-extracting", conn_id)
        shutil.rmtree(conn_dir, ignore_errors=True)

    staging = conn_dir + ".tmp"
    shutil.rmtree(staging, ignore_errors=True)
    os.makedirs(staging, exist_ok=True)
    try:
        if os.path.isdir(source):
            shutil.copytree(source, os.path.join(staging, "db"),
                            dirs_exist_ok=True)
        elif source.endswith((".tar.gz", ".tgz", ".tar")):
            with tarfile.open(source, "r:*") as tar:
                try:
                    tar.extractall(staging, filter="data")
                except TypeError:
                    # Python <3.10.12/<3.11.4 lack the filter kwarg
                    # backport; fall back after rejecting absolute or
                    # traversal paths — and link members entirely, since a
                    # symlink/hardlink could point outside the staging dir
                    # (seed DB tarballs never legitimately contain links).
                    members = tar.getmembers()
                    for m in members:
                        p = m.name
                        if p.startswith(("/", "..")) or "/../" in p:
                            raise NativeClientError(
                                400, f"unsafe path in seed tarball: {p}")
                        if m.issym() or m.islnk():
                            raise NativeClientError(
                                400, f"link member in seed tarball: {p}")
                        if not (m.isfile() or m.isdir()):
                            # FIFOs/devices — filter="data" raises
                            # SpecialFileError for these; match it.
                            raise NativeClientError(
                                400, f"special member in seed tarball: {p}")
                    tar.extractall(staging, members=members)
        elif source.endswith(".json"):
            shutil.copyfile(source, os.path.join(staging, "seed.json"))
        else:
            raise NativeClientError(
                400, f"unrecognized seed db format: {source}")
        with open(os.path.join(staging, ".seed_source.json"), "w",
                  encoding="utf-8") as f:
            f.write(source_tag)
        os.replace(staging, conn_dir)  # atomic publish of the conn dir
    except Exception:
        shutil.rmtree(staging, ignore_errors=True)
        raise
    return _find_seed(conn_dir)


def load_dc_table(path: str) -> Dict[str, Dict[str, str]]:
    """DC table JSON -> {dc_id: {"address", "pubkey_file"}} — the analog of
    Telegram's config dcOptions.  Accepts ``{"dcs": {...}}`` or the flat
    map."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    table = doc.get("dcs", doc) if isinstance(doc, dict) else None
    if not isinstance(table, dict):
        raise ValueError(f"dc table {path}: expected a {{dc_id: ...}} map")
    out: Dict[str, Dict[str, str]] = {}
    for dc, entry in table.items():
        if not isinstance(entry, dict) or not entry.get("address"):
            raise ValueError(f"dc table {path}: dc {dc} needs an address")
        out[str(dc)] = {"address": str(entry["address"]),
                        "pubkey_file": str(entry.get("pubkey_file", ""))}
    return out


def native_client_factory(seed_db: str = "", seed_json: str = "",
                          lib_path: Optional[str] = None,
                          db_source: str = "",
                          db_base_dir: str = ".tdlib/databases",
                          server_addr: str = "", tls: bool = False,
                          tls_insecure: bool = False, sni: str = "",
                          credentials: Optional[Dict[str, str]] = None,
                          tdlib_dir: str = ".tdlib", wire: str = "",
                          server_pubkey_file: str = "",
                          dc_table: Optional[Dict[Any, Dict[str,
                                                            str]]] = None):
    """Pool-compatible factory: returns a callable producing fresh
    authenticated clients (`telegramhelper/connection_pool.go:97-149`
    preloaded each conn from a DB URL).  With ``db_source`` set, each
    connection acquires its own extracted copy of the seed tarball under
    ``{db_base_dir}/conn_<fnv32>`` (`telegramhelper/client.go:232-260`).

    With ``server_addr`` set the pool runs in REMOTE mode: each client
    dials the DC gateway (`clients/dc_gateway.py`) over TCP/TLS and walks
    the auth ladder with ``credentials`` (a `load_credentials` dict) before
    it is handed out — the pool-side half of the reference's
    login-once-per-connection flow (`telegramhelper/client.go:319-377`)."""
    def make(conn_id: str) -> NativeTelegramClient:
        if server_addr:
            client = NativeTelegramClient(
                server_addr=server_addr, tls=tls,
                tls_insecure=tls_insecure, sni=sni, wire=wire,
                server_pubkey_file=server_pubkey_file,
                dc_table=dc_table,
                lib_path=lib_path, conn_id=conn_id)
            creds = credentials or load_credentials(tdlib_dir)
            if creds is None:
                client.close()
                raise NativeClientError(
                    401, "remote mode needs credentials: run `dct --mode "
                         "gen-code` or set TG_PHONE_NUMBER/TG_PHONE_CODE")
            try:
                client.authenticate(
                    creds["phone_number"], creds.get("phone_code", ""),
                    api_id=creds.get("api_id", ""),
                    api_hash=creds.get("api_hash", ""),
                    password=creds.get("password", ""))
                client.wait_ready()
            except Exception:
                client.close()
                raise
            return client
        per_conn_db = seed_db
        if db_source:
            per_conn_db = acquire_seed_db(db_source, db_base_dir, conn_id)
        return NativeTelegramClient(
            seed_db=per_conn_db, seed_json=seed_json, lib_path=lib_path,
            conn_id=conn_id)

    return make
