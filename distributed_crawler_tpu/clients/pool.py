"""Connection pool of authenticated Telegram clients.

Parity with `telegramhelper/connection_pool.go`:
- pool keyed by connection ID, preloaded from per-account database URLs
  (`:97-149`); acquire/release without re-login (`:163-273`);
- error-recreate path: close, wipe, recreate in place (`:346-413`);
- permanent retire on long FLOOD_WAIT (`:421-439`); empty-pool detection;
- every client is wrapped in the per-connection rate limiter at insertion
  (`:144,230,408`); stats (`:467-476`); a testing constructor that accepts
  pre-built clients (`:446`).
"""

from __future__ import annotations

import logging
import queue
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..config.crawler import TelegramRateLimitConfig
from .rate_limiter import Clock, RateLimitedTelegramClient
from .telegram import TelegramClient

logger = logging.getLogger("dct.clients.pool")

ClientFactory = Callable[[str], TelegramClient]


class PoolEmptyError(Exception):
    """All connections are retired or the pool was never initialized."""


@dataclass
class PooledConnection:
    conn_id: str
    client: TelegramClient  # rate-limited wrapper
    database_url: str = ""
    uses: int = 0
    errors: int = 0
    retired: bool = False
    retire_reason: str = ""


class ConnectionPool:
    """Thread-safe pool with retire/recreate semantics."""

    def __init__(self, factory: ClientFactory,
                 database_urls: Optional[List[str]] = None,
                 rate_limit: Optional[TelegramRateLimitConfig] = None,
                 clock: Optional[Clock] = None):
        self.factory = factory
        self.database_urls = list(database_urls or [])
        self.rate_limit = rate_limit or TelegramRateLimitConfig()
        self.clock = clock
        self._lock = threading.RLock()
        self._conns: Dict[str, PooledConnection] = {}
        self._available: "queue.Queue[str]" = queue.Queue()

    # --- construction -----------------------------------------------------
    def initialize(self) -> int:
        """Create one authenticated connection per database URL
        (`connection_pool.go:97-149`).  Returns the number of live
        connections; failures to create individual connections are logged and
        skipped."""
        created = 0
        urls = self.database_urls or [""]
        for i, url in enumerate(urls):
            conn_id = f"conn_{i}"
            try:
                self._insert(conn_id, self.factory(conn_id), url)
                created += 1
            except Exception as e:
                logger.error("failed to create connection %s: %s", conn_id, e)
        logger.info("connection pool initialized", extra={
            "log_tag": "rw_pool", "connections": created})
        return created

    @classmethod
    def for_testing(cls, clients: Dict[str, TelegramClient],
                    rate_limit: Optional[TelegramRateLimitConfig] = None,
                    clock: Optional[Clock] = None) -> "ConnectionPool":
        """Build a pool from pre-built clients (`connection_pool.go:446`)."""
        pool = cls(factory=lambda cid: clients[cid], rate_limit=rate_limit,
                   clock=clock)
        for conn_id, client in clients.items():
            pool._insert(conn_id, client, "")
        return pool

    def _insert(self, conn_id: str, raw_client: TelegramClient,
                database_url: str) -> None:
        # Rate limiter wraps at insertion so quota follows the connection.
        wrapped = RateLimitedTelegramClient(raw_client, self.rate_limit,
                                            clock=self.clock)
        with self._lock:
            self._conns[conn_id] = PooledConnection(
                conn_id=conn_id, client=wrapped, database_url=database_url)
        self._available.put(conn_id)

    # --- acquire / release -------------------------------------------------
    def acquire(self, timeout_s: Optional[float] = None) -> PooledConnection:
        """Get a connection without re-login (`connection_pool.go:163-273`)."""
        while True:
            if self.empty():
                raise PoolEmptyError("no live connections in pool")
            try:
                conn_id = self._available.get(
                    timeout=timeout_s if timeout_s is not None else 5.0)
            except queue.Empty:
                if timeout_s is not None:
                    raise TimeoutError("timed out waiting for a pool connection")
                continue
            with self._lock:
                conn = self._conns.get(conn_id)
                if conn is None or conn.retired:
                    continue  # retired while queued
                conn.uses += 1
                return conn

    def release(self, conn: PooledConnection) -> None:
        with self._lock:
            # Ignore stale handles (retired, or replaced by recreate()) so a
            # conn_id can never be queued twice and shared by two acquirers.
            if conn.retired or self._conns.get(conn.conn_id) is not conn:
                return
        self._available.put(conn.conn_id)

    # --- failure handling --------------------------------------------------
    def recreate(self, conn: PooledConnection) -> PooledConnection:
        """Close and rebuild a connection in place after a connection-level
        error (`connection_pool.go:346-413`)."""
        try:
            conn.client.close()
        except Exception:
            pass
        with self._lock:
            conn.errors += 1
            database_url = conn.database_url
        try:
            raw = self.factory(conn.conn_id)
        except Exception:
            # The old client is closed and unusable: retire the slot so the
            # pool doesn't count a phantom live connection forever.
            self.retire(conn.conn_id, "recreate_failed")
            raise
        wrapped = RateLimitedTelegramClient(raw, self.rate_limit, clock=self.clock)
        with self._lock:
            fresh = PooledConnection(conn_id=conn.conn_id, client=wrapped,
                                     database_url=database_url,
                                     errors=conn.errors)
            self._conns[conn.conn_id] = fresh
        # The caller owns `fresh` (as if acquired) and must release() it;
        # enqueueing here as well would hand the same connection to two users.
        fresh.uses += 1
        return fresh

    def retire(self, conn_id: str, reason: str = "") -> None:
        """Permanently remove a connection (long FLOOD_WAIT,
        `connection_pool.go:421-439`)."""
        with self._lock:
            conn = self._conns.get(conn_id)
            if conn is None or conn.retired:
                return
            conn.retired = True
            conn.retire_reason = reason
        try:
            conn.client.close()
        except Exception:
            pass
        logger.warning("connection retired", extra={
            "log_tag": "rw_pool", "conn_id": conn_id, "reason": reason})

    # --- introspection ------------------------------------------------------
    def empty(self) -> bool:
        with self._lock:
            return all(c.retired for c in self._conns.values()) or not self._conns

    def stats(self) -> Dict[str, object]:
        """`connection_pool.go:467-476`."""
        with self._lock:
            live = [c for c in self._conns.values() if not c.retired]
            return {
                "total": len(self._conns),
                "live": len(live),
                "retired": len(self._conns) - len(live),
                "total_uses": sum(c.uses for c in self._conns.values()),
                "total_errors": sum(c.errors for c in self._conns.values()),
            }

    def close_all(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            try:
                c.client.close()
            except Exception:
                pass
