"""Deployable DCT gateway: the server side of the native wire protocol.

The reference's native client terminated at real Telegram data centers
(TDLib compiled in `Dockerfile.tdlib:19-36`, authenticated with a 30 s init
timeout in `telegramhelper/client.go:319-377`).  This build's C++ client
speaks the in-tree DCT-v1 protocol instead (4-byte big-endian length ‖ JSON
frame over TCP/TLS, `native/net.h`), and THIS module is its production
counterpart: a first-class listener a deployment actually runs (`dct --mode
dc-gateway`), not a test double.

Per connection it drives the TDLib-style auth ladder (handshake →
WaitTdlibParameters → WaitPhoneNumber → WaitCode [→ WaitPassword] → Ready),
verifying credentials against an ACCOUNTS table (per-phone code/password,
the server half of `standalone/runner.go:77-192`'s GenCode flow), then
proxies every request to an embedded offline native engine
(`dct_client_execute`) seeded from the configured store — so all 16 client
methods work over the wire with zero duplicated routing logic.

Production deltas over the test mock (`clients/mock_dc.py`, which now
subclasses this):

- per-account credentials (``accounts=`` or an accounts JSON file) instead
  of one global code;
- a persistent store root: each connection's engine seeds from
  ``seed_source`` via `acquire_seed_db` under ``store_root`` (tarball /
  dir / json, same flow as the client-side pool preload,
  `telegramhelper/client.go:232-260`);
- TLS from operator-provided cert/key paths (self-signed minting stays
  available for bootstrap);
- an auth deadline per connection (the reference's 30 s init timeout,
  server side) so half-open sockets can't pin threads;
- counters + a ``status()`` map for the metrics endpoint, and an address
  file for process-level discovery (port 0 ⇒ kernel-assigned).
"""

from __future__ import annotations

import json
import logging
import os
import socket
import ssl
import struct
import subprocess
import threading
import time
from typing import Any, Dict, Optional

from .native import NativeTelegramClient, acquire_seed_db

logger = logging.getLogger("dct.gateway")

_HEADER = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024
# Server-side mirror of the client's 30 s init budget
# (`telegramhelper/client.go:319-377`): a connection that hasn't reached
# Ready within this window is dropped.
DEFAULT_AUTH_TIMEOUT_S = 30.0
# Concurrent-connection-thread cap (0 = unlimited): the auth watchdog
# bounds each unauthenticated thread's lifetime, the cap bounds their
# count.
DEFAULT_MAX_CONNECTIONS = 256


def send_frame(sock, payload: bytes) -> None:
    if hasattr(sock, "send_payload"):  # wire adapter (e.g. MTProto)
        sock.send_payload(payload)
        return
    sock.sendall(_HEADER.pack(len(payload)) + payload)


def recv_frame(sock) -> Optional[bytes]:
    if hasattr(sock, "recv_payload"):  # wire adapter (e.g. MTProto)
        return sock.recv_payload()
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (n,) = _HEADER.unpack(header)
    if n > MAX_FRAME:
        raise ValueError("oversized frame")
    return _recv_exact(sock, n)


def _recv_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            raise  # auth deadline — let the caller log it distinctly
        except (ConnectionResetError, OSError):
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def make_self_signed_cert(directory: str, cn: str = "localhost") -> tuple:
    """Mint a throwaway self-signed cert with the system openssl binary
    (no key material is committed to the repo)."""
    cert = os.path.join(directory, "dc.crt")
    key = os.path.join(directory, "dc.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "2", "-subj",
         f"/CN={cn}", "-addext", f"subjectAltName=DNS:{cn},IP:127.0.0.1"],
        check=True, capture_output=True)
    return cert, key


class _MtprotoConn:
    """Wire adapter: the DCT session rides MTProto 2.0 encrypted messages
    (`mtproto_wire`) carrying TL API constructor frames (`tl_api`) instead
    of DCT-v1 length-prefixed JSON.  Duck-types the socket surface the
    session loop / watchdog touch; the loop keeps speaking JSON — this
    adapter translates at the wire:

    - inbound: TL function frame -> JSON request (typed constructors or
      the declared dct.rawRequest fallback), remembering the MTProto
      msg_id;
    - outbound: the FIRST send after a recv answers that request as
      ``rpc_result(req_msg_id, ...)`` (real MTProto's correlation);
      subsequent sends are unsolicited ``dct.update`` pushes — exactly
      the reply-then-push order the auth ladder emits."""

    def __init__(self, sock, rsa):
        from .mtproto_wire import MtprotoServerSession

        self._sock = sock
        # Constructor runs the full auth-key handshake; the caller's auth
        # deadline (socket timeout + watchdog) bounds it.
        self._sess = MtprotoServerSession(sock, rsa)
        self._last_req_msg_id: Optional[int] = None
        self._replied = True

    def send_payload(self, payload: bytes) -> None:
        from . import tl_api

        obj = json.loads(payload.decode("utf-8"))
        if not self._replied and self._last_req_msg_id is not None:
            frame = tl_api.serialize_result(obj, self._last_req_msg_id)
            self._replied = True
        else:
            frame = tl_api.serialize_update(obj)
        self._sess.send(frame)

    def recv_payload(self) -> Optional[bytes]:
        from . import tl_api

        raw = self._sess.recv()
        if raw is None:
            return None
        req = tl_api.deserialize_request(raw)
        self._last_req_msg_id = self._sess.session.last_recv_msg_id
        self._replied = False
        return json.dumps(req).encode("utf-8")

    def settimeout(self, t) -> None:
        self._sock.settimeout(t)

    def shutdown(self, how) -> None:
        self._sock.shutdown(how)

    def close(self) -> None:
        self._sock.close()

    def fileno(self) -> int:
        return self._sock.fileno()


def load_accounts(path: str) -> Dict[str, Dict[str, Any]]:
    """Accounts JSON → {phone_number: {"code": ..., "password": ...}}.

    Accepts ``{"accounts": [{"phone_number","code","password"}...]}`` or a
    bare list.  The file is the gateway-side registry that GenCode-minted
    credentials.json files (`clients/native.generate_pcode`) authenticate
    against."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    entries = doc.get("accounts", doc) if isinstance(doc, dict) else doc
    if not isinstance(entries, list):
        raise ValueError(f"accounts file {path}: expected a list")
    out: Dict[str, Dict[str, str]] = {}
    for e in entries:
        phone = str(e.get("phone_number", "")).strip()
        if not phone:
            raise ValueError(f"accounts file {path}: entry missing "
                             f"phone_number: {e}")
        out[phone] = {"code": str(e.get("code", "")),
                      "password": str(e.get("password", ""))}
        if "dc_id" in e:
            # Home DC: a gateway with a different dc_id answers this
            # account's phone step with 303 PHONE_MIGRATE_<dc_id>.
            out[phone]["dc_id"] = int(e["dc_id"])
    return out


class DcGateway:
    """Socket server speaking DCT-v1; one thread per connection.

    ``accounts`` maps phone → {code, password}; empty means any phone is
    accepted against ``expected_code``/``expected_password`` (the
    single-tenant / test configuration).  ``seed_source`` + ``store_root``
    give every session its own materialized store copy; ``seed_json``
    serves an inline store instead (tests, tiny deployments).
    """

    def __init__(self, seed_json: str = "", expected_code: str = "13579",
                 expected_password: str = "", tls: bool = False,
                 host: str = "127.0.0.1", port: int = 0,
                 lib_path: Optional[str] = None,
                 accounts: Optional[Dict[str, Dict[str, Any]]] = None,
                 seed_source: str = "", store_root: str = "",
                 tls_cert: str = "", tls_key: str = "",
                 auth_timeout_s: float = DEFAULT_AUTH_TIMEOUT_S,
                 address_file: str = "", wire: str = "dct",
                 max_connections: int = DEFAULT_MAX_CONNECTIONS,
                 flood: Optional[Dict[str, Dict[str, Any]]] = None,
                 dc_id: int = 1):
        self.seed_json = seed_json or '{"channels": []}'
        self.expected_code = expected_code
        self.expected_password = expected_password
        self.accounts = dict(accounts or {})
        self.seed_source = seed_source
        self.store_root = store_root
        self.auth_timeout_s = auth_timeout_s
        self._lib_path = lib_path
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self.host = host
        self._ssl_ctx = None
        self._owned_cert_dir: Optional[str] = None
        if tls or tls_cert:
            if not tls_cert:
                # Bootstrap path: mint into the store root (persistent) or
                # a tempdir; production passes real cert/key paths.
                import tempfile

                if store_root:
                    cert_dir = os.path.join(store_root, "tls")
                    os.makedirs(cert_dir, exist_ok=True)
                else:
                    self._owned_cert_dir = tempfile.mkdtemp(prefix="dct-dc-")
                    cert_dir = self._owned_cert_dir
                tls_cert = os.path.join(cert_dir, "dc.crt")
                tls_key = os.path.join(cert_dir, "dc.key")
                if not (os.path.exists(tls_cert) and os.path.exists(tls_key)):
                    make_self_signed_cert(cert_dir)
            self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            self._ssl_ctx.load_cert_chain(tls_cert, tls_key)
        self.tls_cert = tls_cert
        if wire not in ("dct", "mtproto"):
            raise ValueError(f"unknown gateway wire {wire!r}")
        self.wire = wire
        self._rsa = None
        self.pubkey_file = ""
        if wire == "mtproto":
            # The gateway's RSA key plays the role of Telegram's DC keys:
            # clients load the public half {n, e} (written next to the
            # address file / store root), the private half stays here.
            from . import mtproto_wire as mtp

            key_path = (os.path.join(store_root, "mtproto_rsa.json")
                        if store_root else "")
            if key_path and os.path.exists(key_path):
                with open(key_path, "r", encoding="utf-8") as f:
                    d = json.load(f)
                self._rsa = mtp.RsaKey(n=int(d["n"], 16), e=int(d["e"]),
                                       d=int(d["d"], 16))
            else:
                self._rsa = mtp.generate_rsa_key()
                if key_path:
                    os.makedirs(store_root, exist_ok=True)
                    tmp = key_path + ".tmp"
                    # 0600 from birth — the private exponent must never
                    # be world-readable, even transiently.
                    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                                 0o600)
                    with os.fdopen(fd, "w", encoding="utf-8") as f:
                        json.dump({"n": hex(self._rsa.n), "e": self._rsa.e,
                                   "d": hex(self._rsa.d)}, f)
                    os.replace(tmp, key_path)
            if address_file:
                self.pubkey_file = address_file + ".pubkey"
            elif store_root:
                self.pubkey_file = os.path.join(store_root,
                                                "mtproto.pubkey.json")
            else:
                # No operator-chosen location: own a tempdir (cleaned up
                # in close(), like the ephemeral-TLS certs) instead of
                # dropping an artifact into the process CWD.
                import tempfile

                if self._owned_cert_dir is None:
                    self._owned_cert_dir = tempfile.mkdtemp(
                        prefix="dct-dc-")
                self.pubkey_file = os.path.join(self._owned_cert_dir,
                                                "mtproto.pubkey.json")
            mtp.save_pubkey(self.pubkey_file, self._rsa)
        self._stop = threading.Event()
        self._threads: list = []
        self._live_conns: list = []
        if max_connections < 0:
            raise ValueError("max_connections must be >= 0 (0 = unlimited)")
        self.max_connections = max_connections
        self._stats_mu = threading.Lock()
        self.connections = 0
        self.rejected_connections = 0
        self.auth_successes = 0
        self.auth_failures = 0
        self.requests_served = 0
        self.active_sessions = 0
        self._conn_seq = 0
        # Per-account FLOOD_WAIT emulation (Telegram's rate discipline,
        # `crawl/runner.go:55-97`): phone -> {wait_s, after_requests,
        # methods}.  Counted per ACCOUNT across connections, like Telegram.
        self._flood_mu = threading.Lock()
        self._flood: Dict[str, Dict[str, Any]] = {
            p: dict(rule) for p, rule in (flood or {}).items()}
        self.flood_rejections = 0
        # This gateway's DC id: accounts homed elsewhere (an account entry
        # with a different "dc_id") get Telegram's 303 PHONE_MIGRATE_X
        # redirect at the phone-number step instead of service here.
        self.dc_id = int(dc_id)
        self.migrations_issued = 0
        if address_file:
            tmp = address_file + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(self.address)
            os.replace(tmp, address_file)  # atomic: readers never see ""
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="dct-gw-accept")

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "DcGateway":
        self._accept_thread.start()
        logger.info("dc gateway listening on %s (tls=%s, accounts=%d)",
                    self.address, self._ssl_ctx is not None,
                    len(self.accounts))
        return self

    def close(self) -> None:
        self._stop.set()
        # shutdown() BEFORE close(): a close alone doesn't wake a thread
        # blocked in accept() — the in-flight syscall pins the open file
        # description and the port stays in LISTEN forever (no rebind on
        # restart).  shutdown aborts the accept immediately.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # not listening yet / already closed
        try:
            self._sock.close()
        except OSError:
            pass
        if self._accept_thread.is_alive():
            self._accept_thread.join(timeout=2.0)
        for conn in self._live_conns:  # kill live sessions, not just accept
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)
        if self._owned_cert_dir is not None:
            import shutil

            shutil.rmtree(self._owned_cert_dir, ignore_errors=True)

    def status(self) -> Dict[str, Any]:
        """GetStatus-shaped map for the metrics endpoint (parity with the
        reference's orchestrator/worker status maps)."""
        with self._stats_mu:
            return {
                "component": "dc-gateway",
                "address": self.address,
                "wire": self.wire,
                "tls": self._ssl_ctx is not None,
                "accounts": len(self.accounts),
                "connections_total": self.connections,
                "rejected_connections": self.rejected_connections,
                "active_sessions": self.active_sessions,
                "auth_successes": self.auth_successes,
                "auth_failures": self.auth_failures,
                "requests_served": self.requests_served,
                "flood_rejections": self.flood_rejections,
                "dc_id": self.dc_id,
                "migrations_issued": self.migrations_issued,
            }

    # -- internals ---------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return  # socket closed
            with self._stats_mu:
                self.connections += 1
                self._conn_seq += 1
                seq = self._conn_seq
                # Reap finished sessions: a long-running gateway serving a
                # reconnecting pool must not grow these lists without bound.
                self._threads = [t for t in self._threads if t.is_alive()]
                self._live_conns = [c for c in self._live_conns
                                    if c.fileno() != -1]
                # Connection cap: the auth watchdog bounds each thread's
                # LIFETIME, this bounds their COUNT — without it a connect
                # flood pins max_connections*auth_timeout thread-seconds
                # of unauthenticated work per wave.
                if (self.max_connections > 0
                        and len(self._threads) >= self.max_connections):
                    self.rejected_connections += 1
                    try:
                        conn.close()
                    except OSError:
                        pass
                    continue
                self._live_conns.append(conn)
            t = threading.Thread(target=self._serve_conn,
                                 args=(conn, addr, seq), daemon=True,
                                 name=f"dct-gw-{seq}")
            t.start()
            with self._stats_mu:
                self._threads.append(t)

    def _make_engine(self, seq: int) -> NativeTelegramClient:
        """Per-session offline engine (per-connection store isolation, like
        the reference's per-connection TDLib databases)."""
        if self.seed_source:
            seed = acquire_seed_db(self.seed_source,
                                   self.store_root or ".dct-gateway/stores",
                                   f"gw-{seq}")
            return NativeTelegramClient(seed_db=seed,
                                        lib_path=self._lib_path,
                                        conn_id=f"gw-{seq}")
        return NativeTelegramClient(seed_json=self.seed_json,
                                    lib_path=self._lib_path,
                                    conn_id=f"gw-{seq}")

    def _serve_conn(self, conn: socket.socket, addr, seq: int) -> None:
        engine = None
        in_session = False
        # The auth deadline is ABSOLUTE over TLS handshake + the whole
        # ladder.  Per-recv timeouts alone cannot bound it — a client can
        # drip junk frames (each recv resets the idle window), drip bytes
        # WITHIN one frame, or drip the TLS handshake itself — so a
        # per-connection watchdog timer hard-stops the socket at the
        # deadline.  shutdown() (not close()) unblocks any in-flight recv
        # without freeing the fd, which could otherwise race a reused fd
        # number on another thread.
        holder = {"sock": conn, "ready": False}

        def _auth_kill():
            if not holder["ready"]:
                try:
                    holder["sock"].shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

        watchdog = threading.Timer(self.auth_timeout_s, _auth_kill)
        watchdog.daemon = True
        watchdog.start()
        deadline = time.monotonic() + self.auth_timeout_s
        try:
            conn.settimeout(self.auth_timeout_s)
            if self._ssl_ctx is not None:
                conn = self._ssl_ctx.wrap_socket(conn, server_side=True)
                # wrap_socket() detaches the raw socket (fileno -1): track
                # the wrapped one or close()/the watchdog can't reach this
                # session.  If the watchdog fired mid-wrap it only saw the
                # detached raw socket — honor the deadline here instead.
                holder["sock"] = conn
                with self._stats_mu:
                    self._live_conns.append(conn)
                if time.monotonic() >= deadline:
                    raise socket.timeout("auth deadline")
            if self.wire == "mtproto":
                # MTProto 2.0 envelope: auth-key handshake now (bounded by
                # the same watchdog/deadline), JSON session inside
                # encrypted messages after.
                conn = _MtprotoConn(conn, self._rsa)
            # 1. Handshake frame first, always.
            conn.settimeout(max(0.001, deadline - time.monotonic()))
            first = recv_frame(conn)
            if first is None:
                return
            hello = json.loads(first.decode("utf-8"))
            if hello.get("@type") != "handshake":
                send_frame(conn, self._err(400, "handshake expected"))
                return
            send_frame(conn, json.dumps({
                "@type": "handshake_ack",
                "session_id": f"sess-{seq}",
                "transport_version": 1}).encode("utf-8"))

            # 2. Auth ladder, server-driven via updates.
            state = "waitTdlibParameters"
            account: Optional[Dict[str, str]] = None
            self._push_auth(conn, "authorizationStateWaitTdlibParameters")
            while not self._stop.is_set():
                if state != "ready":
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise socket.timeout("auth deadline")
                    conn.settimeout(remaining)
                raw = recv_frame(conn)
                if raw is None:
                    return
                req = json.loads(raw.decode("utf-8"))
                rtype = req.get("@type", "")
                if state != "ready":
                    state, account = self._auth_step(conn, state, account,
                                                     rtype, req)
                    if state == "ready":
                        # 3. Ready: the session owns an engine; auth no
                        # longer bounds the read timeout.
                        holder["ready"] = True
                        watchdog.cancel()
                        conn.settimeout(None)
                        try:
                            engine = self._make_engine(seq)
                        except Exception as e:  # store unreadable, OOM, …
                            logger.error("gateway conn %s: engine start "
                                         "failed: %s", addr, e)
                            send_frame(conn, self._err(
                                500, f"INTERNAL: store unavailable: {e}"))
                            return
                        in_session = True
                        with self._stats_mu:
                            self.auth_successes += 1
                            self.active_sessions += 1
                    continue
                if rtype == "close":
                    self._reply(conn, req, {"@type": "ok"})
                    return
                flooded = self._flood_check(
                    (account or {}).get("_phone", ""), rtype)
                if flooded is not None:
                    self._reply(conn, req, flooded)
                    continue
                resp = json.loads(engine.execute_raw(json.dumps(req)))
                with self._stats_mu:
                    self.requests_served += 1
                send_frame(conn, json.dumps(resp).encode("utf-8"))
        except socket.timeout:
            logger.info("gateway conn %s: auth deadline (%.0fs) expired",
                        addr, self.auth_timeout_s)
        except (ValueError, ssl.SSLError, OSError) as e:
            logger.info("gateway connection %s dropped: %s", addr, e)
        finally:
            watchdog.cancel()
            if engine is not None:
                engine.close()
            if in_session:
                with self._stats_mu:
                    self.active_sessions -= 1
            try:
                conn.close()
            except OSError:
                pass

    def inject_flood(self, phone: str, wait_s: int,
                     after_requests: int = 0,
                     methods: Optional[list] = None) -> None:
        """Arm (or re-arm) Telegram-style rate discipline for one account:
        after ``after_requests`` more MATCHING requests, matching requests
        get ``429 Too Many Requests: retry after wait_s`` instead of the
        engine.  ``methods`` limits the rule to specific @type values
        (Telegram rate-limits per method; SearchPublicChat is the
        flood-prone one the reference retires on,
        `crawl/runner.go:1333-1337`); None floods every request."""
        with self._flood_mu:
            self._flood[phone] = {
                "wait_s": int(wait_s),
                "after_requests": max(0, int(after_requests)),
                "methods": list(methods) if methods else None,
                "_count": 0,
            }

    def _flood_check(self, phone: str,
                     rtype: str) -> Optional[Dict[str, Any]]:
        """Count the request against the account's rule; return the
        FLOOD_WAIT error body when this request is over quota.  The wording
        matches what `clients/errors.py` / the native client parse into
        FloodWaitError."""
        if not phone:
            return None
        with self._flood_mu:
            rule = self._flood.get(phone)
            if rule is None:
                return None
            methods = rule.get("methods")
            if methods and rtype not in methods:
                return None
            rule["_count"] = rule.get("_count", 0) + 1
            if rule["_count"] <= int(rule.get("after_requests", 0)):
                return None
            with self._stats_mu:
                self.flood_rejections += 1
            return self._err_obj(
                429, f"Too Many Requests: retry after {rule['wait_s']}")

    def _credentials_for(self, phone: str) -> Optional[Dict[str, str]]:
        """Resolve the account a phone number authenticates against; None
        = unknown phone (rejected when an accounts table is configured)."""
        if self.accounts:
            return self.accounts.get(phone)
        return {"code": self.expected_code,
                "password": self.expected_password}

    def _auth_step(self, conn, state: str, account: Optional[Dict[str, str]],
                   rtype: str, req: Dict[str, Any]):
        if rtype == "setTdlibParameters" and state == "waitTdlibParameters":
            self._reply(conn, req, {"@type": "ok"})
            self._push_auth(conn, "authorizationStateWaitPhoneNumber")
            return "waitPhoneNumber", account
        if rtype == "setAuthenticationPhoneNumber" and \
                state == "waitPhoneNumber":
            phone = req.get("phone_number", "")
            account = self._credentials_for(phone) if phone else None
            if account is None:
                self._count_auth_failure()
                self._reply(conn, req,
                            self._err_obj(400, "PHONE_NUMBER_INVALID"))
                return state, None
            home_dc = int(account.get("dc_id", self.dc_id))
            if home_dc != self.dc_id:
                # Telegram's DC redirect: the account lives on another DC —
                # 303 PHONE_MIGRATE_X; the client reconnects there and
                # restarts the ladder (TDLib does this internally).
                with self._stats_mu:
                    self.migrations_issued += 1
                self._reply(conn, req, self._err_obj(
                    303, f"PHONE_MIGRATE_{home_dc}"))
                return state, None
            # Carry the phone with the session (copy — never mutate the
            # accounts table): the flood emulation is per-account.
            account = dict(account)
            account["_phone"] = phone
            self._reply(conn, req, {"@type": "ok"})
            self._push_auth(conn, "authorizationStateWaitCode")
            return "waitCode", account
        if rtype == "checkAuthenticationCode" and state == "waitCode":
            if req.get("code") != account["code"]:
                self._count_auth_failure()
                self._reply(conn, req,
                            self._err_obj(400, "PHONE_CODE_INVALID"))
                return state, account
            self._reply(conn, req, {"@type": "ok"})
            if account["password"]:
                self._push_auth(conn, "authorizationStateWaitPassword")
                return "waitPassword", account
            self._push_auth(conn, "authorizationStateReady")
            return "ready", account
        if rtype == "checkAuthenticationPassword" and \
                state == "waitPassword":
            if req.get("password") != account["password"]:
                self._count_auth_failure()
                self._reply(conn, req,
                            self._err_obj(400, "PASSWORD_HASH_INVALID"))
                return state, account
            self._reply(conn, req, {"@type": "ok"})
            self._push_auth(conn, "authorizationStateReady")
            return "ready", account
        self._reply(conn, req, self._err_obj(
            401, f"UNAUTHORIZED: {rtype} not valid in state {state}"))
        return state, account

    def _count_auth_failure(self) -> None:
        with self._stats_mu:
            self.auth_failures += 1

    def _push_auth(self, conn, state: str) -> None:
        send_frame(conn, json.dumps({
            "@type": "updateAuthorizationState",
            "authorization_state": {"@type": state}}).encode("utf-8"))

    @staticmethod
    def _err_obj(code: int, message: str) -> Dict[str, Any]:
        return {"@type": "error", "code": code, "message": message}

    def _err(self, code: int, message: str) -> bytes:
        return json.dumps(self._err_obj(code, message)).encode("utf-8")

    @staticmethod
    def _reply(conn, req: Dict[str, Any], body: Dict[str, Any]) -> None:
        if "@extra" in req:
            body = dict(body)
            body["@extra"] = req["@extra"]
        send_frame(conn, json.dumps(body).encode("utf-8"))
