"""File-combining pipeline (reference `chunk/main.go`)."""

from .chunker import Chunker, FileEntry, ProcessedMap

__all__ = ["Chunker", "FileEntry", "ProcessedMap"]
