"""File-combining pipeline to cut blob-storage operation counts.

Parity with the reference's `chunk/main.go` (680 LoC):
- multi-stage pipeline: recovery scanner, directory watcher, batcher,
  consumer (`:105-150`); the reference's fsnotify watcher + event processor
  pair becomes one polling scanner thread here (no inotify dependency, same
  at-least-once semantics since the recovery scanner re-lists the dir anyway)
- batch by trigger size (170 MiB) with hard cap (200 MiB) + flush timeout
  (`:84-103,292-347`)
- double-buffered seen-map with upload-gated rotation so a file can't be
  evicted from both maps before it was uploaded (`processedMap`, `:46-70,
  433-482`)
- combine -> upload via `sm.upload_combined_file` with one 30 s retry ->
  delete sources (`:349-421,510-530`)
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

logger = logging.getLogger("dct.chunk")

DEFAULT_TRIGGER_SIZE = 170 * 1024 * 1024  # MiB (`main.go:800` flag default)
DEFAULT_HARD_CAP = 200 * 1024 * 1024
DEFAULT_BATCH_TIMEOUT_S = 300.0  # 5 min (`chunk/main.go:95`)
ROTATE_THRESHOLD = 100_000  # entries before map rotation (`main.go:477-482`)
UPLOAD_RETRY_DELAY_S = 30.0


@dataclass
class FileEntry:
    path: str
    size: int


class ProcessedMap:
    """Double-buffered dedup set: rotation drops the oldest generation so
    memory stays bounded; `seen` consults both (`chunk/main.go:63-70`)."""

    def __init__(self):
        self._lock = threading.RLock()
        self.current: set = set()
        self.previous: set = set()

    def seen(self, path: str) -> bool:
        with self._lock:
            return path in self.current or path in self.previous

    def mark(self, path: str) -> None:
        with self._lock:
            self.current.add(path)

    def claim(self, path: str) -> bool:
        """Atomic seen-check + mark: True exactly once per path.  Two
        threads scanning concurrently (watcher poll vs a forced scan_now)
        can otherwise both pass seen() and double-enqueue the file."""
        with self._lock:
            if path in self.current or path in self.previous:
                return False
            self.current.add(path)
            return True

    def rotate(self) -> None:
        with self._lock:
            self.previous = self.current
            self.current = set()

    def __len__(self) -> int:
        with self._lock:
            return len(self.current) + len(self.previous)


class Chunker:
    """Watch a directory of JSONL shards, combine them into ~trigger-size
    files, upload, delete sources."""

    def __init__(self, sm, temp_dir: str, watch_dir: str, combine_dir: str,
                 trigger_size: int = DEFAULT_TRIGGER_SIZE,
                 hard_cap: int = DEFAULT_HARD_CAP,
                 batch_timeout_s: float = DEFAULT_BATCH_TIMEOUT_S,
                 scan_interval_s: float = 1.0,
                 recovery_interval_s: float = 60.0):
        self.sm = sm
        self.temp_dir = temp_dir
        self.watch_dir = watch_dir
        self.combine_dir = combine_dir
        self.trigger_size = trigger_size
        self.hard_cap = hard_cap
        self.batch_timeout_s = batch_timeout_s
        self.scan_interval_s = scan_interval_s
        self.recovery_interval_s = recovery_interval_s

        self._file_q: "queue.Queue[Optional[FileEntry]]" = queue.Queue(10000)
        self._jobs_q: "queue.Queue[Optional[List[FileEntry]]]" = \
            queue.Queue(100)
        self.processed = ProcessedMap()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.total_upload_size = 0
        self.posts_uploaded = 0
        # Rotation guards (`chunk/main.go:48-51`): second rotation gated on a
        # successful upload since the first.
        self._last_rotation: float = 0.0
        self._last_upload: float = 0.0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        for d in (self.watch_dir, self.combine_dir, self.temp_dir):
            os.makedirs(d, exist_ok=True)
        # Recovery runs ONCE before the consumer exists and once after the
        # drain in shutdown() — never concurrently with the consumer, which
        # writes into combine_dir (`chunk/main.go` VerifyCleanup :523-536
        # likewise runs recovery only after the pipeline has drained).
        self.recover_combine_dir()
        for target, name in ((self._watch_loop, "chunk-watch"),
                             (self._batch_loop, "chunk-batch"),
                             (self._consume_loop, "chunk-consume")):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        logger.info("chunker started", extra={
            "trigger_mb": self.trigger_size // (1024 * 1024),
            "hardcap_mb": self.hard_cap // (1024 * 1024)})

    def scan_now(self) -> int:
        """Force one synchronous watch-dir scan (callers that just wrote
        final shards use this before shutdown so nothing waits on the
        polling interval).  Returns newly-enqueued file count."""
        return self._scan_once()

    def shutdown(self, timeout_s: float = 30.0) -> None:
        """Graceful drain: stop watching, flush the partial batch, finish
        uploads (`chunk/main.go:160-167`)."""
        if self._stop.is_set():
            return
        self._stop.set()
        self._file_q.put(None)  # sentinel flushes batcher
        deadline = time.monotonic() + timeout_s
        for t in self._threads:
            t.join(timeout=max(0.1, deadline - time.monotonic()))
        self._threads.clear()
        # Post-drain recovery: the consumer is gone, so any combined_* file
        # still present was stranded by a failed upload this run.
        self.recover_combine_dir()

    # -- stage 1+2: polling watcher (fsnotify + event processor) -----------
    def _scan_once(self) -> int:
        found = 0
        try:
            names = os.listdir(self.watch_dir)
        except OSError as e:
            logger.error("watch dir scan failed: %s", e)
            return 0
        for name in names:
            if not name.endswith(".jsonl"):
                continue
            path = os.path.join(self.watch_dir, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            if not self.processed.claim(path):
                continue
            if len(self.processed) >= ROTATE_THRESHOLD and \
                    self._may_rotate():
                self.processed.rotate()
                self._last_rotation = time.monotonic()
            self._file_q.put(FileEntry(path=path, size=size))
            found += 1
        return found

    def _may_rotate(self) -> bool:
        """`chunk/main.go:477-482`: second rotation requires an upload since
        the first, so unuploaded entries can't be forgotten twice."""
        return self._last_rotation == 0.0 or \
            self._last_upload > self._last_rotation

    def _watch_loop(self) -> None:
        while not self._stop.is_set():
            self._scan_once()
            self._stop.wait(self.scan_interval_s)

    # -- recovery scanner (`chunk/main.go:238-290,542-658`) ----------------
    def recover_combine_dir(self) -> None:
        """Re-upload combined files stranded by a crash before upload.

        Only called while no consumer is running (startup / post-drain), and
        only matches final ``combined_*`` names — in-progress output is
        written under a ``.tmp`` suffix and renamed on completion, so a
        half-written blob can never be uploaded.
        """
        try:
            names = os.listdir(self.combine_dir)
        except OSError:
            return
        for name in names:
            # Final names only: .tmp suffixes are in-progress writes.
            if not name.startswith("combined_") or \
                    not name.endswith(".jsonl"):
                continue
            path = os.path.join(self.combine_dir, name)
            try:
                self.sm.upload_combined_file(path)
                os.remove(path)
                logger.info("recovered stranded combined file",
                            extra={"path": path})
            except Exception as e:
                logger.warning("failed to recover combined file %s: %s",
                               path, e)

    # -- stage 3: batcher (`chunk/main.go:292-347`) ------------------------
    def _batch_loop(self) -> None:
        files: List[FileEntry] = []
        size = 0
        last_flush = time.monotonic()

        def flush():
            nonlocal files, size, last_flush
            if files:
                self.total_upload_size += size
                self.posts_uploaded += len(files)
                self._jobs_q.put(list(files))
                files = []
                size = 0
            last_flush = time.monotonic()

        while True:
            try:
                entry = self._file_q.get(timeout=0.25)
            except queue.Empty:
                if files and time.monotonic() - last_flush >= \
                        self.batch_timeout_s:
                    logger.info("batch timeout flush",
                                extra={"log_tag": "chunk_pb"})
                    flush()
                if self._stop.is_set():
                    flush()
                    self._jobs_q.put(None)
                    return
                continue
            if entry is None:  # shutdown sentinel
                flush()
                self._jobs_q.put(None)
                return
            if entry.size > self.hard_cap:
                # Undeliverable: delete (`main.go:316-322`).
                logger.warning("file exceeds hard cap, deleting", extra={
                    "file": entry.path, "bytes": entry.size,
                    "log_tag": "chunk_pb"})
                try:
                    os.remove(entry.path)
                except OSError as e:
                    logger.error("failed to remove oversize file: %s", e)
                continue
            if size > 0 and size + entry.size > self.hard_cap:
                logger.info("hard cap forced flush",
                            extra={"log_tag": "chunk_pb"})
                flush()
            if not files:
                # Timeout counts from when the batch STARTED, not from the
                # previous flush — else the first file after an idle gap
                # longer than the timeout flushes alone immediately.
                last_flush = time.monotonic()
            files.append(entry)
            size += entry.size
            if size >= self.trigger_size:
                flush()

    # -- stage 4: consumer (`chunk/main.go:349-421`) -----------------------
    def _consume_loop(self) -> None:
        while True:
            batch = self._jobs_q.get()
            if batch is None:
                logger.info("all batches uploaded",
                            extra={"log_tag": "chunk_cb"})
                return
            try:
                combined = self.combine_files(batch)
            except Exception as e:
                logger.error("failed to combine batch, files not deleted: %s",
                             e, extra={"log_tag": "chunk_cb"})
                continue
            try:
                self.sm.upload_combined_file(combined)
            except Exception as e:
                logger.error("failed to upload combined file, retrying "
                             "in %ss: %s", UPLOAD_RETRY_DELAY_S, e)
                if self._stop.wait(UPLOAD_RETRY_DELAY_S):
                    # Shutting down: leave the combined file for the
                    # recovery scanner of the next run.
                    continue
                try:
                    self.sm.upload_combined_file(combined)
                except Exception as e2:
                    logger.error("retry failed to upload combined file: %s",
                                 e2)
                    continue
            self._last_upload = time.monotonic()
            self._cleanup_after_upload(batch, combined)

    def combine_files(self, batch: List[FileEntry]) -> str:
        """`chunk/main.go:386-421`."""
        out_path = os.path.join(self.combine_dir,
                                f"combined_{time.time_ns()}.jsonl")
        # Write under a .tmp suffix and rename only when complete (same dir,
        # so the rename is atomic): recovery matches combined_* and can never
        # see a truncated file.
        tmp_path = out_path + ".tmp"
        try:
            with open(tmp_path, "wb") as out:
                for entry in batch:
                    try:
                        current = os.path.getsize(entry.path)
                        if current != entry.size:
                            logger.error("file size changed before combining",
                                         extra={"file": entry.path,
                                                "initial": entry.size,
                                                "current": current})
                    except OSError:
                        pass
                    with open(entry.path, "rb") as f:
                        while True:
                            chunk = f.read(1 << 20)
                            if not chunk:
                                break
                            out.write(chunk)
            os.rename(tmp_path, out_path)
        except Exception:
            try:
                os.remove(tmp_path)
            except OSError:
                pass
            raise
        return out_path

    def _cleanup_after_upload(self, batch: List[FileEntry],
                              combined: str) -> None:
        """`chunk/main.go:510-530`."""
        for entry in batch:
            try:
                os.remove(entry.path)
            except OSError as e:
                logger.warning("failed to delete source %s: %s",
                               entry.path, e)
        try:
            os.remove(combined)
        except OSError as e:
            logger.warning("failed to delete combined %s: %s", combined, e)
