"""Online spherical mini-batch k-means as jitted JAX on the serving mesh.

The device half of BASELINE config #5 (snowball crawl -> E5 embed ->
distributed clustering), in the shape of Sculley's web-scale mini-batch
k-means (WWW 2010) adapted to the serving stack: embeddings stream in as
mini-batches, each step is ONE compiled program per row-count bucket —
assignment is a ``[B, D] x [D, K]`` matmul on the MXU, the update a
one-hot einsum — and centroids fold with the exact per-center running
mean (Sculley's 1/n learning rate).  The math reuses
`models/clustering.py`'s kernels (`assign`/`update`/
`kmeans_plus_plus_init`), so the online step is provably the batch
Lloyd update applied to one mini-batch (pinned by
tests/test_cluster_serve.py's online-vs-batch parity).

Static shapes: mini-batches pad up to a fixed row bucket behind a row
mask (pad rows assign to the out-of-range id ``k``, whose one-hot is all
zeros — they touch neither sums nor counts), so serving dispatches one
compiled step per bucket, never per fill level.  Per-step FLOPs are
captured into the shared cost model as ``path="cluster"`` rows
(`utils/costmodel.kmeans_step_flops` analytic fallback) and every
dispatch feeds a rolling `EfficiencyMeter`, so `/costs` shows
MFU/goodput for the clustering programs exactly like the text and ASR
paths.

Mesh: pass the serving mesh (`inference.worker.build_serving_mesh`) and
each mini-batch's rows shard over the dp axis (`parallel.sharding.
shard_batch`) with centroids replicated — XLA inserts the cross-chip
psums for the one-hot sums/counts, the `models/clustering.fit_sharded`
recipe.  Buckets that don't divide the dp size fall back to replicated
dispatch (correct, just unsharded).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..models import clustering
from ..utils.costmodel import (
    CostModel,
    EfficiencyMeter,
    kmeans_step_flops,
)
from ..utils.metrics import REGISTRY, MetricsRegistry

logger = logging.getLogger("dct.cluster.engine")


@dataclass
class ClusterEngineConfig:
    """Knobs for the online k-means engine (the `cluster:` config block)."""

    k: int = 16
    # Row-count buckets (ascending): a mini-batch pads to the smallest
    # bucket that fits; oversized groups chunk by the largest.  One
    # compiled step per bucket — the engine's whole program set.
    buckets: Tuple[int, ...] = (64, 256)
    # Spherical k-means: rows and centroids L2-normalize, so assignment
    # is cosine similarity — the right metric for E5-style embeddings.
    spherical: bool = True
    seed: int = 0
    # Rolling per-step mean-inertia history (the /clusters trend the
    # gate's max_inertia_growth judges).
    inertia_window: int = 256

    def validate(self) -> None:
        if self.k <= 0:
            raise ValueError("cluster k must be positive")
        if not self.buckets or any(int(b) <= 0 for b in self.buckets):
            raise ValueError("cluster buckets must be positive ints")


class ClusterEngine:
    """Streaming mini-batch k-means state + its compiled step programs.

    Thread-safety: ``observe``/``state_dict``/``load_state``/``snapshot``
    serialize on one lock — the serving worker's feed loop is the only
    writer, the heartbeat/HTTP threads read.
    """

    def __init__(self, cfg: ClusterEngineConfig = ClusterEngineConfig(),
                 mesh=None, registry: MetricsRegistry = REGISTRY):
        cfg.validate()
        self.cfg = cfg
        self.mesh = mesh
        self.n_devices = getattr(mesh, "size", 1) if mesh is not None else 1
        self._lock = threading.RLock()
        self.dim: Optional[int] = None
        self.centroids = None           # [K, D] f32 device array
        self.counts = None              # [K] f32 device array
        self.step = 0
        self.vectors = 0
        self.resumed_from_step: Optional[int] = None
        self._steps: Dict[int, Any] = {}     # bucket -> jitted step fn
        self._inertia: "deque[float]" = deque(maxlen=cfg.inertia_window)
        self._buckets = tuple(sorted(int(b) for b in cfg.buckets))
        # Shared cost plumbing (`utils/costmodel.py`): path="cluster"
        # rows land next to text/asr on /costs, and the rolling meter
        # treats one embedding row as one "token" (vectors/s IS the
        # goodput unit for this path).
        self.costs = CostModel(registry=registry)
        # path="cluster": the gauges become labeled children so a text
        # engine sharing this registry (the gate rig) keeps its own
        # unlabeled mfu/goodput series instead of flapping between the
        # two meters' windows.
        self.meter = EfficiencyMeter(registry=registry,
                                     n_devices=self.n_devices,
                                     path="cluster")
        self.m_compile_miss = registry.counter(
            "tpu_engine_compile_cache_misses_total",
            "jit program builds by bucket and path (first-dispatch "
            "compiles)")

    # -- compiled step -----------------------------------------------------
    def _step_fn(self, bucket: int):
        import jax

        fn = self._steps.get(bucket)
        if fn is None:
            self.m_compile_miss.labels(bucket=str(bucket),
                                       path="cluster").inc()
            k = self.cfg.k
            spherical = self.cfg.spherical

            def step(centroids, counts, x, mask):
                import jax.numpy as jnp

                x = x.astype(jnp.float32)
                if spherical:
                    x = x / jnp.maximum(
                        jnp.linalg.norm(x, axis=1, keepdims=True), 1e-12)
                assigns = clustering.assign(x, centroids)
                # Pad rows assign out of range: one_hot(k, k) is a zero
                # row, so they contribute to neither sums nor counts.
                assigns = jnp.where(mask, assigns, k).astype(jnp.int32)
                sums, bcounts = clustering.update(x, assigns, k)
                new_counts = counts + bcounts
                # Exact per-center running mean — Sculley's 1/n
                # learning rate: c <- (n*c + sum) / (n + batch_n).
                fresh = (counts[:, None] * centroids + sums) \
                    / jnp.maximum(new_counts, 1.0)[:, None]
                new_centroids = jnp.where((bcounts > 0)[:, None], fresh,
                                          centroids)
                if spherical:
                    new_centroids = new_centroids / jnp.maximum(
                        jnp.linalg.norm(new_centroids, axis=1,
                                        keepdims=True), 1e-12)
                safe = jnp.clip(assigns, 0, k - 1)
                diff = x - new_centroids[safe]
                inertia = jnp.sum(
                    jnp.sum(diff * diff, axis=1) * mask.astype(jnp.float32))
                return new_centroids, new_counts, assigns, inertia

            fn = jax.jit(step)
            self._steps[bucket] = fn
        return fn

    def _bucket_for(self, rows: int) -> int:
        for b in self._buckets:
            if rows <= b:
                return b
        return self._buckets[-1]

    def _place(self, x, mask):
        """Shard the padded mini-batch over the mesh's dp axis (centroids
        stay replicated); single-device and non-divisible buckets pass
        through unsharded."""
        import jax.numpy as jnp

        arrs = (jnp.asarray(x), jnp.asarray(mask))
        if self.mesh is not None and self.n_devices > 1:
            from ..parallel.sharding import shard_batch

            arrs = shard_batch(arrs, self.mesh)
        return arrs

    # -- seeding -----------------------------------------------------------
    def _seed(self, x) -> None:
        """k-means++ over the first mini-batch's real rows (cycled when
        fewer than k — duplicates separate as the stream updates them)."""
        import jax
        import jax.numpy as jnp

        x = jnp.asarray(x, jnp.float32)
        if self.cfg.spherical:
            x = x / jnp.maximum(
                jnp.linalg.norm(x, axis=1, keepdims=True), 1e-12)
        centroids = clustering.kmeans_plus_plus_init(
            x, self.cfg.k, jax.random.PRNGKey(self.cfg.seed))
        if self.cfg.spherical:
            centroids = centroids / jnp.maximum(
                jnp.linalg.norm(centroids, axis=1, keepdims=True), 1e-12)
        with self._lock:  # re-entrant: observe() already holds it
            self.centroids = centroids
            self.counts = jnp.zeros((self.cfg.k,), jnp.float32)
        logger.info("cluster engine seeded: k=%d dim=%d from %d rows",
                    self.cfg.k, x.shape[1], x.shape[0])

    # -- public API --------------------------------------------------------
    def observe(self, vectors: Sequence[Sequence[float]]) -> List[int]:
        """Fold one mini-batch of embeddings into the model; returns the
        cluster assignment per input row (in input order).

        The first call fixes ``dim`` and seeds the centroids; later
        mini-batches whose dim differs raise (a mixed-model embedding
        stream is a deployment error, not something to average away).

        ATOMIC across bucket chunks: an oversized mini-batch dispatches
        several chunked steps against LOCAL state and commits only when
        every chunk succeeded — a device failure on chunk 2 leaves the
        model exactly as it was, so the caller's per-batch isolation
        retry cannot double-fold chunk 1's rows.  Device dispatch (and
        any first-call XLA compile) runs OUTSIDE the state lock, so
        snapshot/HTTP readers never block on a compile; the SINGLE
        writer contract (one feed loop per engine, `cluster/worker.py`)
        is what makes the read-modify-commit safe.
        """
        import numpy as np

        if not len(vectors):
            return []
        x_all = np.asarray(vectors, dtype=np.float32)
        if x_all.ndim != 2:
            raise ValueError(
                f"embeddings must be a [N, D] matrix, got shape "
                f"{x_all.shape}")
        with self._lock:
            if self.dim is None:
                self.dim = int(x_all.shape[1])
            elif int(x_all.shape[1]) != self.dim:
                raise ValueError(
                    f"embedding dim {x_all.shape[1]} != model dim "
                    f"{self.dim}")
            if self.centroids is None:
                self._seed(x_all)
            centroids, counts = self.centroids, self.counts
        out: List[int] = []
        inertias: List[float] = []
        steps = 0
        cap = self._buckets[-1]
        for off in range(0, x_all.shape[0], cap):
            chunk = x_all[off:off + cap]
            centroids, counts, assigns, inertia = self._dispatch_chunk(
                centroids, counts, chunk)
            out.extend(assigns)
            inertias.append(inertia / max(1, len(chunk)))
            steps += 1
        with self._lock:  # every chunk succeeded: commit atomically
            self.centroids, self.counts = centroids, counts
            self.step += steps
            self.vectors += int(x_all.shape[0])
            self._inertia.extend(inertias)
        return out

    def _dispatch_chunk(self, centroids, counts, x: "Any"):
        """One padded bucket step over explicit state; returns
        (new_centroids, new_counts, assignments, inertia) without
        touching self.* model state (the observe() commit does)."""
        import jax
        import numpy as np

        rows = int(x.shape[0])
        bucket = self._bucket_for(rows)
        padded = np.zeros((bucket, self.dim), dtype=np.float32)
        padded[:rows] = x
        mask = np.zeros((bucket,), dtype=np.float32)
        mask[:rows] = 1.0
        fn = self._step_fn(bucket)
        placed = self._place(padded, mask)
        t0 = time.perf_counter()
        new_centroids, new_counts, assigns, inertia = fn(
            centroids, counts, *placed)
        jax.block_until_ready(assigns)
        dt = time.perf_counter() - t0
        if not self.costs.has(bucket, "cluster"):
            self.costs.capture(
                bucket, "cluster",
                lambda: fn.lower(centroids, counts, *placed),
                kmeans_step_flops(self.cfg.k, self.dim or 0, bucket),
                batch=bucket, seq=self.dim or 0)
        self.meter.record(dt, self.costs.flops_for(
            bucket, "cluster",
            default=kmeans_step_flops(self.cfg.k, self.dim, bucket)),
            real_tokens=rows, slot_tokens=bucket)
        return (new_centroids, new_counts,
                [int(a) for a in np.asarray(assigns)[:rows]],
                float(inertia))

    def assign_only(self, vectors: Sequence[Sequence[float]]) -> List[int]:
        """Nearest-centroid assignment WITHOUT folding the vectors into
        the model — the redelivery path: a batch whose embeddings were
        already folded (the worker's folded-batch window) must still get
        assignments for its (re-)writeback, but updating the centroids a
        second time would double-count the rows.  Pure host numpy: this
        is the rare path, and a per-shape jit here would pay compile
        churn for nothing."""
        import numpy as np

        with self._lock:
            if self.centroids is None:
                raise ValueError("cluster model not seeded")
            c = np.asarray(self.centroids, dtype=np.float32)
        x = np.asarray(vectors, dtype=np.float32)
        if self.cfg.spherical:
            x = x / np.maximum(
                np.linalg.norm(x, axis=1, keepdims=True), 1e-12)
        scores = -2.0 * (x @ c.T) + np.sum(c * c, axis=1)[None, :]
        return [int(i) for i in np.argmin(scores, axis=1)]

    def warmup(self, dim: int) -> None:
        """Compile every bucket's step program against throwaway state so
        the first live mini-batches don't pay XLA compiles.  Model state
        is untouched: a warmup must never look like a seed (the
        crash-recovery gate proves centroids resume, not re-seed)."""
        import jax.numpy as jnp

        with self._lock:
            if self.centroids is not None and self.dim is not None:
                dim = self.dim  # compile against the LIVE shapes
            dummy_c = jnp.zeros((self.cfg.k, dim), jnp.float32)
            dummy_n = jnp.zeros((self.cfg.k,), jnp.float32)
            import jax

            for bucket in self._buckets:
                x = jnp.zeros((bucket, dim), jnp.float32)
                mask = jnp.ones((bucket,), jnp.float32)
                fn = self._step_fn(bucket)
                placed = self._place(x, mask)
                out = fn(dummy_c, dummy_n, *placed)
                jax.block_until_ready(out[2])
                if not self.costs.has(bucket, "cluster"):
                    self.costs.capture(
                        bucket, "cluster",
                        lambda fn=fn, placed=placed:
                        fn.lower(dummy_c, dummy_n, *placed),
                        kmeans_step_flops(self.cfg.k, dim, bucket),
                        batch=bucket, seq=dim)

    # -- checkpoint state --------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        """JSON-safe model state for atomic checkpointing through the
        state layer (`state/providers.py` save_json is tmp+rename)."""
        import numpy as np

        with self._lock:
            return {
                "schema": "dct-cluster-v1",
                "k": self.cfg.k,
                "dim": self.dim,
                "spherical": self.cfg.spherical,
                "step": self.step,
                "vectors": self.vectors,
                "centroids": np.asarray(self.centroids).tolist()
                if self.centroids is not None else None,
                "counts": np.asarray(self.counts).tolist()
                if self.counts is not None else None,
                "inertia_window": list(self._inertia),
            }

    def load_state(self, state: Dict[str, Any]) -> None:
        """Resume from a checkpoint written by ``state_dict`` — the
        crash-recovery path: a restarted worker continues the SAME model
        (``resumed_from_step``), it never re-seeds."""
        import jax.numpy as jnp

        if int(state.get("k") or 0) != self.cfg.k:
            raise ValueError(
                f"checkpoint k={state.get('k')} != configured k="
                f"{self.cfg.k}")
        if "spherical" in state \
                and bool(state["spherical"]) != self.cfg.spherical:
            # Geometry mismatch is as incompatible as a different k:
            # unnormalized euclidean updates against unit-sphere
            # centroids (or vice versa) degrade silently.
            raise ValueError(
                f"checkpoint spherical={state['spherical']} != "
                f"configured spherical={self.cfg.spherical}")
        with self._lock:
            self.dim = int(state["dim"]) if state.get("dim") else None
            if state.get("centroids") is not None:
                self.centroids = jnp.asarray(state["centroids"],
                                             jnp.float32)
                self.counts = jnp.asarray(state.get("counts") or
                                          [0.0] * self.cfg.k, jnp.float32)
            self.step = int(state.get("step") or 0)
            self.vectors = int(state.get("vectors") or 0)
            self._inertia.clear()
            self._inertia.extend(
                float(v) for v in state.get("inertia_window") or [])
            self.resumed_from_step = self.step

    # -- observability -----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The model half of the /clusters body (JSON-safe)."""
        import numpy as np

        with self._lock:
            sizes = [int(c) for c in np.asarray(self.counts)] \
                if self.counts is not None else []
            norms = [round(float(n), 6) for n in
                     np.linalg.norm(np.asarray(self.centroids), axis=1)] \
                if self.centroids is not None else []
            inertia = [round(v, 6) for v in self._inertia]
            return {
                "k": self.cfg.k,
                "dim": self.dim,
                "spherical": self.cfg.spherical,
                "buckets": list(self._buckets),
                "n_devices": self.n_devices,
                "step": self.step,
                "vectors": self.vectors,
                "seeded": self.centroids is not None,
                "sizes": sizes,
                "nonempty": sum(1 for s in sizes if s > 0),
                "centroid_norms": norms,
                "inertia": inertia,
                "inertia_per_vector": inertia[-1] if inertia else None,
                "resumed_from_step": self.resumed_from_step,
            }

    def underpopulated(self, min_fraction: float = 0.5) -> List[int]:
        """Cluster ids whose assignment share is under ``min_fraction``
        of the uniform share (1/k) — the "sparse corners of the embedding
        space" the cluster-guided frontier steers the crawl toward."""
        import numpy as np

        with self._lock:
            if self.counts is None or self.vectors <= 0:
                return []
            counts = np.asarray(self.counts)
            floor = min_fraction * self.vectors / self.cfg.k
            return [int(i) for i in range(self.cfg.k)
                    if counts[i] < floor]

    def compile_cache_stats(self) -> Dict[str, Any]:
        """Telemetry-heartbeat hook (`utils/telemetry.py` duck-typing):
        which bucket programs exist + cumulative first-dispatch misses."""
        misses: Dict[str, float] = {}
        total = 0.0
        for labels, value in self.m_compile_miss.series():
            if not labels or labels.get("path") != "cluster":
                continue
            misses[f"cluster:{labels.get('bucket', '?')}"] = value
            total += value
        return {"programs_cluster": sorted(self._steps),
                "misses_total": total, "misses": misses}

    def efficiency_snapshot(self) -> Dict[str, Any]:
        """Rolling MFU/goodput map for telemetry heartbeats; {} until the
        first mini-batch lands."""
        return self.meter.snapshot()

    def cost_snapshot(self) -> Dict[str, Any]:
        """The engine half of the /costs body (`set_costs_provider`)."""
        return {
            "model": f"kmeans-k{self.cfg.k}",
            "k": self.cfg.k,
            "dim": self.dim,
            "buckets": list(self._buckets),
            "n_devices": self.n_devices,
            "costs": self.costs.snapshot(),
            "efficiency": self.meter.snapshot(),
        }
