"""Cluster worker service: embedding-carrying result batches in,
cluster assignments + a live centroid model out.

The third serving worker (after the text `TPUWorker` and the ASR
`ASRWorker`), with the same loop discipline: subscribe (the
embedding-result topic), heartbeat with ``worker_type="cluster"``,
per-batch ack/poison isolation, queue-wait/batch-age spans joining the
shared SLO families, ``kill()``/``evaluate_slos()`` chaos seams, span
export on ``TOPIC_SPANS``.  What is new:

- the unit of work is a `RecordBatch` COMING BACK from the TPU worker on
  ``TOPIC_INFERENCE_RESULTS`` with an ``embedding`` per result row (the
  stream nothing consumed before this worker existed);
- "processing" is one online mini-batch k-means step on the
  `ClusterEngine` (`cluster/engine.py`), per-step FLOPs metered as
  ``path="cluster"`` on `/costs`;
- assignments write back idempotently (one atomically-written JSONL per
  batch_id under ``cluster/<crawl>/batches/`` — redeliveries overwrite,
  never duplicate: the embedding→assignment ledger the loadgen gate
  reconciles);
- centroids + counts + inertia checkpoint PERIODICALLY AND ATOMICALLY
  through the state layer (`provider.save_json` is tmp+rename), so a
  restarted worker RESUMES the model from the last checkpoint — proven
  by the ``kill-cluster-worker`` chaos scenario — instead of re-seeding;
- cluster state serves at ``/clusters`` (`utils.metrics.
  set_clusters_provider`) and typed `ClusterUpdateMessage`s on
  ``TOPIC_CLUSTERS`` feed the orchestrator's cluster-guided frontier
  prioritization (under-populated clusters pull their channels' frontier
  pages up to ``PRIORITY_HIGH``).
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..bus.codec import RecordBatch
from ..bus.messages import (
    MSG_HEARTBEAT,
    MSG_WORKER_STOPPING,
    TOPIC_CLUSTERS,
    TOPIC_INFERENCE_RESULTS,
    TOPIC_SPANS,
    TOPIC_WORKER_STATUS,
    ClusterUpdateMessage,
    SpanBatchMessage,
    StatusMessage,
    WORKER_BUSY,
    WORKER_IDLE,
    WORKER_OFFLINE,
)
from ..utils import flight, trace
from ..utils.metrics import (
    REGISTRY,
    MetricsRegistry,
    clear_clusters_provider,
    clear_costs_provider,
    clear_status_provider,
    serve_metrics,
    set_clusters_provider,
    set_costs_provider,
    set_status_provider,
)
from ..utils.occupancy import QueueDepthSampler
from ..utils.slo import SLOWatchdog, standard_slos
from ..utils.telemetry import TelemetryEmitter
from ..utils.timeseries import RegistrySampler
from .engine import ClusterEngine

logger = logging.getLogger("dct.cluster.worker")


def iter_assignments(provider, crawl_id: str,
                     storage_prefix: str = "cluster"):
    """Yield assignment rows across all per-batch files of a crawl, in
    batch-file order — the read side of the idempotent writeback (the
    assignment half of the embedding→assignment ledger)."""
    base = f"{storage_prefix}/{crawl_id}/batches"
    for name in provider.list_dir(base):
        if not name.endswith(".jsonl"):
            continue
        text = provider.get_text(f"{base}/{name}")
        for line in (text or "").splitlines():
            if line:
                yield json.loads(line)


@dataclass
class ClusterWorkerConfig:
    worker_id: str = "cluster-worker-0"
    heartbeat_s: float = 30.0
    queue_capacity: int = 64          # decoded result batches awaiting device
    metrics_port: int = 0             # 0 = don't serve; >0 = HTTP port
    storage_prefix: str = "cluster"
    # Model knobs (forwarded into ClusterEngineConfig when the caller
    # lets the worker build its own engine).
    k: int = 16
    buckets: Tuple[int, ...] = (64, 256)
    spherical: bool = True
    seed: int = 0
    # Coalescing feed: one dequeue drains up to this many queued result
    # batches and folds their embeddings as ONE mini-batch step, then
    # fans assignments back so every batch keeps its own ack + idempotent
    # writeback.
    coalesce_batches: int = 4
    # Checkpoint cadence: centroids+counts+inertia write atomically
    # through the state layer every N committed batches AND at graceful
    # stop (whichever first; 0 disables the count trigger).  Every
    # checkpoint also publishes a ClusterUpdateMessage on TOPIC_CLUSTERS.
    checkpoint_every_batches: int = 8
    # A cluster is "under-populated" when its assignment share is below
    # this fraction of the uniform share (1/k) — the frontier-priority
    # signal carried on TOPIC_CLUSTERS.
    min_cluster_fraction: float = 0.5
    # Bounded channel -> last-assigned-cluster map shipped with updates
    # (the orchestrator's join key for cluster-guided prioritization).
    channel_map_size: int = 256
    # SLO budgets (`utils/slo.py`); 0 = no budget declared.
    slo_batch_p95_ms: float = 0.0     # p95 of cluster_worker.process
    slo_queue_wait_ms: float = 0.0    # p95 of cluster_worker.queue_wait
    slo_batch_age_ms: float = 0.0     # p95 of cluster_worker.batch_age
    # Span export (`utils/trace.py:SpanExporter` -> TOPIC_SPANS).
    span_export_interval_s: float = 15.0
    span_export_max_spans: int = 512
    span_sample_rate: float = 1.0


class ClusterWorker:
    """Consume embedding-result batches, run online k-means, write
    assignments, serve ``/clusters``.

    ``provider`` is any `state.providers.StorageProvider`; assignments
    land as one JSONL per batch under
    ``{storage_prefix}/{crawl_id}/batches/{batch_id}.jsonl`` and the
    model checkpoints at ``{storage_prefix}/centroids.json``.  Use
    :func:`iter_assignments` to read assignments back as one stream.
    """

    CHECKPOINT_PATH = "centroids.json"
    # Folded-batch idempotence window (the orchestrator's
    # `_applied_results` discipline): batch ids whose embeddings already
    # updated the model.  A redelivery — e.g. a nack after a failed
    # writeback, or an unacked frame requeued across a kill — re-writes
    # the ledger (idempotent file) but must NOT fold the same vectors a
    # second time; the newest SNAPSHOT-many ids persist inside the
    # checkpoint so the window holds exactly as far back as the model
    # state itself does (batches folded AFTER the last checkpoint are
    # genuinely absent from a resumed model, so refolding them is
    # correct).
    FOLDED_WINDOW = 4096
    FOLDED_SNAPSHOT = 2048

    def __init__(self, bus, engine: Optional[ClusterEngine] = None,
                 provider=None,
                 cfg: ClusterWorkerConfig = ClusterWorkerConfig(),
                 registry: MetricsRegistry = REGISTRY):
        from .engine import ClusterEngineConfig

        self.bus = bus
        self.engine = engine if engine is not None else ClusterEngine(
            ClusterEngineConfig(k=cfg.k, buckets=tuple(cfg.buckets),
                                spherical=cfg.spherical, seed=cfg.seed),
            registry=registry)
        self.provider = provider
        self.cfg = cfg
        self._queue: "queue.Queue[Tuple[RecordBatch, Any, float]]" = \
            queue.Queue(cfg.queue_capacity)
        self._stop = threading.Event()
        self._threads: list = []
        self._idle = threading.Condition()
        self._inflight = 0
        self._started_at = 0.0
        self._processed = 0
        self._errors = 0
        self._skipped = 0           # batches with no embeddings to cluster
        self._batches_since_ckpt = 0
        self._metrics_server = None
        self._killed = False
        self._stop_announced = False
        self.resumed = False
        self._no_embeddings_warned = False
        # Bounded channel -> last cluster map (newest wins), the
        # ClusterUpdateMessage's frontier join key.
        self._channel_clusters: "OrderedDict[str, int]" = OrderedDict()
        # Folded-batch idempotence window (see the class constants).
        self._folded: "OrderedDict[str, None]" = OrderedDict()
        self.m_queue_depth = registry.gauge(
            "cluster_worker_queue_depth",
            "decoded result batches awaiting the k-means step "
            "(time-weighted rolling mean)")
        self._depth = QueueDepthSampler(self.m_queue_depth)
        self.m_batches = registry.counter(
            "cluster_worker_batches_total", "result batches clustered")
        self.m_vectors = registry.counter(
            "cluster_vectors_total", "embeddings assigned to clusters")
        self.m_outcomes = registry.counter(
            "cluster_worker_batch_outcomes_total",
            "result batches by final commit outcome")
        self.m_batch_age = registry.histogram(
            "cluster_worker_batch_age_seconds",
            "result-batch creation -> k-means step per batch")
        self.m_nonempty = registry.gauge(
            "cluster_nonempty",
            "clusters with at least one assigned embedding")
        self.m_inertia = registry.gauge(
            "cluster_inertia_per_vector",
            "rolling mean per-vector inertia of recent k-means steps "
            "(self-sampled into /timeseries for the watch.py sparkline)")
        self.m_checkpoints = registry.counter(
            "cluster_checkpoints_total", "centroid checkpoints written")
        self._telemetry = TelemetryEmitter(
            engine=self.engine, include_device=True,
            counters={"batch_outcomes": self.m_outcomes})
        self._slo = SLOWatchdog(
            standard_slos(batch_p95_ms=cfg.slo_batch_p95_ms,
                          queue_wait_ms=cfg.slo_queue_wait_ms,
                          batch_age_ms=cfg.slo_batch_age_ms),
            registry=registry)
        self._ts_sampler = RegistrySampler(registry)
        self._span_exporter = trace.SpanExporter(
            max_spans=cfg.span_export_max_spans,
            sample_rate=cfg.span_sample_rate,
            name_prefixes=("cluster_worker.", "cluster."))
        self._last_span_export = time.monotonic()
        # Crash recovery at construction, BEFORE the first subscribe: a
        # restarted worker resumes the model from the last checkpoint —
        # it must never re-seed from whatever mini-batch happens to
        # arrive first (the kill-cluster-worker gate's centerpiece).
        self._try_resume()

    # -- crash recovery ----------------------------------------------------
    def _checkpoint_rel(self) -> str:
        return f"{self.cfg.storage_prefix}/{self.CHECKPOINT_PATH}"

    def _try_resume(self) -> None:
        if self.provider is None:
            return
        try:
            state = self.provider.load_json(self._checkpoint_rel())
        except Exception as e:
            logger.warning("cluster checkpoint read failed: %s", e)
            return
        if not state:
            return
        try:
            self.engine.load_state(state)
        except Exception as e:
            # A foreign/incompatible checkpoint (different k) is a loud
            # deployment error, not a silent re-seed.
            raise ValueError(
                f"cluster checkpoint at {self._checkpoint_rel()} is "
                f"incompatible: {e}") from e
        for bid in state.get("folded_batches") or []:
            self._folded[str(bid)] = None
        self.resumed = True
        flight.record("cluster_resume", worker=self.cfg.worker_id,
                      step=self.engine.step, vectors=self.engine.vectors,
                      k=self.engine.cfg.k)
        logger.info("cluster worker resumed from checkpoint",
                    extra={"worker_id": self.cfg.worker_id,
                           "step": self.engine.step,
                           "vectors": self.engine.vectors})

    def checkpoint(self) -> bool:
        """Write the model atomically through the state layer and publish
        a ClusterUpdateMessage; returns False (and logs) on failure — a
        wedged store must not take the serving loop down.  The cadence
        counter resets ONLY on success: a failed write retries on the
        very next committed batch instead of silently doubling the
        crash-recovery gap to the next full interval."""
        if self.provider is not None:
            try:
                state = self.engine.state_dict()
                state["saved_at"] = time.time()
                state["worker_id"] = self.cfg.worker_id
                with self._idle:
                    state["folded_batches"] = \
                        list(self._folded)[-self.FOLDED_SNAPSHOT:]
                self.provider.save_json(self._checkpoint_rel(), state)
                self.m_checkpoints.inc()
                flight.record("cluster_checkpoint",
                              worker=self.cfg.worker_id,
                              step=self.engine.step,
                              vectors=self.engine.vectors)
            except Exception as e:
                logger.warning("cluster checkpoint write failed: %s", e)
                return False
        self._batches_since_ckpt = 0
        self._publish_update()
        return True

    def _publish_update(self) -> None:
        """Best-effort ClusterUpdateMessage on TOPIC_CLUSTERS (fan-out:
        a missed update degrades prioritization freshness only)."""
        try:
            snap = self.engine.snapshot()
            with self._idle:
                channel_map = dict(self._channel_clusters)
            msg = ClusterUpdateMessage.new(
                self.cfg.worker_id, k=snap["k"], step=snap["step"],
                vectors=snap["vectors"], sizes=snap["sizes"],
                inertia=snap["inertia_per_vector"],
                underpopulated=self.engine.underpopulated(
                    self.cfg.min_cluster_fraction),
                channel_clusters=channel_map)
            self.bus.publish(TOPIC_CLUSTERS, msg.to_dict())
        except Exception as e:
            logger.warning("cluster update publish failed: %s", e)

    # -- observability surfaces --------------------------------------------
    def get_status(self) -> dict:
        return {
            "worker_id": self.cfg.worker_id,
            "worker_type": "cluster",
            "k": self.engine.cfg.k,
            "dim": self.engine.dim,
            "is_running": not self._stop.is_set() and bool(self._threads),
            "queue_depth": self._queue.qsize(),
            "inflight": self._inflight,
            "processed_batches": self._processed,
            "error_batches": self._errors,
            "skipped_batches": self._skipped,
            "vectors": self.engine.vectors,
            "resumed": self.resumed,
            "uptime_s": (time.monotonic() - self._started_at)
            if self._started_at else 0.0,
        }

    def get_costs(self) -> dict:
        """The /costs body: the cluster engine's cost/efficiency snapshot
        (path="cluster" rows) plus the worker's SLO state and per-tenant
        spend rows."""
        out = dict(self.engine.cost_snapshot())
        out["worker_id"] = self.cfg.worker_id
        out["slo"] = self._slo.snapshot()
        ledger = self._tenant_ledger()
        if ledger is not None:
            out["tenants"] = ledger.snapshot()
        return out

    # -- tenant attribution (ISSUE 17) --------------------------------------
    def _tenant_ledger(self):
        return getattr(getattr(self.engine, "meter", None), "tenants", None)

    def _set_meter_tenants(self, weights) -> None:
        set_fn = getattr(getattr(self.engine, "meter", None),
                         "set_tenants", None)
        if callable(set_fn):
            set_fn(weights)

    def get_clusters(self) -> dict:
        """The /clusters body (`set_clusters_provider` seam): centroid
        sizes/norms, inertia trend, assignment throughput, checkpoint +
        resume state."""
        snap = self.engine.snapshot()
        eff = self.engine.meter.snapshot()
        snap.update({
            "worker_id": self.cfg.worker_id,
            "resumed": self.resumed,
            "resume_step": self.engine.resumed_from_step,
            "assign_vectors_per_s": eff.get("goodput_tokens_per_s", 0.0),
            "underpopulated": self.engine.underpopulated(
                self.cfg.min_cluster_fraction),
            "checkpoint": {
                "path": self._checkpoint_rel(),
                "every_batches": self.cfg.checkpoint_every_batches,
                "written": int(self.m_checkpoints.value),
            },
            "processed_batches": self._processed,
            "skipped_batches": self._skipped,
        })
        return snap

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._started_at = time.monotonic()
        set_status_provider(self.get_status)
        set_costs_provider(self.get_costs)
        set_clusters_provider(self.get_clusters)
        self.bus.subscribe(TOPIC_INFERENCE_RESULTS, self._handle_payload)
        for target, name in ((self._feed_loop, "cluster-feed"),
                             (self._heartbeat_loop, "cluster-heartbeat")):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        if self.cfg.metrics_port:
            self._metrics_server = serve_metrics(self.cfg.metrics_port)
        logger.info("cluster worker started", extra={
            "worker_id": self.cfg.worker_id, "k": self.engine.cfg.k,
            "resumed": self.resumed})

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        clear_status_provider(self.get_status)
        clear_costs_provider(self.get_costs)
        clear_clusters_provider(self.get_clusters)
        for t in self._threads:
            t.join(timeout=timeout_s)
        if self.cfg.span_export_interval_s > 0:
            self.export_spans()
        # Final checkpoint on graceful stop only — kill() deliberately
        # loses everything since the last periodic checkpoint, exactly
        # like SIGKILL (that gap is what the chaos gate measures).
        if not self._killed and self.engine.step > 0:
            self.checkpoint()
        self._announce_stopping()
        if self.provider is not None:
            flush = getattr(self.provider, "flush", None)
            if callable(flush):
                flush()
        if self._metrics_server is not None:
            self._metrics_server.shutdown()

    def kill(self) -> None:
        """Abrupt-death simulation (the chaos seam): halt the threads
        WITHOUT draining, checkpointing, or acking queued batches — the
        in-process analog of SIGKILL.  Un-acked frames requeue
        server-side on manual-ack buses; the /status, /costs and
        /clusters providers stay registered, exactly as a dead process
        leaves its endpoints unreachable rather than deregistered."""
        self._killed = True
        self._stop.set()
        flight.record("worker_kill", worker=self.cfg.worker_id,
                      queue_depth=self._queue.qsize(),
                      inflight=self._inflight, step=self.engine.step)
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()

    def _announce_stopping(self) -> None:
        if self._killed or self._stop_announced:
            return
        self._stop_announced = True
        try:
            self.bus.publish(TOPIC_WORKER_STATUS, StatusMessage.new(
                self.cfg.worker_id, MSG_WORKER_STOPPING, WORKER_OFFLINE,
                tasks_processed=self._processed,
                tasks_success=self._processed - self._errors,
                tasks_error=self._errors,
                uptime_s=time.monotonic() - self._started_at,
                worker_type="cluster").to_dict())
        except Exception as e:  # a dead bus must not break shutdown
            logger.debug("stopping announcement failed: %s", e)

    def evaluate_slos(self) -> list:
        """One SLO evaluation tick on demand (the heartbeat loop's twin;
        the loadgen gate calls this at phase boundaries)."""
        return self._slo.evaluate()

    def export_spans(self) -> int:
        """Ship spans completed since the last export on TOPIC_SPANS;
        never raises — span telemetry must not take the worker down."""
        try:
            spans, dropped = self._span_exporter.collect()
            if not spans and not dropped:
                return 0
            msg = SpanBatchMessage.new(
                self.cfg.worker_id, [s.to_dict() for s in spans],
                dropped=dropped)
            self.bus.publish(TOPIC_SPANS, msg.to_dict())
            return len(spans)
        except Exception as e:
            logger.warning("span export failed: %s", e)
            return 0

    def warmup(self) -> None:
        """Pre-compile the bucket step programs when the embedding dim is
        already known (a resumed checkpoint carries it); a fresh model
        compiles on the first live mini-batch instead."""
        if self.engine.dim:
            self.engine.warmup(self.engine.dim)

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until every accepted batch — queued OR mid-step — has
        finished (the TPUWorker drain contract)."""
        with self._idle:
            return self._idle.wait_for(
                lambda: self._inflight == 0, timeout=timeout_s)

    # -- bus handler (never blocks on the device) --------------------------
    def _handle_payload(self, payload: Dict[str, Any], ack=None) -> None:
        """``ack`` is supplied by manual-ack buses (RemoteBus): the frame
        acks only after the step AND the assignment writeback, so a
        worker crash mid-queue requeues it server-side."""
        batch = RecordBatch.from_dict(payload)
        if not batch.records:
            if ack is not None:
                ack(True)
            return
        with self._idle:
            self._inflight += 1
        try:
            self._queue.put((batch, ack, time.monotonic()), timeout=5.0)
        except queue.Full:
            self._finish_one()
            if ack is not None:
                self.m_outcomes.labels(outcome="requeued").inc()
                flight.record("batch", batch=batch.batch_id,
                              outcome="requeued", reason="queue_full",
                              worker=self.cfg.worker_id)
                ack(False)  # requeue server-side; don't block the stream
                return
            raise
        self._depth.update(self._queue.qsize())

    def _finish_one(self) -> None:
        with self._idle:
            self._inflight -= 1
            if self._inflight == 0:
                self._idle.notify_all()

    # -- feed loop (coalescing) --------------------------------------------
    def _feed_loop(self) -> None:
        while not self._stop.is_set():
            try:
                items = [self._queue.get(timeout=0.1)]
            except queue.Empty:
                continue
            while len(items) < max(1, self.cfg.coalesce_batches):
                try:
                    items.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            self._depth.update(self._queue.qsize())
            try:
                self._process_group(items)
            finally:
                for _ in items:
                    self._finish_one()

    @staticmethod
    def _extract(batch: RecordBatch
                 ) -> Tuple[List[List[float]], List[Dict[str, Any]]]:
        """(embeddings, row metadata) for the rows of one result batch
        that carry an embedding — raises on malformed vectors so the
        batch fails alone (per-batch poison isolation)."""
        vecs: List[List[float]] = []
        rows: List[Dict[str, Any]] = []
        for record, result in zip(batch.records, batch.results):
            emb = (result or {}).get("embedding")
            if emb is None:
                continue
            vec = [float(v) for v in emb]
            if not vec:
                raise ValueError(
                    f"empty embedding for post "
                    f"{record.get('post_uid', '?')!r}")
            vecs.append(vec)
            rows.append({
                "post_uid": record.get("post_uid", ""),
                "channel_name": record.get("channel_name", ""),
            })
        return vecs, rows

    def _process_group(self,
                       items: List[Tuple[RecordBatch, Any, float]]) -> None:
        now = time.monotonic()
        ledger = self._tenant_ledger()
        for batch, _, enq_t in items:
            trace.record("cluster_worker.queue_wait", now - enq_t,
                         trace_id=batch.trace_id, batch=batch.batch_id,
                         worker=self.cfg.worker_id, tenant=batch.tenant)
            if ledger is not None and batch.tenant:
                ledger.observe_queue_wait(batch.tenant, now - enq_t)
        # Extract per batch FIRST: a batch whose embeddings are malformed
        # fails alone, before any neighbor joins it in the step.
        good: List[Tuple[RecordBatch, Any, list, list]] = []
        for batch, ack, _ in items:
            try:
                vecs, rows = self._extract(batch)
                self._observe_age(batch)
            except Exception as e:
                self._errors += 1
                self.m_outcomes.labels(outcome="error").inc()
                logger.exception("batch %s failed to extract embeddings: "
                                 "%s", batch.batch_id, e)
                if ack is not None:
                    ack(False)
                continue
            if not vecs:
                # No embeddings at all: the publisher runs with
                # publish_embeddings off — nothing to cluster, ack so the
                # frame doesn't redeliver forever, and say so LOUDLY once.
                self._skipped += 1
                self.m_outcomes.labels(outcome="skipped").inc()
                if not self._no_embeddings_warned:
                    self._no_embeddings_warned = True
                    logger.warning(
                        "result batch %s carries no embeddings — is the "
                        "TPU worker running with publish_embeddings "
                        "off? clustering requires embedding-carrying "
                        "result batches", batch.batch_id)
                if ack is not None:
                    ack(True)
                continue
            good.append((batch, ack, vecs, rows))
        if not good:
            return
        # Redeliveries (nack after a failed writeback, frames requeued
        # across a kill — or BOTH copies of one batch draining in the
        # same coalesced group after an ack-timeout requeue) must not
        # fold the same vectors twice: anything already folded, or a
        # duplicate batch_id WITHIN this group, re-assigns against the
        # current centroids (no model update) and re-writes its
        # idempotent ledger file.
        fresh, refold = [], []
        group_ids: set = set()
        with self._idle:
            for g in good:
                bid = g[0].batch_id
                if bid in self._folded or bid in group_ids:
                    refold.append(g)
                else:
                    group_ids.add(bid)
                    fresh.append(g)
        all_vecs = [v for _, _, vecs, _ in fresh for v in vecs]
        if fresh:
            # Tenant weights for the combined step = vector counts.
            weights: Dict[str, float] = {}
            for batch, _, vecs, _ in fresh:
                weights[batch.tenant] = weights.get(batch.tenant, 0.0) \
                    + max(1, len(vecs))
            self._set_meter_tenants(weights)
            dominant = max(weights, key=weights.get) if weights else ""
            try:
                # One mini-batch step for the coalesced group, under the
                # FIRST batch's trace (one device stream, one ambient
                # context); co-batched ids ride as attrs.
                with trace.span("cluster_worker.process",
                                trace_id=fresh[0][0].trace_id,
                                batches=len(fresh),
                                batch_ids=[b.batch_id
                                           for b, _, _, _ in fresh],
                                vectors=len(all_vecs),
                                worker=self.cfg.worker_id,
                                tenant=dominant):
                    assigns = self.engine.observe(all_vecs)
            except Exception as e:
                # The combined step failed; isolate per batch so one
                # poisoned batch cannot take its neighbors down.  The
                # model is untouched (engine.observe commits atomically
                # across its chunks), so the per-batch retry cannot
                # double-fold a partially-applied group.
                logger.exception(
                    "coalesced cluster step over %d batches failed (%s); "
                    "isolating per batch", len(fresh), e)
                for batch, ack, vecs, rows in fresh:
                    self._process_isolated(batch, ack, vecs, rows)
                for batch, ack, vecs, rows in refold:
                    self._process_refold(batch, ack, vecs, rows)
                return
            self._mark_folded(b.batch_id for b, _, _, _ in fresh)
            off = 0
            for batch, ack, vecs, rows in fresh:
                part = assigns[off:off + len(vecs)]
                off += len(vecs)
                self._commit_batch(batch, ack, rows, part)
        # Refolds AFTER the fresh fold: a first-ever group containing a
        # duplicate has seeded centroids to assign against by now.
        for batch, ack, vecs, rows in refold:
            self._process_refold(batch, ack, vecs, rows)
        self._refresh_gauges()
        self._maybe_checkpoint()

    def _mark_folded(self, batch_ids) -> None:
        """Record batch ids whose vectors just updated the model (the
        fold happened the moment observe() returned — even a later
        writeback failure must not refold them)."""
        with self._idle:
            for bid in batch_ids:
                self._folded[bid] = None
                self._folded.move_to_end(bid)
            while len(self._folded) > self.FOLDED_WINDOW:
                self._folded.popitem(last=False)

    def _process_refold(self, batch: RecordBatch, ack, vecs,
                        rows) -> None:
        """A redelivered already-folded batch: assignments against the
        current centroids (no model update), then the normal idempotent
        commit."""
        try:
            with trace.span("cluster_worker.process",
                            trace_id=batch.trace_id,
                            batch=batch.batch_id, refold=True,
                            worker=self.cfg.worker_id):
                assigns = self.engine.assign_only(vecs)
        except Exception as e:
            self._errors += 1
            self.m_outcomes.labels(outcome="error").inc()
            logger.exception("refold of batch %s failed: %s",
                             batch.batch_id, e)
            self._ack(batch, ack, False)
            return
        flight.record("batch", batch=batch.batch_id, outcome="refold",
                      vectors=len(assigns), worker=self.cfg.worker_id)
        self._commit_batch(batch, ack, rows, assigns)

    def _process_isolated(self, batch: RecordBatch, ack, vecs,
                          rows) -> None:
        try:
            self._set_meter_tenants({batch.tenant: max(1, len(vecs))})
            with trace.span("cluster_worker.process",
                            trace_id=batch.trace_id,
                            batch=batch.batch_id, isolated=True,
                            worker=self.cfg.worker_id,
                            tenant=batch.tenant):
                assigns = self.engine.observe(vecs)
        except Exception as e:
            self._errors += 1
            self.m_outcomes.labels(outcome="error").inc()
            flight.record("batch", batch=batch.batch_id, outcome="error",
                          error=str(e), worker=self.cfg.worker_id)
            logger.exception("cluster batch %s failed: %s",
                             batch.batch_id, e)
            self._ack(batch, ack, False)
            return
        self._mark_folded([batch.batch_id])
        self._commit_batch(batch, ack, rows, assigns)
        self._refresh_gauges()
        self._maybe_checkpoint()

    def _commit_batch(self, batch: RecordBatch, ack, rows,
                      assigns: List[int]) -> None:
        """The ONE commit/ack/error path every route shares: track the
        channel map, write assignments idempotently, ack."""
        try:
            for row, cluster in zip(rows, assigns):
                ch = row.get("channel_name") or ""
                if ch:
                    with self._idle:
                        self._channel_clusters[ch] = int(cluster)
                        self._channel_clusters.move_to_end(ch)
                        while len(self._channel_clusters) > \
                                max(1, self.cfg.channel_map_size):
                            self._channel_clusters.popitem(last=False)
            with trace.span("cluster_worker.commit",
                            trace_id=batch.trace_id,
                            batch=batch.batch_id, vectors=len(assigns)):
                self._writeback(batch, rows, assigns)
            self._processed += 1
            self._batches_since_ckpt += 1
            self.m_batches.inc()
            self.m_vectors.inc(len(assigns))
            self.m_outcomes.labels(outcome="ok").inc()
            flight.record("batch", batch=batch.batch_id, outcome="ok",
                          vectors=len(assigns), worker=self.cfg.worker_id)
            self._ack(batch, ack, True)
        except Exception as e:
            self._errors += 1
            self.m_outcomes.labels(outcome="error").inc()
            flight.record("batch", batch=batch.batch_id, outcome="error",
                          error=str(e), worker=self.cfg.worker_id)
            logger.exception("cluster batch %s commit failed: %s",
                             batch.batch_id, e)
            self._ack(batch, ack, False)

    def _ack(self, batch: RecordBatch, ack, ok: bool) -> None:
        if ack is None:
            return
        t0 = time.perf_counter()
        ack(ok)
        trace.record("cluster_worker.ack", time.perf_counter() - t0,
                     trace_id=batch.trace_id, batch=batch.batch_id, ok=ok)

    def _observe_age(self, batch: RecordBatch) -> None:
        if batch.created_at is None:
            return
        from ..state.datamodels import utcnow

        age = (utcnow() - batch.created_at).total_seconds()
        if age >= 0:
            self.m_batch_age.observe(age)
            trace.record("cluster_worker.batch_age", age,
                         trace_id=batch.trace_id, batch=batch.batch_id,
                         worker=self.cfg.worker_id, tenant=batch.tenant)

    def _writeback(self, batch: RecordBatch, rows,
                   assigns: List[int]) -> None:
        """Idempotent: one atomically-written file per batch_id — a bus
        redelivery (e.g. frames requeued across a worker kill)
        overwrites the same file with the same content instead of
        duplicating ledger rows."""
        if self.provider is None:
            return
        rel = (f"{self.cfg.storage_prefix}/{batch.crawl_id or 'adhoc'}"
               f"/batches/{batch.batch_id}.jsonl")
        lines = []
        for row, cluster in zip(rows, assigns):
            lines.append(json.dumps({
                "post_uid": row.get("post_uid", ""),
                "channel_name": row.get("channel_name", ""),
                "cluster": int(cluster),
                "batch_id": batch.batch_id,
                "trace_id": batch.trace_id,
                "tenant": batch.tenant,
            }, ensure_ascii=False))
        self.provider.put_text(rel, "\n".join(lines) + "\n")

    def _refresh_gauges(self) -> None:
        snap = self.engine.snapshot()
        self.m_nonempty.set(snap["nonempty"])
        if snap["inertia_per_vector"] is not None:
            self.m_inertia.set(snap["inertia_per_vector"])

    def _maybe_checkpoint(self) -> None:
        every = self.cfg.checkpoint_every_batches
        if every > 0 and self._batches_since_ckpt >= every:
            self.checkpoint()

    # -- heartbeats --------------------------------------------------------
    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._slo.evaluate()
            except Exception as e:  # budget math must never kill the beat
                logger.warning("slo evaluation failed: %s", e)
            status = WORKER_BUSY if not self._queue.empty() else WORKER_IDLE
            msg = StatusMessage.new(
                self.cfg.worker_id, MSG_HEARTBEAT, status,
                tasks_processed=self._processed,
                tasks_success=self._processed - self._errors,
                tasks_error=self._errors,
                uptime_s=time.monotonic() - self._started_at,
                worker_type="cluster")
            msg.queue_length = self._queue.qsize()
            msg.resource_usage = self._telemetry.snapshot()
            msg.resource_usage["queue"] = {
                "depth": self._queue.qsize(),
                "depth_time_weighted": round(self._depth.sample(), 4),
            }
            slo_snap = self._slo.snapshot()
            msg.resource_usage["slo_breaches"] = slo_snap["breaches"]
            if slo_snap.get("tenant_breaches"):
                msg.resource_usage["tenant_slo_breaches"] = \
                    slo_snap["tenant_breaches"]
            ledger = self._tenant_ledger()
            if ledger is not None:
                tenants = ledger.snapshot()
                if tenants["rows"]:
                    msg.resource_usage["tenants"] = tenants
            msg.resource_usage["cluster"] = {
                "step": self.engine.step,
                "vectors": self.engine.vectors,
                "nonempty": int(self.m_nonempty.value),
            }
            self._ts_sampler.sample()
            try:
                self.bus.publish(TOPIC_WORKER_STATUS, msg.to_dict())
            except Exception as e:  # bus outage must not kill the worker
                logger.warning("heartbeat publish failed: %s", e)
            self._wait_with_span_exports(self.cfg.heartbeat_s)

    def _wait_with_span_exports(self, wait_s: float) -> None:
        deadline = time.monotonic() + wait_s
        interval = self.cfg.span_export_interval_s
        while not self._stop.is_set():
            if interval > 0 and \
                    time.monotonic() - self._last_span_export >= interval:
                self._last_span_export = time.monotonic()
                self.export_spans()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            self._stop.wait(min(remaining, interval)
                            if interval > 0 else remaining)
