"""cluster/ — streaming distributed clustering (BASELINE config #5).

The pipeline's organize stage: the `ClusterWorker` subscribes to the
embedding-carrying result batches the TPU worker publishes on
``TOPIC_INFERENCE_RESULTS``, folds them into an online spherical
mini-batch k-means model (`ClusterEngine`, reusing the jitted
MXU-friendly kernels of `models/clustering.py`), writes per-batch
assignment ledgers idempotently through the state layer, checkpoints
centroids atomically for crash recovery, serves `/clusters`, and
announces `ClusterUpdateMessage`s on ``TOPIC_CLUSTERS`` for the
orchestrator's cluster-guided frontier prioritization.
"""

from .engine import ClusterEngine, ClusterEngineConfig
from .worker import ClusterWorker, ClusterWorkerConfig, iter_assignments

__all__ = [
    "ClusterEngine",
    "ClusterEngineConfig",
    "ClusterWorker",
    "ClusterWorkerConfig",
    "iter_assignments",
]
