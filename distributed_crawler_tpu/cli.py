"""The CLI entry point: flag parsing, precedence chain, mode dispatch.

Parity with the reference's `main.go` (869 LoC):
- the full flag surface (`main.go:751-854`) via argparse, with the same
  four-level precedence (flags > CRAWLER_* env > YAML config > defaults)
  through `config.precedence.ConfigResolver`
- time-ago / date-between / max-crawl-duration parsing
  (`main.go:91-142,432-471` -> `utils/timeparse`)
- sampling-method validation matrix (`main.go` PersistentPreRunE)
- mode dispatch (`main.go:586-628`): standalone | launch (the four-way
  router) | orchestrator | worker | job | tpu-worker | version
- the reference's pprof server on :6060 (`main.go:60-80`) becomes the
  first-class metrics endpoint (`utils/metrics.serve_metrics`)
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
from typing import Callable, List, Optional

from .config.crawler import (
    CrawlerConfig,
    generate_crawl_id,
    read_urls_from_file,
)
from .config.precedence import ConfigResolver
from .config.sampling import SamplingValidationInput, validate_sampling_method
from .utils.structlog import setup_logging
from .utils.timeparse import parse_date_between, parse_duration, parse_time_ago

logger = logging.getLogger("dct.cli")


def build_parser() -> argparse.ArgumentParser:
    """The flag surface (`main.go:751-854`).  Defaults are None so the
    precedence resolver can tell "explicitly set" from "default"."""
    p = argparse.ArgumentParser(
        prog="dct",
        description="distributed_crawler_tpu — TPU-native distributed "
                    "social-media crawler + inference framework")
    a = p.add_argument
    a("--config", default=None, help="config file (default: ./config.yaml)")
    a("--log-level", default=None, help="trace|debug|info|warn|error")
    a("--log-json", action="store_const", const=True, default=None)
    a("--mode", default=None,
      help="standalone | launch | orchestrator | worker | job | "
           "job-submit | tpu-worker | asr-worker | cluster-worker | "
           "train-head | cluster | bus | transcribe | dc-gateway | "
           "gen-code")
    a("--worker-id", default=None, help="worker identifier (worker modes)")
    a("--concurrency", type=int, default=None)
    a("--timeout", type=int, default=None, help="HTTP timeout seconds")
    a("--user-agent", default=None)
    a("--output", default=None, help="output format")
    a("--storage-root", default=None)
    a("--min-post-date", default=None, help="YYYY-MM-DD")
    a("--time-ago", default=None, help="e.g. 30d, 6h, 2w, 1m, 1y")
    a("--max-crawl-duration", default=None, help="e.g. 48h, 24h30m")
    a("--date-between", default=None, help="YYYY-MM-DD,YYYY-MM-DD")
    a("--sample-size", type=int, default=None)
    a("--tdlib-database-url", default=None)
    a("--tdlib-database-urls", default=None, help="comma-separated")
    a("--tdlib-verbosity", type=int, default=None)
    # Client side of the DC gateway seam (pool dials instead of embedding
    # an offline store; credentials from --tdlib-dir / TG_* env).
    a("--dc-address", default=None,
      help="host:port of a dc-gateway; pool connections dial it over the "
           "wire protocol (empty = offline embedded store)")
    a("--dc-tls", action="store_const", const=True, default=None,
      help="dial the gateway over TLS (Chrome-shaped ClientHello)")
    a("--dc-tls-insecure", action="store_const", const=True, default=None,
      help="skip cert verification (self-signed gateway bootstrap)")
    a("--dc-sni", default=None, help="TLS SNI override")
    a("--dc-wire", default=None, choices=["dct", "mtproto"],
      help="client wire protocol (must match the gateway's --gateway-wire)")
    a("--dc-pubkey-file", default=None,
      help="gateway RSA public key JSON ({n, e}; written by a "
           "--gateway-wire mtproto gateway as <address-file>.pubkey) — "
           "required with --dc-wire mtproto")
    a("--dc-table-file", default=None,
      help="DC table JSON ({dc_id: {address, pubkey_file}}; Telegram's "
           "dcOptions analog) — lets connections follow PHONE_MIGRATE_X "
           "redirects to an account's home DC")
    a("--min-users", type=int, default=None)
    a("--crawl-id", default=None)
    a("--crawl-label", default=None)
    a("--max-comments", type=int, default=None)
    a("--max-depth", type=int, default=None)
    a("--max-posts", type=int, default=None)
    a("--max-pages", type=int, default=None)
    a("--skip-media", action="store_const", const=True, default=None)
    a("--youtube-api-key", default=None)
    a("--platform", default=None, help="telegram | youtube")
    a("--sampling", default=None,
      help="channel | random | random-walk | snowball")
    a("--seed-size", type=int, default=None)
    a("--walkback-rate", type=int, default=None)
    a("--min-channel-videos", type=int, default=None)
    a("--null-config", default=None)
    a("--exit-on-complete", action="store_const", const=True, default=None)
    # Validator / tandem
    a("--tandem-crawl", action="store_const", const=True, default=None)
    a("--validate-only", action="store_const", const=True, default=None)
    a("--validator-request-rate", type=float, default=None)
    a("--validator-request-jitter-ms", type=int, default=None)
    a("--validator-claim-batch-size", type=int, default=None)
    a("--validator-timeout", default=None, help="e.g. 30m")
    a("--validator-base-url", default=None,
      help="validation endpoint base (default https://t.me); point at a "
           "mirror/forward proxy")
    a("--validator-transport", default=None,
      help="t.me transport: urllib | chrome (native Chrome-shaped TLS)")
    # Combine files (chunker)
    a("--combine-files", action="store_const", const=True, default=None)
    a("--combine-watch-dir", default=None)
    a("--combine-temp-dir", default=None)
    a("--combine-write-dir", default=None)
    a("--combine-trigger-size", type=int, default=None, help="MiB")
    a("--object-store", default=None,
      help="remote blob target for combined files (memory:// | "
           "file:///path; empty = combined files land under "
           "<storage-root>/combined/)")
    a("--combine-hard-cap", type=int, default=None, help="MiB")
    # Inputs
    a("--urls", default=None, help="comma-separated URLs to crawl")
    a("--url-file", default=None, help="file with one URL per line")
    # Distributed bus (the DCN leg; orchestrator hosts, workers connect)
    a("--bus-address", default=None,
      help="gRPC bus address, e.g. 127.0.0.1:50551 (orchestrator binds it, "
           "workers dial it; empty = in-process bus)")
    # Bus durability (docs/operations.md "Bus durability & dead letters"):
    # broker WAL spool + publisher outbox + persisted dead-letter queue.
    a("--bus-spool-dir", default=None,
      help="broker WAL spool directory: the hosted GrpcBusServer journals "
           "every pull-topic frame + dead letters here, so a restarted "
           "broker generation resumes exactly where the dead one stopped; "
           "setting this also routes this process's publishes through a "
           "durable outbox (empty = RAM-only bus, the historical behavior)")
    a("--bus-outbox-max-frames", type=int, default=None,
      help="bound on publishes buffered in the durable outbox while the "
           "broker is unreachable (default 1024; the orchestrator pauses "
           "crawl dispatch as the buffer nears this bound)")
    # Partitioned bus (docs/operations.md "Partitioned bus & sharded
    # frontier"): N broker shards behind one consistent-hash client.
    a("--bus-shard-addresses", default=None,
      help="comma-separated gRPC addresses of the bus broker SHARDS "
           "(one `--mode bus` process per address, each with its OWN "
           "--bus-spool-dir).  This process routes pull-topic frames by "
           "post_uid/work-item key across them (bus/partition.py) and "
           "broadcasts fan-out topics; a dead shard's frames park in "
           "that shard's outbox until it returns")
    a("--bus-shards", type=int, default=None,
      help="expected shard count; validated against "
           "--bus-shard-addresses so a truncated address list fails "
           "loudly instead of silently re-dealing the hash ring")
    a("--bus-ack-timeout-s", type=float, default=None,
      help="seconds a pulled frame may stay unacked before the broker "
           "requeues it for another worker (default 300)")
    a("--bus-max-attempts", type=int, default=None,
      help="delivery attempts per frame before it is dead-lettered "
           "(default 5; with --bus-spool-dir dead letters persist and are "
           "listable/replayable via tools/dlq.py and /dlq)")
    # Observability (pprof-analog)
    a("--metrics-port", type=int, default=None,
      help="serve /metrics + /healthz on this port (0 = off)")
    a("--profiler-port", type=int, default=None,
      help="serve a jax.profiler trace server on this port (0 = off; "
           "the reference's :6060 pprof analog)")
    a("--trace-buffer", type=int, default=None,
      help="completed spans kept in memory for the /traces endpoint "
           "(0 disables span recording; default 2048)")
    a("--slow-trace-ms", type=float, default=None,
      help="log any span slower than this many milliseconds "
           "(0 = off, the default)")
    a("--dump-dir", default=None,
      help="write postmortem bundles (flight ring + traces + metrics + "
           "config fingerprint) here on SIGTERM, unhandled exception, or "
           "fatal signal; empty (default) = no dumps")
    a("--flight-buffer", type=int, default=None,
      help="flight-recorder events kept in memory for postmortem bundles "
           "(0 disables recording; default 512)")
    a("--telemetry-interval", type=float, default=None,
      help="seconds between telemetry-rich heartbeats in the worker "
           "modes (default 30; clamped to 90 so heartbeats always beat "
           "the orchestrator's 300 s liveness timeout)")
    a("--slo-batch-p95-ms", type=float, default=None,
      help="SLO budget on the per-batch processing span's p95 in ms, "
           "evaluated each heartbeat (breach -> slo_breach_total{slo} + "
           "WARNING with the offending trace_id + flight event; 0 = off)")
    a("--slo-queue-wait-ms", type=float, default=None,
      help="SLO budget on the TPU worker's queue-wait p95 in ms "
           "(0 = off, the default)")
    a("--slo-batch-age-ms", type=float, default=None,
      help="SLO budget on whole-pipeline batch age p95 in ms "
           "(RecordBatch creation -> device; covers the broker leg "
           "queue-wait can't see, so it fires on a dead worker's "
           "stranded backlog; 0 = off, the default)")
    a("--profile-on-slow-ms", type=float, default=None,
      help="auto-capture a bounded jax.profiler trace to --dump-dir when "
           "a device batch exceeds this many ms (one capture at a time; "
           "0 = off); /profile?seconds=N on the metrics port does the "
           "same on demand")
    a("--span-export-interval", type=float, default=None,
      help="seconds between span exports from the serving workers to the "
           "orchestrator's distributed-trace collector (SpanBatchMessage "
           "on the spans topic -> /dtraces; 0 disables export, default "
           "15)")
    a("--span-export-max-spans", type=int, default=None,
      help="max spans shipped per export batch (excess newest-kept, "
           "counted as dropped; default 512)")
    a("--span-sample-rate", type=float, default=None,
      help="fraction of TRACES whose spans are exported (stable per-"
           "trace hash, so every process ships the same subset and "
           "cross-process traces stay complete; default 1.0)")
    a("--timeseries-window", type=float, default=None,
      help="rolling time-series retention in seconds for the /timeseries "
           "store (worker self-samples + the orchestrator's fleet folds; "
           "default 900)")
    a("--timeseries-max-samples", type=int, default=None,
      help="samples kept per time series (O(1)-append ring; default 512)")
    a("--alert-rules", default=None,
      help="watchtower alert rules: inline JSON list or @path/to/"
           "rules.json; each entry replaces the same-named rule of the "
           "default pack (queue_wait_burn, batch_age_burn, "
           "per_chip_goodput_collapse, dlq_growth, outbox_near_full, "
           "stale_worker — docs/operations.md \"Watchtower\")")
    a("--tenant", default=None,
      help="tenant label stamped onto every record batch this crawl's "
           "ingestion publishes (per-tenant spend + SLO accounting on "
           "/tenants and /costs; empty = the documented 'default' "
           "tenant — docs/operations.md \"Tenant attribution\")")
    a("--tenant-budgets", default=None,
      help="per-tenant error budgets: inline JSON or @path/to/"
           "budgets.json with {window_s, budgets: {tenant: {slo: "
           "allowed_breaches}}}; the orchestrator's /tenants surface "
           "reports windowed burn, remaining budget, and exhaustion "
           "projection per (tenant, slo) — docs/operations.md \"Tenant "
           "attribution & error budgets\")")
    # Elastic fleet (orchestrator mode; docs/operations.md "Elastic fleet
    # & autoscaling"): an alert-actuated autoscaler that spawns/retires
    # `--mode tpu-worker` child processes against the watchtower's firing
    # alerts, flight-recorded and served at /autoscaler.
    a("--autoscaler", action="store_const", const=True, default=None,
      help="run the elastic-fleet autoscaler beside the orchestrator: "
           "firing watchtower alerts scale a pool of tpu-worker child "
           "processes up, sustained headroom scales it back down "
           "(requires --bus-address so children can dial the broker)")
    a("--autoscaler-pools", default=None,
      help="full pool-policy list: inline JSON list or @path/to/"
           "pools.json (fields: pool, min_workers, max_workers, "
           "scale_up_alerts, up/down_cooldown_s, stabilization_s, "
           "trend_series/trend_slope_per_s, headroom_series/"
           "headroom_below); overrides the single-pool knobs below")
    a("--autoscaler-min", type=int, default=None,
      help="single-pool shortcut: minimum tpu-worker children "
           "(default 1)")
    a("--autoscaler-max", type=int, default=None,
      help="single-pool shortcut: maximum tpu-worker children "
           "(default 4)")
    a("--autoscaler-up-cooldown", type=float, default=None,
      help="seconds between scale-up steps (default 30)")
    a("--autoscaler-down-cooldown", type=float, default=None,
      help="seconds between scale-down steps (default 60)")
    a("--autoscaler-stabilization", type=float, default=None,
      help="seconds of sustained headroom required before any "
           "scale-down (default 30)")
    a("--autoscaler-eval-interval", type=float, default=None,
      help="seconds between autoscaler control passes (default 5)")
    a("--autoscaler-worker-args", default=None,
      help="extra CLI args appended to every spawned tpu-worker child, "
           'e.g. "--infer-model xlmr --metrics-port 0" (the bus address '
           "and a generated --worker-id are supplied automatically)")
    # Load harness (`python -m tools.loadtest`; loadgen/).  These keys
    # configure the synthetic workload + SLO gate; the crawl/worker modes
    # ignore them, but they resolve through the same precedence chain so
    # a config file can pin a site's load-test defaults.
    a("--loadgen-scenario", default=None,
      help="loadgen scenario: checked-in name (steady-state, "
           "kill-worker, backend-wedge) or a JSON scenario file path "
           "(tools/loadtest.py; docs/operations.md)")
    a("--loadgen-seed", type=int, default=None,
      help="loadgen workload seed (same seed -> identical batch shapes "
           "and arrival schedule)")
    a("--loadgen-duration-s", type=float, default=None,
      help="loadgen load-phase duration in seconds")
    a("--loadgen-arrival", default=None, choices=["poisson", "ramp"],
      help="loadgen arrival process: open-loop poisson or closed-loop "
           "concurrency ramp")
    a("--loadgen-rate", type=float, default=None,
      help="loadgen offered load in batches/s (poisson arrivals)")
    a("--loadgen-platform-mix", default=None,
      help='loadgen platform weights, e.g. "telegram=0.8,youtube=0.2"')
    a("--loadgen-gate", default=None,
      help="loadgen gate-envelope overrides: inline JSON object or "
           "@path/to/gate.json (merged over the scenario's gate block)")
    # TPU inference stage
    a("--bus-serve", action="store_const", const=True, default=None,
      help="also HOST the gRPC bus broker at --bus-address (tpu-worker "
           "and job modes; orchestrator mode always hosts)")
    # Job submission (mode=job-submit -> a running `--mode job` service)
    a("--job-name", default=None,
      help="job name; the prefix routes it (telegram-crawl*, "
           "youtube-crawl*, scheduled-crawl*, maintenance-job*)")
    a("--job-due-s", type=float, default=None,
      help="seconds until the job fires (default 0 = now)")
    a("--job-repeat-s", type=float, default=None,
      help="re-fire the job every N seconds after the first run "
           "(default 0 = one-shot; e.g. 86400 for a nightly crawl)")
    a("--job-data", default=None,
      help="job payload: inline JSON object or @path/to/file.json")
    a("--job-delete", action="store_const", const=True, default=None,
      help="delete the named job instead of scheduling")
    a("--infer", action="store_const", const=True, default=None,
      help="enable the TPU inference stage")
    a("--infer-model", default=None, help="model registry key")
    a("--infer-backpressure-high", type=int, default=None,
      help="orchestrator pauses crawl distribution when live TPU workers' "
           "summed queue depth crosses this (0 = valve off; default 64)")
    a("--infer-backpressure-low", type=int, default=None,
      help="distribution resumes once the backlog drains below this "
           "(default 32)")
    # Crash recovery (orchestrator mode): the crawl journal + resume.
    a("--journal-dir", default=None,
      help="orchestrator crash-recovery journal directory (default: "
           "<dump-dir>/orch-journal/<crawl-id> when --dump-dir is set, "
           "else <storage-root>/<crawl-id>/orch-journal); an existing "
           "journal or persisted crawl is RESUMED, not re-seeded")
    a("--fresh", action="store_const", const=True, default=None,
      help="discard any existing crawl state + journal and re-seed "
           "(without this, orchestrator mode refuses to clobber an "
           "existing crawl)")
    # Media transcription (mode=transcribe): BASELINE config #4 — Whisper
    # over a crawl's media tree.
    a("--asr-pretrained-dir", default=None,
      help="local HF Whisper checkpoint dir (weights + optional "
           "tokenizer.json for text output)")
    a("--transcribe-input", default=None,
      help="dir scanned recursively for .wav media (e.g. a crawl's "
           "media/ tree), or a single file")
    a("--transcribe-output", default=None,
      help="transcripts JSONL path (default <input>/transcripts.jsonl)")
    a("--asr-batch-size", type=int, default=None,
      help="waveform batch per device dispatch (default 8; also the top "
           "window-count bucket of the ASR worker)")
    # Media/ASR serving (`media/`): crawl-side bridge + mode=asr-worker.
    a("--media-bridge", action="store_const", const=True, default=None,
      help="publish crawled audio refs to the media topic "
           "(tpu-media-batches) so a mode=asr-worker transcribes them; "
           "needs --skip-media false")
    a("--media-batch-size", type=int, default=None,
      help="audio refs per AudioBatchMessage (default 8)")
    a("--media-deadline-ms", type=int, default=None,
      help="flush a partial audio-ref batch after this long (default 250)")
    a("--asr-window-buckets", default=None,
      help="comma-separated window-count buckets the ASR worker compiles "
           "(one Whisper program per bucket; default: powers of two up "
           "to --asr-batch-size)")
    a("--asr-max-windows-per-file", type=int, default=None,
      help="cap on 30 s windows taken from one media file (0 = "
           "unbounded); keeps an hour-long video from starving queued "
           "neighbors")
    a("--slo-asr-batch-p95-ms", type=float, default=None,
      help="SLO budget on the ASR worker's per-group processing p95 in "
           "ms (asr_worker.process/coalesce spans; breach -> "
           "slo_breach_total{slo=asr_batch}; 0 = off)")
    a("--infer-batch-size", type=int, default=None)
    # Serving mesh (`parallel:` config block; docs/tpu.md "Multi-chip
    # serving").  Defaults = single-device serving; the flags feed
    # parallel.mesh.best_mesh_config/make_mesh via
    # inference.worker.build_serving_mesh in the tpu-worker (and
    # --bus-serve standalone) modes.
    a("--mesh-data", type=int, default=None,
      help="data-parallel mesh axis (dp): batches shard across this many "
           "chips; 0 = auto (devices / (seq*tensor)) once a mesh is on, "
           "and with every mesh flag at its default serving stays "
           "single-device")
    a("--mesh-seq", type=int, default=None,
      help="sequence-parallel mesh axis (sp); default 1")
    a("--mesh-tensor", type=int, default=None,
      help="tensor-parallel mesh axis (tp); default 1")
    a("--mesh-devices", type=int, default=None,
      help="devices the serving mesh spans: 0 (default) = off unless an "
           "axis flag asks for >1, -1 = all visible devices, N = the "
           "first N visible devices (CPU recipe: XLA_FLAGS="
           "--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu)")
    a("--infer-attention", default=None,
      help="attention dispatch: auto (flash past the length threshold on "
           "TPU) | xla | flash")
    a("--infer-moe-dispatch", default=None, choices=["dense", "capacity"],
      help="Switch-MoE dispatch for MoE checkpoints: dense (exact, "
           "n_experts× MLP FLOPs) | capacity (Switch static-slot packing,"
           " ~1.25× FLOPs)")
    a("--infer-param-dtype", default=None,
      help="cast float params at engine startup (e.g. bfloat16) — halves "
           "weight HBM traffic when serving; empty keeps the f32 layout")
    a("--infer-quantize", default=None,
      help="quantize the projection GEMMs at engine startup ('int8' = "
           "dynamic per-token activation scales; 'int8_static' = "
           "calibrated per-tensor scales that fuse the quantize into the "
           "producer epilogue; empty keeps the float path; train-head "
           "always ignores this)")
    # Classifier fine-tune (mode=train-head): crawl JSONL + labels ->
    # orbax checkpoint the engine reloads via --head-checkpoint.
    a("--train-posts", default=None,
      help="crawl posts JSONL (train-head mode)")
    a("--train-lora-rank", type=int, default=None,
      help="0 (default) fine-tunes only the classifier head on the frozen "
           "encoder; >0 additionally trains rank-N LoRA adapters on the "
           "projection GEMMs and saves the merged float checkpoint "
           "(use when the pretrained embedding space can't separate the "
           "classes)")
    a("--train-scope", default=None, choices=["head", "lora", "full"],
      help="what to train: head (frozen-encoder features, default), "
           "lora (rank from --train-lora-rank), or full (every encoder "
           "weight through make_train_step: AdamW+warmup+clipping, MoE "
           "aux loss, --train-grad-accum microbatching)")
    a("--train-grad-accum", type=int, default=None,
      help="gradient-accumulation microbatch count for --train-scope "
           "full (1 = off)")
    a("--train-state-dir", default=None,
      help="--train-scope full: checkpoint params+optimizer state per "
           "epoch here and RESUME from the newest epoch on restart")
    a("--train-labels", default=None,
      help='labels JSONL: {"post_uid": ..., "label": int|str} per line')
    a("--head-checkpoint", default=None,
      help="orbax checkpoint dir (written by train-head, read by "
           "tpu-worker)")
    a("--train-epochs", type=int, default=None)
    a("--train-lr", type=float, default=None)
    # Embedding clustering (mode=cluster): BASELINE config #5's closing
    # move — crawl/inference JSONL -> TPU k-means -> cluster assignments.
    a("--cluster-input", default=None,
      help="JSONL rows with an 'embedding' field (TPU worker results) or "
           "text fields (embedded on the fly)")
    a("--cluster-k", type=int, default=None)
    a("--cluster-iters", type=int, default=None)
    a("--cluster-output", default=None, help="output JSON path")
    # Streaming clustering (mode=cluster-worker, `cluster/`): the online
    # k-means serving worker consuming embedding-carrying result batches
    # from TOPIC_INFERENCE_RESULTS (--cluster-k is shared with the
    # offline mode above).
    a("--cluster-serve", action="store_const", const=True, default=None,
      help="declare a clustering stage attached to this deployment: "
           "serve-mode brokers pull-enable TOPIC_INFERENCE_RESULTS so a "
           "cluster worker's frames requeue across its restarts, and a "
           "TPU worker with --no-publish-embeddings is rejected loudly")
    a("--cluster-buckets", nargs="+", type=int, default=None,
      help="row-count buckets for the k-means mini-batch step (one "
           "compiled program per bucket; default 64 256)")
    a("--cluster-checkpoint-every", type=int, default=None,
      help="checkpoint centroids atomically every N committed batches "
           "(default 8; 0 disables the count trigger — graceful stop "
           "still checkpoints)")
    a("--cluster-min-fraction", type=float, default=None,
      help="a cluster is under-populated below this fraction of the "
           "uniform share (default 0.5) — the frontier-priority signal "
           "on TOPIC_CLUSTERS")
    a("--no-publish-embeddings", dest="publish_embeddings",
      action="store_const", const=False, default=None,
      help="strip embedding vectors from result batches published on "
           "TOPIC_INFERENCE_RESULTS (bus bandwidth; the JSONL "
           "write_embeddings knob is independent).  Rejected when "
           "clustering is enabled (--cluster-serve): the cluster worker "
           "consumes those embeddings")
    a("--generate-code", action="store_true",
      help="run the Telegram auth bootstrap (TG_* env vars) and write "
           "credentials.json under --tdlib-dir, then exit (alias: "
           "--mode gen-code)")
    a("--tdlib-dir", default=None,
      help="client-side auth/credentials dir (default .tdlib) — gen-code "
           "writes credentials.json here, pools read it back")
    # DC gateway (mode=dc-gateway): the deployable server side of the
    # native wire protocol (`clients/dc_gateway.py`; the reference's
    # Telegram-DC seam, `telegramhelper/client.go:319-377`).
    a("--gateway-listen", default=None,
      help="host:port the gateway binds (default 127.0.0.1:8443; "
           "port 0 = kernel-assigned, see --gateway-address-file)")
    a("--gateway-tls", action="store_const", const=True, default=None,
      help="serve TLS; without --gateway-tls-cert a self-signed pair is "
           "minted under <storage-root>/tls")
    a("--gateway-tls-cert", default=None, help="PEM cert chain path")
    a("--gateway-tls-key", default=None, help="PEM private key path")
    a("--gateway-accounts", default=None,
      help="accounts JSON ({'accounts': [{phone_number, code, password}]});"
           " empty = single-tenant via --gateway-expected-code")
    a("--gateway-expected-code", default=None,
      help="auth code accepted for any phone when no accounts file is set")
    a("--gateway-expected-password", default=None,
      help="2FA password leg for the single-tenant configuration")
    a("--gateway-seed-json", default=None,
      help="inline store JSON or @path/to/store.json (tiny deployments; "
           "--tdlib-database-url supplies a tarball/dir store instead)")
    a("--gateway-address-file", default=None,
      help="write host:port here once bound (discovery for port 0)")
    a("--gateway-max-connections", type=int, default=None,
      help="cap on concurrent connection threads (default 256, 0 = "
           "unlimited); beyond it new connects are closed immediately")
    a("--gateway-dc-id", type=int, default=None,
      help="this gateway's DC id (default 1); accounts whose dc_id "
           "differs get 303 PHONE_MIGRATE_<home> at the phone step")
    a("--gateway-wire", default=None, choices=["dct", "mtproto"],
      help="wire protocol: dct (DCT-v1 frames, default) or mtproto "
           "(MTProto 2.0: auth-key handshake + AES-IGE messages, "
           "`native/mtproto.h`); mtproto writes the server public key to "
           "<address-file>.pubkey for clients (--dc-pubkey-file)")
    a("--version", action="store_true")
    return p


# flag dest -> dotted config key (the viper BindPFlag table,
# `main.go:813-854`)
_KEY_MAP = {
    "log_level": "logging.level",
    "log_json": "logging.json",
    "mode": "distributed.mode",
    "worker_id": "distributed.worker_id",
    "concurrency": "crawler.concurrency",
    "timeout": "crawler.timeout",
    "user_agent": "crawler.useragent",
    "output": "output.format",
    "storage_root": "storage.root",
    "min_post_date": "crawler.minpostdate",
    "time_ago": "crawler.timeago",
    "max_crawl_duration": "crawler.maxcrawlduration",
    "date_between": "crawler.datebetween",
    "sample_size": "crawler.samplesize",
    "tdlib_database_url": "tdlib.database_url",
    "tdlib_database_urls": "tdlib.database_urls",
    "tdlib_verbosity": "tdlib.verbosity",
    "min_users": "crawler.minusers",
    "crawl_id": "crawler.crawlid",
    "crawl_label": "crawler.crawllabel",
    "max_comments": "crawler.maxcomments",
    "max_depth": "crawler.maxdepth",
    "max_posts": "crawler.maxposts",
    "max_pages": "crawler.maxpages",
    "skip_media": "crawler.skipmedia",
    "youtube_api_key": "youtube.api_key",
    "platform": "crawler.platform",
    "sampling": "crawler.sampling",
    "seed_size": "crawler.seedsize",
    "walkback_rate": "crawler.walkback_rate",
    "min_channel_videos": "crawler.min_channel_videos",
    "null_config": "crawler.null_config",
    "exit_on_complete": "crawler.exit_on_complete",
    "tandem_crawl": "crawler.tandem_crawl",
    "validate_only": "crawler.validate_only",
    "validator_request_rate": "crawler.validator_request_rate",
    "validator_request_jitter_ms": "crawler.validator_request_jitter_ms",
    "validator_claim_batch_size": "crawler.validator_claim_batch_size",
    "validator_timeout": "crawler.validator_timeout",
    "validator_transport": "crawler.validator_transport",
    "validator_base_url": "crawler.validator_base_url",
    "combine_files": "crawler.combine_files",
    "combine_watch_dir": "crawler.combine_watch_dir",
    "combine_temp_dir": "crawler.combine_temp_dir",
    "combine_write_dir": "crawler.combine_write_dir",
    "combine_trigger_size": "crawler.combine_trigger_size",
    "combine_hard_cap": "crawler.combine_hard_cap",
    "object_store": "crawler.object_store_url",
    "urls": "crawler.urls",
    "url_file": "crawler.url_file",
    "bus_address": "distributed.bus_address",
    "bus_serve": "distributed.bus_serve",
    "bus_spool_dir": "bus.spool_dir",
    "bus_shards": "bus.shards",
    "bus_shard_addresses": "bus.shard_addresses",
    "bus_outbox_max_frames": "bus.outbox_max_frames",
    "bus_ack_timeout_s": "bus.ack_timeout_s",
    "bus_max_attempts": "bus.max_attempts",
    "job_name": "job.name",
    "job_due_s": "job.due_s",
    "job_repeat_s": "job.repeat_s",
    "job_data": "job.data",
    "job_delete": "job.delete",
    "metrics_port": "observability.metrics_port",
    "profiler_port": "observability.profiler_port",
    "trace_buffer": "observability.trace_buffer",
    "slow_trace_ms": "observability.slow_trace_ms",
    "dump_dir": "observability.dump_dir",
    "flight_buffer": "observability.flight_buffer",
    "telemetry_interval": "observability.telemetry_interval_s",
    "slo_batch_p95_ms": "observability.slo_batch_p95_ms",
    "slo_queue_wait_ms": "observability.slo_queue_wait_ms",
    "slo_batch_age_ms": "observability.slo_batch_age_ms",
    "profile_on_slow_ms": "observability.profile_on_slow_ms",
    "span_export_interval": "observability.span_export_interval_s",
    "span_export_max_spans": "observability.span_export_max_spans",
    "span_sample_rate": "observability.span_sample_rate",
    "timeseries_window": "observability.timeseries_window_s",
    "timeseries_max_samples": "observability.timeseries_max_samples",
    "alert_rules": "observability.alert_rules",
    "tenant": "crawler.tenant",
    "tenant_budgets": "observability.tenant_budgets",
    "autoscaler": "autoscaler.enabled",
    "autoscaler_pools": "autoscaler.pools",
    "autoscaler_min": "autoscaler.min_workers",
    "autoscaler_max": "autoscaler.max_workers",
    "autoscaler_up_cooldown": "autoscaler.up_cooldown_s",
    "autoscaler_down_cooldown": "autoscaler.down_cooldown_s",
    "autoscaler_stabilization": "autoscaler.stabilization_s",
    "autoscaler_eval_interval": "autoscaler.eval_interval_s",
    "autoscaler_worker_args": "autoscaler.worker_args",
    "loadgen_scenario": "loadgen.scenario",
    "loadgen_seed": "loadgen.seed",
    "loadgen_duration_s": "loadgen.duration_s",
    "loadgen_arrival": "loadgen.arrival",
    "loadgen_rate": "loadgen.rate_batches_per_s",
    "loadgen_platform_mix": "loadgen.platform_mix",
    "loadgen_gate": "loadgen.gate",
    "infer": "inference.enabled",
    "infer_model": "inference.model",
    "infer_backpressure_high": "distributed.inference_backpressure_high",
    "infer_backpressure_low": "distributed.inference_backpressure_low",
    "journal_dir": "orchestrator.journal_dir",
    "fresh": "orchestrator.fresh",
    "infer_batch_size": "inference.batch_size",
    "mesh_data": "parallel.data",
    "mesh_seq": "parallel.seq",
    "mesh_tensor": "parallel.tensor",
    "mesh_devices": "parallel.devices",
    "infer_attention": "inference.attention",
    "infer_moe_dispatch": "inference.moe_dispatch",
    "infer_param_dtype": "inference.param_dtype",
    "infer_quantize": "inference.quantize",
    "asr_pretrained_dir": "inference.asr_pretrained_dir",
    "transcribe_input": "transcribe.input",
    "transcribe_output": "transcribe.output",
    "asr_batch_size": "inference.asr_batch_size",
    "media_bridge": "media.enabled",
    "media_batch_size": "media.batch_size",
    "media_deadline_ms": "media.batch_deadline_ms",
    "asr_window_buckets": "media.window_buckets",
    "asr_max_windows_per_file": "media.max_windows_per_file",
    "slo_asr_batch_p95_ms": "observability.slo_asr_batch_p95_ms",
    "train_posts": "train.posts_file",
    "train_labels": "train.labels_file",
    "train_lora_rank": "train.lora_rank",
    "train_scope": "train.scope",
    "train_grad_accum": "train.grad_accum_steps",
    "train_state_dir": "train.state_dir",
    "head_checkpoint": "train.checkpoint_dir",
    "train_epochs": "train.epochs",
    "train_lr": "train.learning_rate",
    "cluster_input": "cluster.input_file",
    "cluster_k": "cluster.k",
    "cluster_iters": "cluster.iters",
    "cluster_output": "cluster.output_file",
    "cluster_serve": "cluster.enabled",
    "cluster_buckets": "cluster.buckets",
    "cluster_checkpoint_every": "cluster.checkpoint_every_batches",
    "cluster_min_fraction": "cluster.min_cluster_fraction",
    "publish_embeddings": "inference.publish_embeddings",
    "tdlib_dir": "tdlib.dir",
    "dc_address": "tdlib.dc_address",
    "dc_tls": "tdlib.dc_tls",
    "dc_tls_insecure": "tdlib.dc_tls_insecure",
    "dc_sni": "tdlib.dc_sni",
    "dc_wire": "tdlib.dc_wire",
    "dc_pubkey_file": "tdlib.dc_pubkey_file",
    "dc_table_file": "tdlib.dc_table_file",
    "gateway_listen": "gateway.listen",
    "gateway_dc_id": "gateway.dc_id",
    "gateway_wire": "gateway.wire",
    "gateway_tls": "gateway.tls",
    "gateway_tls_cert": "gateway.tls_cert",
    "gateway_tls_key": "gateway.tls_key",
    "gateway_accounts": "gateway.accounts",
    "gateway_expected_code": "gateway.expected_code",
    "gateway_expected_password": "gateway.expected_password",
    "gateway_seed_json": "gateway.seed_json",
    "gateway_address_file": "gateway.address_file",
    "gateway_max_connections": "gateway.max_connections",
}


def resolve_config(args: argparse.Namespace,
                   env=None) -> "tuple[CrawlerConfig, ConfigResolver]":
    """Apply the four-level precedence chain and build CrawlerConfig
    (`main.go:185-520`)."""
    flags = {key: getattr(args, dest) for dest, key in _KEY_MAP.items()}
    r = ConfigResolver(flags=flags, env=env, config_file=args.config)

    cfg = CrawlerConfig()
    cfg.concurrency = r.get_int("crawler.concurrency", 1)
    cfg.timeout = r.get_int("crawler.timeout", 30)
    cfg.user_agent = r.get_str("crawler.useragent", cfg.user_agent)
    cfg.output_format = r.get_str("output.format", "jsonl")
    cfg.storage_root = r.get_str("storage.root", "/tmp/crawl")
    cfg.sample_size = r.get_int("crawler.samplesize", 0)
    cfg.tdlib_database_url = r.get_str("tdlib.database_url")
    cfg.tdlib_database_urls = r.get_list("tdlib.database_urls")
    cfg.tdlib_verbosity = r.get_int("tdlib.verbosity", 1)
    cfg.tdlib_dir = r.get_str("tdlib.dir", ".tdlib")
    cfg.dc_address = r.get_str("tdlib.dc_address")
    cfg.dc_tls = r.get_bool("tdlib.dc_tls", False)
    cfg.dc_tls_insecure = r.get_bool("tdlib.dc_tls_insecure", False)
    cfg.dc_sni = r.get_str("tdlib.dc_sni")
    cfg.dc_wire = r.get_str("tdlib.dc_wire")
    cfg.dc_pubkey_file = r.get_str("tdlib.dc_pubkey_file")
    cfg.dc_table_file = r.get_str("tdlib.dc_table_file")
    cfg.min_users = r.get_int("crawler.minusers", 100)
    cfg.crawl_id = r.get_str("crawler.crawlid") or generate_crawl_id()
    cfg.crawl_label = r.get_str("crawler.crawllabel")
    cfg.tenant = r.get_str("crawler.tenant")
    cfg.max_comments = r.get_int("crawler.maxcomments", -1)
    cfg.max_depth = r.get_int("crawler.maxdepth", -1)
    cfg.max_posts = r.get_int("crawler.maxposts", -1)
    cfg.max_pages = r.get_int("crawler.maxpages", 108000)
    cfg.skip_media_download = r.get_bool("crawler.skipmedia", False)
    cfg.youtube_api_key = r.get_str("youtube.api_key")
    cfg.platform = r.get_str("crawler.platform", "telegram")
    cfg.sampling_method = r.get_str("crawler.sampling", "channel")
    cfg.seed_size = r.get_int("crawler.seedsize", 0)
    cfg.walkback_rate = r.get_int("crawler.walkback_rate", 15)
    cfg.min_channel_videos = r.get_int("crawler.min_channel_videos", 10)
    cfg.null_config = r.get_str("crawler.null_config", "")
    if cfg.null_config == "{}":
        cfg.null_config = ""
    cfg.exit_on_complete = r.get_bool("crawler.exit_on_complete", False)
    cfg.tandem_crawl = r.get_bool("crawler.tandem_crawl", False)
    cfg.validate_only = r.get_bool("crawler.validate_only", False)
    cfg.validator_request_rate = r.get_float(
        "crawler.validator_request_rate", 6.0)
    cfg.validator_request_jitter_ms = r.get_int(
        "crawler.validator_request_jitter_ms", 200)
    cfg.validator_claim_batch_size = r.get_int(
        "crawler.validator_claim_batch_size", 10)
    cfg.validator_transport = r.get_str(
        "crawler.validator_transport", "urllib")
    cfg.validator_base_url = r.get_str(
        "crawler.validator_base_url", "https://t.me")
    cfg.combine_files = r.get_bool("crawler.combine_files", False)
    cfg.combine_watch_dir = r.get_str("crawler.combine_watch_dir",
                                      "/tmp/watch-files")
    cfg.combine_temp_dir = r.get_str("crawler.combine_temp_dir",
                                     "/tmp/temp-files")
    cfg.combine_write_dir = r.get_str("crawler.combine_write_dir",
                                      "/tmp/combine-write")
    cfg.combine_trigger_size = r.get_int("crawler.combine_trigger_size",
                                         170) * 1024 * 1024
    cfg.combine_hard_cap = r.get_int("crawler.combine_hard_cap",
                                     200) * 1024 * 1024
    cfg.object_store_url = r.get_str("crawler.object_store_url", "")
    cfg.inference.enabled = r.get_bool("inference.enabled", False)
    model = r.get_str("inference.model")
    if model:
        cfg.inference.embed_model = model
    batch = r.get_int("inference.batch_size", 0)
    if batch:
        cfg.inference.batch_size = batch
    buckets = r.get_list("inference.bucket_sizes")
    if buckets:
        cfg.inference.bucket_sizes = [int(b) for b in buckets]
    cfg.inference.mesh_data = r.get_int("parallel.data", 0)
    cfg.inference.mesh_seq = r.get_int("parallel.seq", 1)
    cfg.inference.mesh_tensor = r.get_int("parallel.tensor", 1)
    cfg.inference.mesh_devices = r.get_int("parallel.devices", 0)
    cfg.inference.param_dtype = r.get_str("inference.param_dtype", "")
    cfg.inference.quantize = r.get_str("inference.quantize", "")
    cfg.inference.attention = r.get_str("inference.attention", "")
    cfg.inference.moe_dispatch = r.get_str("inference.moe_dispatch", "")
    cfg.inference.pretrained_dir = r.get_str(
        "inference.pretrained_dir", cfg.inference.pretrained_dir)
    cfg.inference.asr_pretrained_dir = r.get_str(
        "inference.asr_pretrained_dir", cfg.inference.asr_pretrained_dir)
    cfg.media.enabled = r.get_bool("media.enabled", False)
    cfg.media.batch_size = r.get_int("media.batch_size",
                                     cfg.media.batch_size)
    cfg.media.batch_deadline_ms = r.get_int("media.batch_deadline_ms",
                                            cfg.media.batch_deadline_ms)
    cfg.media.window_buckets = [int(b) for b in
                                r.get_list("media.window_buckets")]
    cfg.media.max_windows_per_file = r.get_int(
        "media.max_windows_per_file", cfg.media.max_windows_per_file)
    cfg.media.coalesce_batches = r.get_int("media.coalesce_batches",
                                           cfg.media.coalesce_batches)

    # Date windows (`main.go:432-471`): date-between wins over time-ago wins
    # over min-post-date.
    date_between = r.get_str("crawler.datebetween")
    time_ago = r.get_str("crawler.timeago")
    min_post_date = r.get_str("crawler.minpostdate")
    if date_between:
        cfg.date_between_min, cfg.date_between_max = \
            parse_date_between(date_between)
    elif time_ago:
        cfg.post_recency = parse_time_ago(time_ago)
    elif min_post_date:
        from datetime import datetime, timezone
        cfg.min_post_date = datetime.strptime(
            min_post_date, "%Y-%m-%d").replace(tzinfo=timezone.utc)

    duration = r.get_str("crawler.maxcrawlduration")
    if duration:
        cfg.max_crawl_duration_s = parse_duration(duration)
    vtimeout = r.get_str("crawler.validator_timeout")
    if vtimeout:
        cfg.validator_timeout_s = parse_duration(vtimeout)

    # Sampling-method validity matrix (`main.go` PersistentPreRunE ->
    # common/sampling_validation.go). Validate-only pods need no URLs, and
    # neither do the non-crawling service modes (TPU inference / training /
    # clustering).
    if not cfg.validate_only and r.get_str("distributed.mode", "") not in (
            "tpu-worker", "asr-worker", "cluster-worker", "train-head",
            "cluster", "bus", "job-submit", "transcribe", "dc-gateway",
            "gen-code"):
        validate_sampling_method(SamplingValidationInput(
            platform=cfg.platform, sampling_method=cfg.sampling_method,
            url_list=r.get_list("crawler.urls"),
            url_file=r.get_str("crawler.url_file"),
            mode=r.get_str("distributed.mode", ""),
            seed_size=cfg.seed_size, crawl_id=cfg.crawl_id))
    return cfg, r


def collect_urls(r: ConfigResolver) -> List[str]:
    """--urls + --url-file (`main.go:522-585`)."""
    urls = list(r.get_list("crawler.urls"))
    url_file = r.get_str("crawler.url_file")
    if url_file:
        urls.extend(read_urls_from_file(url_file))
    return urls


def main(argv: Optional[List[str]] = None, env=None) -> int:
    args = build_parser().parse_args(argv)
    if args.version:
        print("distributed_crawler_tpu v0.1.0")
        return 0
    if args.generate_code:
        # Auth bootstrap (`standalone/runner.go:68,77-192`): the alias IS
        # --mode gen-code — routed through the same resolver so gateway
        # settings from flags, env (CRAWLER_*), or config file all apply
        # (a raw-flag shortcut here silently minted against the embedded
        # engine whenever the gateway was configured via env/file).
        args.mode = "gen-code"
    try:
        cfg, r = resolve_config(args, env=env)
    except (ValueError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    setup_logging(r.get_str("logging.level", "info"),
                  json_output=r.get_bool("logging.json", False))
    # Tracer knobs apply to EVERY mode (the tracer is process-global and
    # the tpu-worker's own metrics server serves /traces from it too).
    from .utils import trace as _trace

    _trace.configure(
        capacity=r.get_int("observability.trace_buffer", 2048),
        slow_span_s=r.get_float("observability.slow_trace_ms", 0.0) / 1000.0)

    mode = r.get_str("distributed.mode", "")
    # Flight recorder: ring size + config fingerprint always; the crash
    # hooks (excepthooks + faulthandler) arm only when a dump dir is
    # configured — without one a dump is a no-op and nothing is hooked.
    from .utils import flight as _flight

    _flight.configure(
        capacity=r.get_int("observability.flight_buffer", 512),
        fingerprint={"mode": mode or "standalone",
                     "worker_id": r.get_str("distributed.worker_id"),
                     "platform": cfg.platform,
                     "crawl_id": cfg.crawl_id,
                     "bus_address": r.get_str("distributed.bus_address")})
    dump_dir = r.get_str("observability.dump_dir", "")
    if dump_dir:
        _flight.install(dump_dir)
    # Rolling time-series store (utils/timeseries.py): retention knobs
    # apply to every mode — worker self-samples and orchestrator fleet
    # folds land in the same process-global store behind /timeseries.
    from .utils import timeseries as _timeseries

    _timeseries.configure(
        max_samples=r.get_int("observability.timeseries_max_samples", 512),
        window_s=r.get_float("observability.timeseries_window_s", 900.0))
    # The on-demand /profile capture endpoint (`utils/profiling.py`)
    # writes its trace bundles next to the postmortem bundles; without a
    # dump dir it answers 503 with a clear error instead of capturing
    # into nowhere.
    from .utils import profiling as _profiling

    _profiling.configure(dump_dir=dump_dir)
    # Observability servers for every mode (`main.go:60-80` ran pprof
    # unconditionally) — EXCEPT the serving workers (tpu-worker /
    # asr-worker), where the worker's own start() owns the metrics port
    # (binding here too would EADDRINUSE its startup).
    if mode not in ("tpu-worker", "asr-worker", "cluster-worker"):
        metrics_port = r.get_int("observability.metrics_port", 0)
        if metrics_port:
            from .utils.metrics import serve_metrics
            serve_metrics(metrics_port)
        profiler_port = r.get_int("observability.profiler_port", 0)
        if profiler_port:
            # Guarded: unavailable/duplicate profiler logs a WARNING
            # instead of killing startup; shares jax's single profiler
            # session with the /profile capture endpoint.
            _profiling.start_profiler_server(profiler_port)
    urls = collect_urls(r)
    if cfg.validate_only and mode in ("", "standalone", "launch"):
        # The validator pod is a launch-router branch
        # (`dapr/standalone.go:276-314`); a bare `--validate-only` must
        # not fall through to a sequential crawl of nothing.
        mode = "launch"
    logger.info("starting", extra={"mode": mode or "standalone",
                                   "platform": cfg.platform,
                                   "url_count": len(urls)})
    try:
        if mode in ("", "standalone"):
            from .modes.common import create_state_manager, determine_crawl_id
            from .modes.standalone import start_standalone_mode
            temp = create_state_manager(cfg)
            exec_id, _ = determine_crawl_id(temp, cfg)
            sm, closer = _maybe_bridge(create_state_manager(cfg, exec_id),
                                       cfg, r)
            try:
                start_standalone_mode(urls, cfg, sm=sm)
            finally:
                closer()
        elif mode == "launch":  # the reference's dapr-standalone router
            from .modes.common import create_state_manager, determine_crawl_id
            from .modes.runner import launch
            temp = create_state_manager(cfg)
            exec_id, _ = determine_crawl_id(temp, cfg)
            sm, closer = _maybe_bridge(create_state_manager(cfg, exec_id),
                                       cfg, r)
            try:
                launch(urls, cfg, sm=sm)
            finally:
                closer()
        elif mode == "orchestrator":
            _run_orchestrator(urls, cfg, r)
        elif mode == "worker":
            _run_worker(cfg, r)
        elif mode == "job":  # the reference's dapr-job scheduled mode
            _run_job_service(cfg, r)
        elif mode == "job-submit":
            return _run_job_submit(r)
        elif mode == "tpu-worker":
            _run_tpu_worker(cfg, r)
        elif mode == "asr-worker":
            _run_asr_worker(cfg, r)
        elif mode == "cluster-worker":
            _run_cluster_worker(cfg, r)
        elif mode == "bus":
            # Dedicated broker process — the in-tree analog of the
            # reference's always-on Dapr sidecar (`daprstate.go:119-133`).
            if not r.get_str("distributed.bus_address"):
                print("error: bus mode requires --bus-address",
                      file=sys.stderr)
                return 2
            bus = _make_bus(r, serve=True)
            if r.get_str("bus.spool_dir", ""):
                # Durable broker: serve the dead-letter queue on the
                # metrics port (tools/dlq.py --url reads it).
                from .utils.metrics import set_dlq_provider
                set_dlq_provider(bus.dlq_snapshot)
            try:
                _serve_forever()
            finally:
                # Shutdown grace: REMOTE consumers can keep pulling while
                # the broker drains (unlike --bus-serve hosts, whose only
                # consumer is themselves and already exiting).  close()
                # must run even if the drain is interrupted (second ^C).
                try:
                    bus.drain(timeout_s=r.get_float(
                        "distributed.shutdown_drain_s", 30.0))
                finally:
                    bus.close()
        elif mode == "train-head":
            return _run_train_head(cfg, r)
        elif mode == "transcribe":
            return _run_transcribe(cfg, r)
        elif mode == "cluster":
            return _run_cluster(cfg, r)
        elif mode == "dc-gateway":
            _run_dc_gateway(cfg, r)
        elif mode == "gen-code":
            return _run_gen_code(r)
        else:
            print(f"error: unknown execution mode: {mode}", file=sys.stderr)
            return 2
    except CliConfigError as e:
        # Config-shaped errors raised by mode runners (missing --worker-id,
        # --bus-serve without --bus-address, …) — report like the
        # resolve_config errors above instead of a traceback.
        print(f"error: {e}", file=sys.stderr)
        return 2
    except KeyboardInterrupt:
        logger.info("interrupted, shutting down")
        return 130
    finally:
        # A pool installed by setup_pool_from_config is process-wide; for
        # in-process callers running main() repeatedly (tests, embedders)
        # it must be torn down here or the next run would silently crawl
        # the previous run's databases.
        from .crawl import shutdown_connection_pool
        shutdown_connection_pool()
    return 0


def _maybe_bridge(sm, cfg: CrawlerConfig, r: ConfigResolver):
    """--infer wraps the state manager with the crawl->TPU InferenceBridge
    so stored posts ship to `tpu-inference-batches`, and --media-bridge
    additionally wraps it with the crawl->ASR MediaBridge so stored audio
    refs ship to `tpu-media-batches`; returns (sm, closer).  The bridges
    publish over the gRPC bus when --bus-address is set (separate
    tpu-worker / asr-worker processes consume), else in-process."""
    if not (cfg.inference.enabled or cfg.media.enabled):
        # The closer owns the final sm.close() either way: modes receiving a
        # prebuilt sm never close it themselves (owns_sm=False), so without
        # this the completed-status metadata written after the last layer
        # would never be flushed to disk.
        return sm, sm.close
    bus = _make_bus(r)
    wrapped = sm
    if cfg.inference.enabled:
        from .inference.bridge import InferenceBridge
        wrapped = InferenceBridge(wrapped, bus, crawl_id=cfg.crawl_id,
                                  batch_size=cfg.inference.batch_size,
                                  deadline_s=cfg.inference.batch_deadline_ms
                                  / 1000.0,
                                  tenant=cfg.tenant)
    if cfg.media.enabled:
        # Outermost: the media hook (`notify_media_stored`) lands here,
        # store_post falls through to the InferenceBridge underneath.
        from .media.bridge import MediaBridge
        wrapped = MediaBridge(wrapped, bus, crawl_id=cfg.crawl_id,
                              batch_size=cfg.media.batch_size,
                              deadline_s=cfg.media.batch_deadline_ms
                              / 1000.0,
                              tenant=cfg.tenant)

    def closer():
        wrapped.close()  # each bridge flushes, then closes its inner
        try:
            bus.close()
        except Exception as e:
            logger.warning("bridge bus close failed: %s", e)

    return wrapped, closer


def _heartbeat_interval(r: "ConfigResolver") -> float:
    """The telemetry-heartbeat period, clamped so it can never exceed a
    third of the orchestrator's default liveness timeout (300 s): the
    heartbeat doubles as the liveness signal, and a period above the
    timeout would make `check_worker_health` flap healthy workers
    offline and re-queue their in-flight work forever."""
    interval = r.get_float("observability.telemetry_interval_s", 30.0)
    clamped = min(max(interval, 1.0), 90.0)
    if clamped != interval:
        logger.warning(
            "telemetry interval %.0fs clamped to %.0fs (heartbeats are "
            "the liveness signal; the orchestrator offlines workers "
            "silent past worker_timeout_s)", interval, clamped)
    return clamped


def _alert_rules(r: "ConfigResolver"):
    """The watchtower rule list from ``observability.alert_rules`` — a
    YAML list in the config file, or inline JSON / ``@path`` from the
    ``--alert-rules`` flag.  Configured rules replace their same-named
    defaults; a malformed rule is a config error (exit 2), not a
    silently-defaulted watchtower."""
    import json as _json

    from .utils.alerts import rules_from_config

    raw = r.get("observability.alert_rules")
    if isinstance(raw, str) and raw:
        if raw.startswith("@"):
            try:
                with open(raw[1:], "r", encoding="utf-8") as f:
                    raw = f.read()
            except OSError as e:
                raise CliConfigError(f"cannot read --alert-rules file: {e}")
        try:
            raw = _json.loads(raw)
        except ValueError as e:
            raise CliConfigError(f"--alert-rules is not valid JSON: {e}")
    try:
        return rules_from_config(raw or None)
    except ValueError as e:
        raise CliConfigError(f"bad alert rule: {e}")


def _tenant_budgets(r: "ConfigResolver"):
    """The per-tenant error budgets from ``observability.tenant_budgets``
    — a YAML mapping in the config file, or inline JSON / ``@path`` from
    the ``--tenant-budgets`` flag.  Returns the validated ``(budgets,
    window_s)`` pair; a malformed block is a config error (exit 2), not
    a silently-unenforced budget."""
    import json as _json

    from .orchestrator.tenants import budgets_from_config

    raw = r.get("observability.tenant_budgets")
    if isinstance(raw, str) and raw:
        if raw.startswith("@"):
            try:
                with open(raw[1:], "r", encoding="utf-8") as f:
                    raw = f.read()
            except OSError as e:
                raise CliConfigError(
                    f"cannot read --tenant-budgets file: {e}")
        try:
            raw = _json.loads(raw)
        except ValueError as e:
            raise CliConfigError(f"--tenant-budgets is not valid JSON: {e}")
    try:
        return budgets_from_config(raw or None)
    except ValueError as e:
        raise CliConfigError(f"bad tenant budget: {e}")


def _build_autoscaler(r: "ConfigResolver", orch, bus):
    """The elastic-fleet control plane for orchestrator mode
    (`orchestrator/autoscaler.py`): pool policies from ``autoscaler.pools``
    (JSON / ``@path``) or the single-pool shortcut knobs, actuated through
    a `SubprocessSupervisor` spawning ``--mode tpu-worker`` children that
    dial this orchestrator's broker.  Returns the started-but-not-ticking
    Autoscaler (caller runs start()/stop()), or None when disabled."""
    import json as _json
    import shlex as _shlex

    if not r.get_bool("autoscaler.enabled", False):
        return None
    bus_address = r.get_str("distributed.bus_address")
    shard_addresses = _parse_shard_addresses(r)
    if not bus_address and not shard_addresses:
        raise CliConfigError(
            "--autoscaler requires --bus-address (or "
            "--bus-shard-addresses on a partitioned control plane): "
            "spawned workers must be able to dial the broker(s)")
    from .orchestrator.autoscaler import (
        Autoscaler,
        PoolPolicy,
        SubprocessSupervisor,
        default_subprocess_argv,
        pools_from_config,
    )

    raw = r.get("autoscaler.pools")
    if isinstance(raw, str) and raw:
        if raw.startswith("@"):
            try:
                with open(raw[1:], "r", encoding="utf-8") as f:
                    raw = f.read()
            except OSError as e:
                raise CliConfigError(
                    f"cannot read --autoscaler-pools file: {e}")
        try:
            raw = _json.loads(raw)
        except ValueError as e:
            raise CliConfigError(
                f"--autoscaler-pools is not valid JSON: {e}")
    try:
        pools = pools_from_config(raw or None)
        if not pools:
            pools = [PoolPolicy(
                pool="tpu",
                min_workers=r.get_int("autoscaler.min_workers", 1),
                max_workers=r.get_int("autoscaler.max_workers", 4),
                up_cooldown_s=r.get_float("autoscaler.up_cooldown_s",
                                          30.0),
                down_cooldown_s=r.get_float("autoscaler.down_cooldown_s",
                                            60.0),
                stabilization_s=r.get_float("autoscaler.stabilization_s",
                                            30.0))]
            pools[0].validate()
    except ValueError as e:
        raise CliConfigError(f"bad autoscaler pool: {e}")
    extra = _shlex.split(r.get_str("autoscaler.worker_args", ""))
    supervisor = SubprocessSupervisor({
        p.pool: default_subprocess_argv(p.pool, bus_address,
                                        extra_args=extra,
                                        shard_addresses=shard_addresses
                                        or None)
        for p in pools})
    autoscaler = Autoscaler(
        supervisor, pools,
        eval_interval_s=r.get_float("autoscaler.eval_interval_s", 5.0),
        alerts_fn=orch.get_alerts)
    # The bus seam too: a remote autoscaler would subscribe exactly like
    # this (the in-process alerts_fn read stays authoritative).
    try:
        autoscaler.attach_bus(bus)
    except Exception as e:
        logger.warning("autoscaler TOPIC_ALERTS subscription failed: %s",
                       e)
    return autoscaler


class CliConfigError(ValueError):
    """A user-fixable configuration error raised by a mode runner; main()
    reports it as `error: …` (exit 2) instead of a traceback.  Keep this
    distinct from ValueError so genuine programming errors deep in the
    crawl/inference stack still surface with their tracebacks."""


def _serve_forever(poll_s: float = 1.0,
                   running: Optional[Callable[[], bool]] = None) -> None:
    """Block the main thread while a service's worker threads run; an
    optional ``running`` predicate ends the loop when it turns False.

    SIGTERM is mapped to KeyboardInterrupt for the duration, so a
    supervisor's stop (docker stop, kubelet) takes the same graceful
    close/drain path as ^C instead of killing mid-write; when a
    ``--dump-dir`` is configured the flight recorder writes its
    postmortem bundle FIRST (the graceful teardown may hang — the black
    box must already be on disk)."""
    import signal as _signal
    import time as _time

    from .utils import flight as _flight

    def _term(_sig, _frm):
        _flight.dump("sigterm")  # no-op without a configured dump dir
        raise KeyboardInterrupt

    prev = None
    installed = False
    try:
        prev = _signal.signal(_signal.SIGTERM, _term)
        installed = True  # prev may be None (non-Python disposition):
        # restore is keyed on INSTALLATION, not on prev's truthiness.
    except ValueError:
        pass  # not the main thread (tests drive this inline)
    try:
        while running is None or running():
            _time.sleep(poll_s)
    finally:
        if installed:
            try:
                _signal.signal(_signal.SIGTERM,
                               prev if prev is not None
                               else _signal.SIG_DFL)
            except ValueError:
                pass


def _gen_code(tdlib_dir: str = ".tdlib", env=None, server_addr: str = "",
              tls: bool = False, tls_insecure: bool = False,
              sni: str = "", wire: str = "",
              server_pubkey_file: str = "") -> int:
    """Auth bootstrap (`standalone/runner.go:77-192`): drive the ladder
    from TG_* env — against a remote dc-gateway when --dc-address is set,
    else the embedded auth-enabled engine — and write credentials.json
    under ``tdlib_dir`` for pools to consume."""
    from .clients.native import NativeTelegramClient, generate_pcode

    client = None
    try:
        if server_addr:
            client = NativeTelegramClient(
                server_addr=server_addr, tls=tls,
                tls_insecure=tls_insecure, sni=sni, wire=wire,
                server_pubkey_file=server_pubkey_file, conn_id="gen-code")
        path = generate_pcode(tdlib_dir=tdlib_dir, env=env, client=client)
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    finally:
        if client is not None:
            client.close()
    print(f"credentials saved to {path}")
    return 0


def _run_gen_code(r: ConfigResolver) -> int:
    return _gen_code(
        tdlib_dir=r.get_str("tdlib.dir", ".tdlib"),
        env=dict(r._env),
        server_addr=r.get_str("tdlib.dc_address"),
        tls=r.get_bool("tdlib.dc_tls", False),
        tls_insecure=r.get_bool("tdlib.dc_tls_insecure", False),
        sni=r.get_str("tdlib.dc_sni"),
        wire=r.get_str("tdlib.dc_wire"),
        server_pubkey_file=r.get_str("tdlib.dc_pubkey_file"))


def _run_dc_gateway(cfg: CrawlerConfig, r: ConfigResolver) -> None:
    """mode=dc-gateway: host the deployable wire-protocol server
    (`clients/dc_gateway.py`) — the production counterpart of the C++
    client's remote mode (the reference's Telegram-DC seam)."""
    from .clients.dc_gateway import (
        DEFAULT_MAX_CONNECTIONS,
        DcGateway,
        load_accounts,
    )
    from .utils.metrics import clear_status_provider, set_status_provider

    listen = r.get_str("gateway.listen", "127.0.0.1:8443")
    host, _, port_s = listen.rpartition(":")
    if not host or not port_s.isdigit():
        raise CliConfigError(
            f"--gateway-listen must be host:port, got {listen!r}")
    accounts = None
    accounts_path = r.get_str("gateway.accounts")
    if accounts_path:
        accounts = load_accounts(accounts_path)
    seed_json = r.get_str("gateway.seed_json")
    if seed_json.startswith("@"):
        with open(seed_json[1:], "r", encoding="utf-8") as f:
            seed_json = f.read()
    gw = DcGateway(
        host=host, port=int(port_s),
        tls=r.get_bool("gateway.tls", False),
        tls_cert=r.get_str("gateway.tls_cert"),
        tls_key=r.get_str("gateway.tls_key"),
        accounts=accounts,
        expected_code=r.get_str("gateway.expected_code", "13579"),
        expected_password=r.get_str("gateway.expected_password"),
        seed_json=seed_json,
        seed_source=cfg.tdlib_database_url,
        store_root=os.path.join(cfg.storage_root or ".", "dc-gateway"),
        address_file=r.get_str("gateway.address_file"),
        wire=r.get_str("gateway.wire", "dct") or "dct",
        max_connections=r.get_int("gateway.max_connections",
                                  DEFAULT_MAX_CONNECTIONS),
        dc_id=r.get_int("gateway.dc_id", 1),
    ).start()
    set_status_provider(gw.status)
    try:
        _serve_forever()
    finally:
        clear_status_provider(gw.status)
        gw.close()


def _bus_outbox_config(r: ConfigResolver, who: str):
    """The durable-outbox config for one publisher, or None when bus
    durability is off (`bus.spool_dir` empty).  The spill WAL lands under
    ``<spool-dir>/outbox/<who>`` — a path per publisher role, so a
    co-hosted orchestrator and worker never share a WAL."""
    spool_dir = r.get_str("bus.spool_dir", "") if r else ""
    if not spool_dir:
        return None
    from .bus.outbox import OutboxConfig
    return OutboxConfig(
        dir=os.path.join(spool_dir, "outbox", who or "client"),
        max_frames=r.get_int("bus.outbox_max_frames", 1024))


def _parse_shard_addresses(r: ConfigResolver) -> list:
    """The partitioned-bus shard list from ``bus.shard_addresses``
    (comma string from --bus-shard-addresses, or a YAML list), validated
    LOUDLY: a declared ``bus.shards`` count must match (a truncated
    address list would silently re-deal the consistent-hash ring), and
    duplicate addresses are rejected (two shards sharing one broker —
    and therefore one WAL spool — cross-contaminate crash recovery)."""
    get = getattr(r, "get", None)  # partial test resolvers
    raw = get("bus.shard_addresses") if callable(get) else None
    if isinstance(raw, str):
        addrs = [a.strip() for a in raw.split(",") if a.strip()]
    elif isinstance(raw, (list, tuple)):
        addrs = [str(a).strip() for a in raw if str(a).strip()]
    else:
        addrs = []
    declared = r.get_int("bus.shards", 0) if r else 0
    if declared > 1 and not addrs:
        raise CliConfigError(
            "--bus-shards needs --bus-shard-addresses (one gRPC address "
            "per broker shard)")
    if not addrs:
        return []
    if declared and declared != len(addrs):
        raise CliConfigError(
            f"--bus-shards={declared} but --bus-shard-addresses names "
            f"{len(addrs)} shard(s) — a mismatched list would re-deal "
            f"the consistent-hash ring; fix one of them")
    if len(set(addrs)) != len(addrs):
        raise CliConfigError(
            f"duplicate addresses in --bus-shard-addresses {addrs!r}: "
            f"two shards sharing one broker (and its WAL spool) would "
            f"cross-contaminate each other's crash recovery")
    return addrs


def _make_bus(r: ConfigResolver, serve: bool = False):
    """Bus selection: --bus-address set -> gRPC DCN transport (orchestrator
    hosts a GrpcBusServer with the work queue pull-enabled; workers dial a
    RemoteBus with competing-consumer pull).  Unset -> in-process bus.
    With `bus.spool_dir` set, the hosted broker journals pull-topic frames
    + dead letters in the WAL spool and client publishes ride a durable
    outbox (docs/operations.md "Bus durability & dead letters").
    With `bus.shard_addresses` set, the CLIENT side becomes a
    `PartitionedBus` over every shard (docs/operations.md "Partitioned
    bus & sharded frontier") — serving stays one broker per process."""
    shard_addrs = _parse_shard_addresses(r) if r else []
    if shard_addrs and serve:
        raise CliConfigError(
            "--bus-serve (and --mode bus) host ONE broker shard per "
            "process: run one --mode bus process per shard address, each "
            "with its OWN --bus-spool-dir, and point clients at "
            "--bus-shard-addresses")
    if shard_addrs and r.get_str("distributed.bus_address"):
        # Silently preferring one would leave the operator believing
        # traffic rides the other — the loud-misconfiguration rule.
        raise CliConfigError(
            "--bus-address and --bus-shard-addresses are mutually "
            "exclusive: pass the single broker OR the shard list, "
            "not both")
    if shard_addrs:
        import dataclasses

        from .bus.grpc_bus import RemoteBus
        from .bus.partition import (
            PartitionedBus,
            ShardMap,
            default_shard_ids,
        )

        sids = default_shard_ids(len(shard_addrs))
        who = r.get_str("distributed.worker_id") \
            or r.get_str("distributed.mode") or "client"
        base_cfg = _bus_outbox_config(r, who)
        shard_outbox = None
        if base_cfg is not None:
            # Per-shard spill WALs under the publisher's outbox dir —
            # distinct by construction (PartitionedBus re-validates).
            def shard_outbox(sid, _base=base_cfg):  # noqa: E731
                return dataclasses.replace(
                    _base, dir=os.path.join(_base.dir, sid))
        endpoints = {sid: RemoteBus(addr)
                     for sid, addr in zip(sids, shard_addrs)}
        logger.info("partitioned bus: %d shard(s) %s (durable outboxes: "
                    "%s)", len(shard_addrs), shard_addrs,
                    "on" if base_cfg is not None else "off")
        bus = PartitionedBus(endpoints, ShardMap(sids),
                             outbox=shard_outbox, name=who)
        # Any process holding a partitioned client serves the /shards
        # table on its metrics port (per-shard breaker/outbox/parked
        # state — the operator's "which shard is limping" read).
        from .utils.metrics import set_shards_provider

        set_shards_provider(bus.snapshot)
        return bus
    address = r.get_str("distributed.bus_address") if r else ""
    if not address:
        if r and r.get_str("bus.spool_dir", ""):
            # The durability switch only applies to the gRPC broker; an
            # operator who set it without a bus address must not believe
            # frames are being journaled when they are not.
            logger.warning(
                "bus.spool_dir is set but distributed.bus_address is "
                "empty: the in-process bus has no spool/outbox/DLQ — "
                "bus durability is INACTIVE")
        from .bus.inmemory import InMemoryBus
        bus = InMemoryBus(sync=False)
        bus.start()
        return bus
    if serve:
        from .bus.grpc_bus import GrpcBusServer
        from .bus.messages import (
            TOPIC_INFERENCE_BATCHES,
            TOPIC_JOBS,
            TOPIC_MEDIA_BATCHES,
            TOPIC_WORK_QUEUE,
        )
        server = GrpcBusServer(
            address,
            spool_dir=r.get_str("bus.spool_dir", "") or None,
            ack_timeout_s=r.get_float("bus.ack_timeout_s", 300.0),
            max_attempts=r.get_int("bus.max_attempts", 5))
        # Pre-enable the pull (competing-consumer) topics so frames
        # published before the first consumer connects are queued, not
        # dropped.  Fan-out topics (results/status/commands/transcripts)
        # stay local-dispatch only — pull-enabling them on a broker
        # nobody drains would accumulate frames without bound.
        server.enable_pull(TOPIC_WORK_QUEUE)
        server.enable_pull(TOPIC_INFERENCE_BATCHES)
        server.enable_pull(TOPIC_MEDIA_BATCHES)
        server.enable_pull(TOPIC_JOBS)
        get_bool = getattr(r, "get_bool", None)  # partial test resolvers
        if (callable(get_bool) and get_bool("cluster.enabled", False)) \
                or r.get_str("distributed.mode", "") == "cluster-worker":
            # A clustering stage is attached (`--cluster-serve` /
            # `cluster.enabled`, or this IS the cluster worker hosting
            # its own broker): the result stream becomes a pull topic so
            # a dead cluster worker's un-acked frames requeue.  Gated,
            # because pull-enabling it with no consumer would accumulate
            # every result frame forever.
            from .bus.messages import TOPIC_INFERENCE_RESULTS

            server.enable_pull(TOPIC_INFERENCE_RESULTS)
        server.start()
        return server
    from .bus.grpc_bus import RemoteBus
    who = r.get_str("distributed.worker_id") \
        or r.get_str("distributed.mode") or "client"
    return RemoteBus(address, outbox=_bus_outbox_config(r, who))


def _make_serving_bus(r: ConfigResolver) -> "_ServingBus":
    """Broker + loopback consumer for a --bus-serve process; raises
    CliConfigError when --bus-address is missing."""
    address = r.get_str("distributed.bus_address")
    if not address:
        raise CliConfigError("--bus-serve requires --bus-address")
    server = _make_bus(r, serve=True)
    # The loopback client half goes through _make_bus too, so it inherits
    # the durable-outbox wiring when bus durability is on.
    return _ServingBus(server, _make_bus(r))


class _ServingBus:
    """A GrpcBusServer plus a loopback RemoteBus client: lets one process
    both HOST the broker and CONSUME from it (``--bus-serve`` on the TPU
    worker — the standalone analog of the reference's always-on Dapr
    sidecar).  The bus API delegates to the client; close() tears down
    client then server."""

    def __init__(self, server, client):
        self._server = server
        self._client = client

    def publish(self, topic, payload):
        self._client.publish(topic, payload)

    def subscribe(self, topic, handler, **kw):
        self._client.subscribe(topic, handler, **kw)

    def close(self):
        try:
            self._client.close()
        finally:
            self._server.close()


def _run_orchestrator(urls: List[str], cfg: CrawlerConfig,
                      r: ConfigResolver) -> None:
    """`main.go:647-706`."""
    from .modes.common import create_state_manager
    from .orchestrator import CrawlJournal, Orchestrator
    from .orchestrator.orchestrator import OrchestratorConfig
    broker = _make_bus(r, serve=True)
    bus = broker
    outbox_cfg = _bus_outbox_config(r, "orchestrator")
    if outbox_cfg is not None and hasattr(broker, "dlq_snapshot"):
        # Bus durability on: the orchestrator's LOCAL publishes ride a
        # durable outbox too (a wedged in-process broker path degrades
        # to buffered instead of erroring the dispatch tick, and the
        # dispatch valve watches the buffer depth), and the broker's
        # dead-letter queue is served at /dlq.
        from .bus.outbox import OutboxBus
        from .utils.metrics import set_dlq_provider
        bus = OutboxBus(broker, outbox_cfg, name="orchestrator",
                        close_inner=False)
        set_dlq_provider(broker.dlq_snapshot)
    sm = create_state_manager(cfg, cfg.crawl_id)
    ocfg = OrchestratorConfig(
        inference_backpressure_high=r.get_int(
            "distributed.inference_backpressure_high", 64),
        inference_backpressure_low=r.get_int(
            "distributed.inference_backpressure_low", 32),
        state_retry_attempts=r.get_int("resilience.state_retry_attempts", 2),
        state_breaker_threshold=r.get_int(
            "resilience.state_breaker_threshold", 5),
        state_breaker_recovery_s=r.get_float(
            "resilience.state_breaker_recovery_s", 15.0),
        publish_retry_attempts=r.get_int(
            "resilience.publish_retry_attempts", 3))
    # Crash-recovery journal (docs/operations.md "Crash recovery &
    # resiliency policies"): default location follows --dump-dir, falling
    # back to the crawl's storage root.
    journal_dir = r.get_str("orchestrator.journal_dir", "")
    if not journal_dir:
        # Default paths are keyed by crawl id so a shared dump dir never
        # hands one crawl another crawl's journal (the orchestrator also
        # verifies the journal's recorded crawl id before resuming).
        dump_dir = r.get_str("observability.dump_dir", "")
        crawl = cfg.crawl_id or "crawl"
        journal_dir = (
            os.path.join(dump_dir, "orch-journal", crawl) if dump_dir
            else os.path.join(cfg.storage_root or "/tmp/crawl", crawl,
                              "orch-journal"))
    orch = Orchestrator(cfg.crawl_id, cfg, bus, sm, ocfg=ocfg,
                        journal=CrawlJournal(journal_dir),
                        alert_rules=_alert_rules(r))
    from .utils.metrics import (
        set_alerts_provider,
        set_autoscaler_provider,
        set_cluster_provider,
        set_dtraces_provider,
        set_status_provider,
        set_tenants_provider,
    )
    set_status_provider(orch.get_status)  # /status (`orchestrator.go:596`)
    set_cluster_provider(orch.get_cluster)  # /cluster fleet view
    set_dtraces_provider(orch.get_dtraces)  # /dtraces distributed traces
    set_alerts_provider(orch.get_alerts)  # /alerts watchtower surface
    # /tenants: per-tenant spend + error budgets over the fleet folds;
    # budgets validated loudly from config (exit 2 on a typo'd block).
    budgets, budget_window_s = _tenant_budgets(r)
    orch.watchtower.tenants.configure(budgets=budgets,
                                      window_s=budget_window_s)
    set_tenants_provider(orch.get_tenants)
    # Elastic fleet (--autoscaler): alert-actuated tpu-worker children
    # against this broker, decisions served at /autoscaler.
    autoscaler = _build_autoscaler(r, orch, bus)
    if autoscaler is not None:
        set_autoscaler_provider(autoscaler.snapshot)
    orch.start(urls, fresh=r.get_bool("orchestrator.fresh", False))
    if autoscaler is not None:
        autoscaler.start()
    try:
        _serve_forever(
            running=lambda: orch.is_running and not orch.crawl_completed)
    finally:
        if autoscaler is not None:
            # Stop the control loop, then retire every child through the
            # graceful SIGTERM path (their un-acked frames requeue into
            # the broker's spool/queues before it drains below).
            autoscaler.stop()
            try:
                autoscaler.supervisor.stop_all()
            except Exception as e:
                logger.warning("autoscaler child teardown failed: %s", e)
            from .utils.metrics import clear_autoscaler_provider
            clear_autoscaler_provider(autoscaler.snapshot)
        orch.stop()
        # This process hosts the broker: exiting the moment the crawl
        # completes would take undelivered frames (e.g. inference batches
        # a TPU worker hasn't pulled yet) down with it.  COMPLETED crawls
        # only — an interrupted/aborted run must exit promptly, not stall
        # on frames nobody will ever consume.
        try:
            drain = getattr(broker, "drain", None)
            if callable(drain) and orch.crawl_completed:
                drain_s = r.get_float("distributed.shutdown_drain_s", 30.0)
                if bus is not broker:
                    # Flush buffered publishes INTO the broker first, or
                    # the broker drain below would pass on empty queues
                    # while frames still sit in the outbox.
                    bus.outbox.drain(timeout_s=drain_s)
                drain(timeout_s=drain_s)
        finally:
            try:
                if bus is not broker:
                    # The durable-wiring branch ran: stop the outbox
                    # flusher and unregister OUR /dlq provider (guarded
                    # by identity, mirroring the setup condition).
                    bus.close()   # close_inner=False: broker outlives it
                    from .utils.metrics import clear_dlq_provider
                    clear_dlq_provider(broker.dlq_snapshot)
            finally:
                broker.close()


def _run_worker(cfg: CrawlerConfig, r: ConfigResolver) -> None:
    """`main.go:708-750`."""
    worker_id = r.get_str("distributed.worker_id")
    if not worker_id:
        raise CliConfigError("worker mode requires --worker-id")
    from .modes.common import create_state_manager
    from .worker import CrawlWorker
    bus = _make_bus(r)
    sm, bridge_closer = _maybe_bridge(
        create_state_manager(cfg, cfg.crawl_id), cfg, r)
    youtube_crawler = None
    if cfg.platform == "youtube":
        from .modes.youtube_random import initialize_youtube_crawler_components
        youtube_crawler, _yt_client = \
            initialize_youtube_crawler_components(sm, cfg)
    else:
        from .crawl import setup_pool_from_config
        setup_pool_from_config(cfg)  # `worker.go:96-133` pool init
    from .worker.worker import WorkerConfig
    worker = CrawlWorker(worker_id, cfg, bus, sm,
                         wcfg=WorkerConfig(
                             worker_id=worker_id,
                             heartbeat_s=_heartbeat_interval(r),
                             slo_batch_p95_ms=r.get_float(
                                 "observability.slo_batch_p95_ms", 0.0)),
                         youtube_crawler=youtube_crawler)
    from .utils.metrics import set_status_provider
    set_status_provider(worker.get_status)  # /status (`worker.go:459`)
    worker.start()
    try:
        _serve_forever(running=lambda: worker.is_running)
    finally:
        worker.stop()
        bridge_closer()
        bus.close()


def _run_job_service(cfg: CrawlerConfig, r: ConfigResolver) -> None:
    """`main.go:602` -> dapr.StartDaprMode."""
    from .modes.jobs import JobScheduler, JobService
    service = JobService(cfg)
    scheduler = JobScheduler(service)
    bus = None
    if r.get_bool("distributed.bus_serve", False) \
            or r.get_str("distributed.bus_address"):
        # Accept schedule/delete commands over the bus — the transport
        # replacing the reference's Dapr invocation handlers.
        from .bus.messages import TOPIC_JOBS
        if r.get_bool("distributed.bus_serve", False):
            bus = _make_serving_bus(r)  # raises without --bus-address
        else:
            bus = _make_bus(r)
        bus.subscribe(TOPIC_JOBS, scheduler.handle_command)
    scheduler.start()
    try:
        _serve_forever()
    finally:
        scheduler.stop()
        if bus is not None:
            try:
                bus.close()
            except Exception as e:
                logger.warning("bus close failed: %s", e)


def _run_job_submit(r: ConfigResolver) -> int:
    """mode=job-submit: publish a schedule/delete command to a running
    `--mode job` service over the bus (the client half of the reference's
    scheduleJob/deleteJob invocation API, `dapr/job.go:212-267`)."""
    import json as _json

    name = r.get_str("job.name")
    if not name:
        raise CliConfigError("job-submit requires --job-name")
    if not r.get_str("distributed.bus_address"):
        raise CliConfigError("job-submit requires --bus-address")
    if r.get_bool("job.delete", False):
        command = {"action": "delete", "name": name}
    else:
        raw = r.get_str("job.data", "")
        if raw.startswith("@"):
            try:
                with open(raw[1:], "r", encoding="utf-8") as f:
                    raw = f.read()
            except OSError as e:
                raise CliConfigError(f"cannot read --job-data file: {e}")
        try:
            data = _json.loads(raw) if raw else {}
        except ValueError as e:
            raise CliConfigError(f"--job-data is not valid JSON: {e}")
        if not isinstance(data, dict):
            raise CliConfigError("--job-data must be a JSON object")
        command = {"action": "schedule", "name": name,
                   "due_in_s": r.get_float("job.due_s", 0.0),
                   "repeat_every_s": r.get_float("job.repeat_s", 0.0),
                   "data": data}
    from .bus.messages import TOPIC_JOBS
    bus = _make_bus(r)
    try:
        bus.publish(TOPIC_JOBS, command)
    finally:
        bus.close()
    print(_json.dumps({"submitted": command["action"], "job": name}))
    return 0


def _run_train_head(cfg: CrawlerConfig, r: ConfigResolver) -> int:
    """mode=train-head: crawl JSONL + labels file → fine-tuned classifier
    head → orbax checkpoint (+ labels.json vocabulary) that `tpu-worker`
    reloads via --head-checkpoint — closing BASELINE config #3's loop.

    Labels file: one JSON object per line, {"post_uid": ..., "label": X}
    where X is an int class id or a string class name (a sorted vocabulary
    is built and saved for string labels)."""
    import json as _json

    from .inference.checkpoint import save_params
    from .models.train import TrainConfig, finetune_head

    posts_file = r.get_str("train.posts_file")
    labels_file = r.get_str("train.labels_file")
    ckpt_dir = r.get_str("train.checkpoint_dir")
    if not (posts_file and labels_file and ckpt_dir):
        print("error: train-head needs --train-posts, --train-labels and "
              "--head-checkpoint", file=sys.stderr)
        return 2

    texts: dict = {}
    with open(posts_file, "r", encoding="utf-8") as f:
        for line in f:
            if not line.strip():
                continue
            row = _json.loads(line)
            text = row.get("all_text") or row.get("description") or ""
            if row.get("post_uid") and text:
                texts[row["post_uid"]] = text

    raw_labels: list = []
    with open(labels_file, "r", encoding="utf-8") as f:
        for line in f:
            if not line.strip():
                continue
            row = _json.loads(line)
            if row.get("post_uid") in texts:
                raw_labels.append((row["post_uid"], row["label"]))
    if not raw_labels:
        print("error: no labelled posts matched the crawl file",
              file=sys.stderr)
        return 2

    values = [lbl for _, lbl in raw_labels]
    str_count = sum(isinstance(v, str) for v in values)
    if str_count and str_count != len(values):
        # A single stray string would silently remap every int id through
        # string-sort order — refuse instead.
        print("error: labels file mixes string and integer labels; "
              "use one kind consistently", file=sys.stderr)
        return 2
    if str_count:
        vocab = sorted({str(v) for v in values})
        index = {name: i for i, name in enumerate(vocab)}
        pairs = [(uid, index[str(v)]) for uid, v in raw_labels]
    else:
        vocab = None
        pairs = [(uid, int(v)) for uid, v in raw_labels]
        if any(lbl < 0 for _, lbl in pairs):
            print("error: negative label ids are not valid classes "
                  "(drop unlabeled rows instead of marking them -1)",
                  file=sys.stderr)
            return 2
    n_labels = (len(vocab) if vocab is not None
                else max(lbl for _, lbl in pairs) + 1)

    engine = _make_engine(cfg, r, n_labels=n_labels, cast_params=False)

    token_lists = engine.tokenizer.encode_batch(
        [texts[uid] for uid, _ in pairs])
    labels = [lbl for _, lbl in pairs]
    epochs = r.get_int("train.epochs", 20)
    if epochs < 1:
        print("error: --train-epochs must be >= 1", file=sys.stderr)
        return 2
    lora_rank = r.get_int("train.lora_rank", 0)
    if lora_rank < 0:
        print(f"error: --train-lora-rank must be >= 0, got {lora_rank}",
              file=sys.stderr)
        return 2
    # Scope: explicit --train-scope wins; otherwise a positive lora rank
    # implies lora (the historical behavior), else head.
    scope = r.get_str("train.scope") or ("lora" if lora_rank > 0
                                         else "head")
    if scope not in ("head", "lora", "full"):
        # The flag has argparse choices; the YAML path must reject typos
        # too — a silent fall-through would head-train when the user
        # asked for a full fine-tune.
        print(f"error: train.scope must be head|lora|full, got {scope!r}",
              file=sys.stderr)
        return 2
    if scope == "lora" and lora_rank <= 0:
        print("error: --train-scope lora needs --train-lora-rank > 0",
              file=sys.stderr)
        return 2
    if scope != "lora" and lora_rank > 0:
        print(f"error: --train-lora-rank conflicts with --train-scope "
              f"{scope}", file=sys.stderr)
        return 2
    grad_accum = r.get_int("train.grad_accum_steps", 1)
    if grad_accum < 1:
        print(f"error: --train-grad-accum must be >= 1, got {grad_accum}",
              file=sys.stderr)
        return 2
    if grad_accum > 1 and scope != "full":
        print(f"error: --train-grad-accum applies to --train-scope full "
              f"only (scope is {scope})", file=sys.stderr)
        return 2
    state_dir = r.get_str("train.state_dir")
    if state_dir and scope != "full":
        print(f"error: --train-state-dir applies to --train-scope full "
              f"only (scope is {scope})", file=sys.stderr)
        return 2
    if scope == "lora":
        from .models.lora import finetune_lora

        tc = TrainConfig(
            learning_rate=r.get_float("train.learning_rate", 1e-4),
            warmup_steps=10)
        params, history = finetune_lora(
            engine.ecfg, engine.params, token_lists, labels,
            rank=lora_rank, tc=tc, epochs=epochs,
            batch_size=min(16, max(4, len(labels))))
    elif scope == "full":
        from .models.train import finetune_full

        batch = min(16, max(4, len(labels)))
        # Accumulation splits each batch; keep microbatches non-empty.
        grad_accum = min(grad_accum, batch)
        batch -= batch % grad_accum
        tc = TrainConfig(
            learning_rate=r.get_float("train.learning_rate", 2e-5),
            warmup_steps=10, grad_accum_steps=grad_accum)
        params, history = finetune_full(
            engine.ecfg, engine.params, token_lists, labels, tc=tc,
            epochs=epochs, batch_size=batch,
            state_dir=state_dir or None)
    else:
        tc = TrainConfig(
            learning_rate=r.get_float("train.learning_rate", 1e-3),
            warmup_steps=10)
        params, history = finetune_head(
            engine.ecfg, engine.params, token_lists, labels, tc=tc,
            epochs=epochs, batch_size=min(32, max(8, len(labels))),
            buckets=tuple(cfg.inference.bucket_sizes))

    # Monotonic step numbering: retraining into the same dir always
    # produces the NEW latest step, regardless of epoch counts.
    from .inference.checkpoint import latest_step_dir

    prior = latest_step_dir(ckpt_dir)
    next_step = (int(os.path.basename(prior).split("_", 1)[1]) + 1
                 if prior else 1)
    step_dir = os.path.join(ckpt_dir, f"step_{next_step}")
    save_params(step_dir, params)
    vocab_path = os.path.join(ckpt_dir, "labels.json")
    if vocab is not None:
        with open(vocab_path, "w", encoding="utf-8") as f:
            _json.dump({"labels": vocab}, f)
    elif os.path.exists(vocab_path):
        # Integer-label retrain into a dir that had a string vocabulary:
        # the old names no longer describe this head — remove them.
        os.remove(vocab_path)
    print(_json.dumps({
        "trained_examples": len(labels),
        "n_labels": n_labels,
        "epochs": epochs,
        "lora_rank": lora_rank,
        "final_loss": history[-1]["loss"],
        "final_accuracy": history[-1]["accuracy"],
        "checkpoint": step_dir,
    }))
    return 0


def _run_transcribe(cfg: CrawlerConfig, r: ConfigResolver) -> int:
    """mode=transcribe: BASELINE config #4 — Whisper ASR over crawled media.

    Scans ``--transcribe-input`` recursively for 16 kHz PCM ``.wav`` files
    (a crawl's ``media/`` tree; other containers belong to an upstream
    ffmpeg step), windows + buckets them through the SAME
    `media/chunker.py` featurize path the serving ASR worker uses (long
    files are transcribed across every 30 s window and reassembled, not
    truncated), and writes one JSONL row per file:
    ``{"path", "tokens", "text", "windows", "error"}`` (text only when
    the checkpoint dir ships tokenizer assets; ``error`` non-empty for
    decode failures).  With ``--bus-address`` and ``--infer``,
    transcripts also publish to the inference topic as a RecordBatch so
    they flow through embed+classify — media → text → embedding end to
    end."""
    import json as _json

    src = r.get_str("transcribe.input")
    asr_dir = cfg.inference.asr_pretrained_dir
    if not src or not asr_dir:
        print("error: transcribe mode needs --transcribe-input and "
              "--asr-pretrained-dir", file=sys.stderr)
        return 2
    if os.path.isfile(src):
        paths = [src]
        base = os.path.dirname(src) or "."
    else:
        paths = sorted(
            os.path.join(root, name)
            for root, _dirs, files in os.walk(src)
            for name in files if name.lower().endswith(".wav"))
        base = src
    if not paths:
        print(f"error: no .wav files under {src}", file=sys.stderr)
        return 2

    from .inference.asr import ASRPipeline

    pipeline = ASRPipeline.from_pretrained(
        asr_dir, batch_size=r.get_int("inference.asr_batch_size", 8),
        window_buckets=cfg.media.window_buckets or None)
    if cfg.media.max_windows_per_file:
        pipeline.chunker.max_windows_per_file = \
            cfg.media.max_windows_per_file
    results = pipeline.transcribe_files(paths)

    out_path = r.get_str("transcribe.output") or os.path.join(
        base, "transcripts.jsonl")
    failed = 0
    with open(out_path, "w", encoding="utf-8") as f:
        for res in results:
            if res.error:
                failed += 1
            f.write(_json.dumps({
                "path": os.path.relpath(res.path, base),
                "tokens": res.tokens,
                "text": res.text,
                "windows": res.windows,
                "error": res.error,
            }, ensure_ascii=False) + "\n")

    if cfg.inference.enabled and r.get_str("distributed.bus_address"):
        # Transcripts onto the inference topic: the TPU worker embeds and
        # classifies them like any crawled post.  channel_name groups by
        # the media file's directory (the per-channel layout the crawler
        # writes media under).
        from .bus.codec import RecordBatch
        from .bus.messages import TOPIC_INFERENCE_BATCHES
        from .datamodel.post import Post

        posts = []
        for res in results:
            if res.error or not (res.tokens or res.text):
                continue
            rel = os.path.relpath(res.path, base)
            posts.append(Post(
                post_uid=f"media:{rel}",
                channel_name=os.path.dirname(rel) or "transcripts",
                description=res.text or " ".join(str(t)
                                                 for t in res.tokens)))
        if posts:
            bus = _make_bus(r)
            try:
                bus.publish(TOPIC_INFERENCE_BATCHES,
                            RecordBatch.from_posts(
                                posts, crawl_id=cfg.crawl_id).to_dict())
            finally:
                bus.close()

    print(_json.dumps({
        "transcribed": len(results) - failed,
        "failed": failed,
        "output": out_path,
    }))
    # Every file failing is a failed RUN (a gating script must not ship
    # an all-empty transcripts file as success).
    return 0 if len(results) > failed else 1


def _make_engine(cfg: CrawlerConfig, r: ConfigResolver,
                 n_labels: Optional[int] = None,
                 with_checkpoint: bool = False,
                 cast_params: bool = True,
                 with_mesh: bool = False):
    """One engine-wiring path for tpu-worker / train-head / cluster.

    ``cast_params=False`` keeps the f32 layout regardless of
    ``inference.param_dtype`` / ``inference.quantize`` — train-head must
    fine-tune on (and persist) full-precision weights even when the same
    config file serves bf16 or int8.

    ``with_mesh=True`` (the serving modes) builds the data-parallel
    serving mesh from the ``parallel:`` block / --mesh-* flags
    (`inference.worker.build_serving_mesh`); params shard per
    `parallel.sharding` and batches shard across dp.  train-head and the
    cluster text-embed path stay single-device (cluster's k-means builds
    its own mesh)."""
    from .inference.engine import EngineConfig, InferenceEngine

    mesh = None
    if with_mesh:
        from .inference.worker import build_serving_mesh

        try:
            mesh = build_serving_mesh(
                data=cfg.inference.mesh_data,
                seq=cfg.inference.mesh_seq,
                tensor=cfg.inference.mesh_tensor,
                devices=cfg.inference.mesh_devices)
        except ValueError as e:
            raise CliConfigError(str(e))
    kw = dict(
        model=cfg.inference.embed_model.replace("-", "_"),
        batch_size=cfg.inference.batch_size,
        buckets=tuple(cfg.inference.bucket_sizes),
        pretrained_dir=cfg.inference.pretrained_dir or None,
        param_dtype=(cfg.inference.param_dtype or None)
        if cast_params else None,
        quantize=(cfg.inference.quantize or None) if cast_params else None,
        # train-head differentiates the model, and the Pallas flash kernel
        # has no custom_vjp — so training is PINNED to the XLA path
        # (unlike param_dtype/quantize, where None is already the safe
        # default, 'auto' here could still dispatch flash at long buckets).
        attention=(cfg.inference.attention or None) if cast_params
        else "xla",
        # Same reasoning for MoE: serving may pick capacity dispatch;
        # train-head keeps the model's exact dense default.
        moe_dispatch=(cfg.inference.moe_dispatch or None) if cast_params
        else None)
    if n_labels is not None:
        kw["n_labels"] = n_labels
    if with_checkpoint:
        kw["checkpoint_dir"] = r.get_str("train.checkpoint_dir") or None
    return InferenceEngine(EngineConfig(**kw), mesh=mesh)


def _run_cluster(cfg: CrawlerConfig, r: ConfigResolver) -> int:
    """mode=cluster: embeddings (or text, embedded on the fly) → TPU
    k-means → cluster assignments — BASELINE config #5's closing move
    (snowball crawl + embed + clustering)."""
    import json as _json

    import numpy as np

    input_file = r.get_str("cluster.input_file")
    output_file = r.get_str("cluster.output_file")
    k = r.get_int("cluster.k", 8)
    iters = r.get_int("cluster.iters", 25)
    if not input_file or not output_file:
        print("error: cluster mode needs --cluster-input and "
              "--cluster-output", file=sys.stderr)
        return 2
    if k < 2:
        print("error: --cluster-k must be >= 2", file=sys.stderr)
        return 2
    if iters < 1:
        print("error: --cluster-iters must be >= 1", file=sys.stderr)
        return 2

    uids: list = []
    embeddings: list = []
    texts: list = []
    with open(input_file, "r", encoding="utf-8") as f:
        for line in f:
            if not line.strip():
                continue
            row = _json.loads(line)
            uid = row.get("post_uid") or row.get("id") or str(len(uids))
            if isinstance(row.get("embedding"), list):
                uids.append(uid)
                embeddings.append(row["embedding"])
            else:
                text = row.get("all_text") or row.get("description") or ""
                if text:
                    uids.append(uid)
                    texts.append(text)
    if embeddings and texts:
        print("error: input mixes 'embedding' rows with text rows; "
              "cluster one kind at a time", file=sys.stderr)
        return 2
    if texts:
        x = _make_engine(cfg, r).embed(texts)
    else:
        widths = {len(e) for e in embeddings}
        if len(widths) != 1 or 0 in widths:
            print(f"error: embedding rows have inconsistent widths "
                  f"{sorted(widths)}; cluster one embedding space at a "
                  f"time", file=sys.stderr)
            return 2
        x = np.asarray(embeddings, np.float32)
    if len(x) < k:
        print(f"error: {len(x)} rows cannot form {k} clusters",
              file=sys.stderr)
        return 2

    import jax
    import jax.numpy as jnp

    from .models.clustering import fit, fit_sharded

    n_dev = len(jax.devices())
    if n_dev > 1 and len(x) % n_dev == 0:
        # Multi-chip deployment: shard rows over dp, XLA psums the one-hot
        # sums/counts across chips (BASELINE config #5's v5e-8 shape).
        from .parallel import make_mesh

        result = fit_sharded(jnp.asarray(x), k, make_mesh(), iters=iters)
    else:
        result = fit(jnp.asarray(x), k, iters=iters)
    assignments = np.asarray(result.assignments)
    sizes = np.bincount(assignments, minlength=k).tolist()
    with open(output_file, "w", encoding="utf-8") as f:
        _json.dump({
            "k": k,
            "iters": iters,
            "inertia": float(result.inertia),
            "cluster_sizes": sizes,
            "centroids": np.asarray(result.centroids).tolist(),
            "assignments": [
                {"post_uid": uid, "cluster": int(c)}
                for uid, c in zip(uids, assignments)],
        }, f)
    print(_json.dumps({
        "clustered": len(uids),
        "k": k,
        "inertia": round(float(result.inertia), 4),
        "cluster_sizes": sizes,
        "output": output_file,
    }))
    return 0


def _build_tpu_worker(cfg: CrawlerConfig, r: ConfigResolver):
    """Construct the TPU worker (engine + results sink + config) — split
    from the serve loop so the wiring is testable."""
    from .inference.worker import TPUWorker, TPUWorkerConfig
    from .state.providers import LocalStorageProvider

    serve = r.get_bool("distributed.bus_serve", False)
    if serve and not r.get_str("distributed.bus_address"):
        raise CliConfigError("--bus-serve requires --bus-address")  # early
    if r.get_bool("cluster.enabled", False) \
            and not r.get_bool("inference.publish_embeddings", True):
        # The loud half of the publish_embeddings knob: a clustering
        # stage is declared but this worker would publish result batches
        # with the embeddings stripped — the cluster worker downstream
        # would starve silently, batch after batch.
        raise CliConfigError(
            "--cluster-serve (cluster.enabled) requires embedding-"
            "carrying result batches; drop --no-publish-embeddings")
    # Engine and sink before the bus: if either raises (bad model key,
    # unreachable object store, too few devices for the mesh), no server
    # port has been bound and no threads need tearing down.
    engine = _make_engine(cfg, r, with_checkpoint=True, with_mesh=True)
    # Results sink: the object store when configured (--object-store),
    # else JSONL under the same storage root the crawler uses.
    if cfg.object_store_url:
        from .state.objectstore import (
            ObjectStorageProvider,
            make_object_client,
        )

        provider = ObjectStorageProvider(
            make_object_client(cfg.object_store_url))
    else:
        provider = LocalStorageProvider(cfg.storage_root)
    if serve:
        # Host the broker AND consume from it over loopback — the
        # single-service deployment of BASELINE configs #2/#3 (crawl
        # process publishes, this process brokers + infers).
        bus = _make_serving_bus(r)
    else:
        bus = _make_bus(r)
    return TPUWorker(bus, engine, provider=provider,
                     cfg=TPUWorkerConfig(
                         worker_id=r.get_str("distributed.worker_id")
                         or "tpu-worker-0",
                         publish_embeddings=r.get_bool(
                             "inference.publish_embeddings", True),
                         heartbeat_s=_heartbeat_interval(r),
                         metrics_port=r.get_int(
                             "observability.metrics_port", 0),
                         profiler_port=r.get_int(
                             "observability.profiler_port", 0),
                         stall_warn_s=r.get_float(
                             "inference.stall_warn_s", 120.0),
                         stall_exit_s=r.get_float(
                             "inference.stall_exit_s", 0.0),
                         slo_batch_p95_ms=r.get_float(
                             "observability.slo_batch_p95_ms", 0.0),
                         slo_queue_wait_ms=r.get_float(
                             "observability.slo_queue_wait_ms", 0.0),
                         slo_batch_age_ms=r.get_float(
                             "observability.slo_batch_age_ms", 0.0),
                         profile_on_slow_ms=r.get_float(
                             "observability.profile_on_slow_ms", 0.0),
                         span_export_interval_s=r.get_float(
                             "observability.span_export_interval_s", 15.0),
                         span_export_max_spans=r.get_int(
                             "observability.span_export_max_spans", 512),
                         span_sample_rate=r.get_float(
                             "observability.span_sample_rate", 1.0)))


def _build_asr_worker(cfg: CrawlerConfig, r: ConfigResolver):
    """Construct the ASR worker (Whisper pipeline + transcript sink +
    config) — split from the serve loop so the wiring is testable.
    Returns (worker, reentry_closer)."""
    from .inference.asr import ASRPipeline
    from .media.worker import ASRWorker, ASRWorkerConfig
    from .state.providers import LocalStorageProvider

    serve = r.get_bool("distributed.bus_serve", False)
    if serve and not r.get_str("distributed.bus_address"):
        raise CliConfigError("--bus-serve requires --bus-address")
    asr_dir = cfg.inference.asr_pretrained_dir
    if not asr_dir:
        raise CliConfigError("asr-worker mode requires --asr-pretrained-dir")
    # Pipeline and sink before the bus: a bad checkpoint dir must fail
    # before any port is bound (the _build_tpu_worker discipline).
    pipeline = ASRPipeline.from_pretrained(
        asr_dir, batch_size=r.get_int("inference.asr_batch_size", 8),
        window_buckets=cfg.media.window_buckets or None)
    if cfg.media.max_windows_per_file:
        pipeline.chunker.max_windows_per_file = \
            cfg.media.max_windows_per_file
    if cfg.object_store_url:
        from .state.objectstore import (
            ObjectStorageProvider,
            make_object_client,
        )

        provider = ObjectStorageProvider(
            make_object_client(cfg.object_store_url))
    else:
        provider = LocalStorageProvider(cfg.storage_root)
    bus = _make_serving_bus(r) if serve else _make_bus(r)
    worker = ASRWorker(bus, pipeline, provider=provider,
                       cfg=ASRWorkerConfig(
                           worker_id=r.get_str("distributed.worker_id")
                           or "asr-worker-0",
                           heartbeat_s=_heartbeat_interval(r),
                           metrics_port=r.get_int(
                               "observability.metrics_port", 0),
                           coalesce_batches=cfg.media.coalesce_batches,
                           slo_asr_batch_p95_ms=r.get_float(
                               "observability.slo_asr_batch_p95_ms", 0.0),
                           slo_queue_wait_ms=r.get_float(
                               "observability.slo_queue_wait_ms", 0.0),
                           slo_batch_age_ms=r.get_float(
                               "observability.slo_batch_age_ms", 0.0),
                           span_export_interval_s=r.get_float(
                               "observability.span_export_interval_s",
                               15.0),
                           span_export_max_spans=r.get_int(
                               "observability.span_export_max_spans", 512),
                           span_sample_rate=r.get_float(
                               "observability.span_sample_rate", 1.0)))
    reentry_closer = None
    if cfg.inference.enabled:
        # Close the loop in-process: transcripts re-enter the text
        # pipeline as synthetic posts through an InferenceBridge over
        # the crawl's own state sink (post_uid = media:<id> keeps the
        # dedupe window effective across re-crawls).
        from .inference.bridge import InferenceBridge
        from .media.bridge import TranscriptReentry
        from .modes.common import create_state_manager

        bridge = InferenceBridge(
            create_state_manager(cfg, cfg.crawl_id), worker.bus,
            crawl_id=cfg.crawl_id,
            batch_size=cfg.inference.batch_size,
            deadline_s=cfg.inference.batch_deadline_ms / 1000.0)
        TranscriptReentry(bridge, worker.bus)
        reentry_closer = bridge.close
    return worker, reentry_closer


def _run_asr_worker(cfg: CrawlerConfig, r: ConfigResolver) -> None:
    """mode=asr-worker: the media/ASR serving worker (BASELINE config #4
    live) — AudioBatchMessages in, transcripts out, optional re-entry
    into the text inference pipeline with --infer."""
    worker, reentry_closer = _build_asr_worker(cfg, r)
    worker.warmup()  # compile every window-bucket program before serving
    worker.start()
    try:
        _serve_forever()
    finally:
        worker.stop()
        if reentry_closer is not None:
            try:
                reentry_closer()
            except Exception as e:
                logger.warning("reentry bridge close failed: %s", e)
        try:
            worker.bus.close()
        except Exception as e:
            logger.warning("bus close failed: %s", e)


def _build_cluster_worker(cfg: CrawlerConfig, r: ConfigResolver):
    """Construct the streaming clustering worker (engine + assignment
    sink + config) — split from the serve loop so the wiring is
    testable (the _build_tpu_worker discipline)."""
    from .cluster.engine import ClusterEngine, ClusterEngineConfig
    from .cluster.worker import ClusterWorker, ClusterWorkerConfig
    from .inference.worker import build_serving_mesh
    from .state.providers import LocalStorageProvider

    serve = r.get_bool("distributed.bus_serve", False)
    if serve and not r.get_str("distributed.bus_address"):
        raise CliConfigError("--bus-serve requires --bus-address")
    # Engine before the bus: a bad mesh/bucket config must fail before
    # any port is bound.
    mesh = build_serving_mesh(
        data=cfg.inference.mesh_data, seq=cfg.inference.mesh_seq,
        tensor=cfg.inference.mesh_tensor,
        devices=cfg.inference.mesh_devices)
    buckets = tuple(int(b) for b in r.get_list("cluster.buckets")) \
        or (64, 256)
    engine = ClusterEngine(
        ClusterEngineConfig(k=r.get_int("cluster.k", 16), buckets=buckets),
        mesh=mesh)
    if cfg.object_store_url:
        from .state.objectstore import (
            ObjectStorageProvider,
            make_object_client,
        )

        provider = ObjectStorageProvider(
            make_object_client(cfg.object_store_url))
    else:
        provider = LocalStorageProvider(cfg.storage_root)
    bus = _make_serving_bus(r) if serve else _make_bus(r)
    return ClusterWorker(
        bus, engine=engine, provider=provider,
        cfg=ClusterWorkerConfig(
            worker_id=r.get_str("distributed.worker_id")
            or "cluster-worker-0",
            heartbeat_s=_heartbeat_interval(r),
            metrics_port=r.get_int("observability.metrics_port", 0),
            k=r.get_int("cluster.k", 16),
            buckets=buckets,
            checkpoint_every_batches=r.get_int(
                "cluster.checkpoint_every_batches", 8),
            min_cluster_fraction=r.get_float(
                "cluster.min_cluster_fraction", 0.5),
            slo_batch_p95_ms=r.get_float(
                "observability.slo_batch_p95_ms", 0.0),
            slo_queue_wait_ms=r.get_float(
                "observability.slo_queue_wait_ms", 0.0),
            slo_batch_age_ms=r.get_float(
                "observability.slo_batch_age_ms", 0.0),
            span_export_interval_s=r.get_float(
                "observability.span_export_interval_s", 15.0),
            span_export_max_spans=r.get_int(
                "observability.span_export_max_spans", 512),
            span_sample_rate=r.get_float(
                "observability.span_sample_rate", 1.0)))


def _run_cluster_worker(cfg: CrawlerConfig, r: ConfigResolver) -> None:
    """mode=cluster-worker: the streaming clustering worker (BASELINE
    config #5 live) — embedding-carrying result batches in, cluster
    assignments + /clusters + TOPIC_CLUSTERS updates out.  A restart
    resumes the centroid model from the last atomic checkpoint."""
    worker = _build_cluster_worker(cfg, r)
    worker.warmup()  # compile bucket programs when a checkpoint fixed dim
    worker.start()
    try:
        _serve_forever()
    finally:
        worker.stop()
        try:
            worker.bus.close()  # serve-mode: broker + loopback client too
        except Exception as e:
            logger.warning("bus close failed: %s", e)


def _run_tpu_worker(cfg: CrawlerConfig, r: ConfigResolver) -> None:
    """The new TPU inference worker mode (SURVEY.md §7.6)."""
    from .parallel.multihost import initialize_multihost

    # Pod-scale bring-up from DCT_COORDINATOR / DCT_NUM_PROCESSES /
    # DCT_PROCESS_ID env vars; single-host runs are a no-op.
    initialize_multihost()
    cache_dir = r.get_str("inference.compilation_cache_dir", "")
    if cache_dir:
        # Restarts (watchdog stall-exit, redeploys) reload each bucket's
        # program from disk instead of recompiling, so warmup() below is
        # near-instant on every start after the first.
        from .inference.engine import enable_compilation_cache

        enable_compilation_cache(cache_dir)
    worker = _build_tpu_worker(cfg, r)
    # Pre-compile the (bucket, batch) programs so the first crawl batches
    # don't pay XLA compile latency mid-stream — under the stall watchdog,
    # since bring-up is the longest on-chip window.
    worker.warmup()
    worker.start()
    try:
        _serve_forever()
    finally:
        worker.stop()
        try:
            worker.bus.close()  # serve-mode: broker + loopback client too
        except Exception as e:
            logger.warning("bus close failed: %s", e)


if __name__ == "__main__":
    sys.exit(main())
