"""Record-batching codec: the wire format feeding the TPU inference worker.

This is the north star's extension of the reference's message layer
(BASELINE.json: "`distributed/messages.go` gains a record-batching codec"):
crawled posts are accumulated into fixed-size batches, serialized as
length-prefixed compressed frames, and streamed over gRPC/DCN to the TPU
worker.  Design goals:

- batches sized for the device (default 256 records) so host-side batching,
  not the wire, sets the padding bucket;
- zstd compression (zlib fallback) — crawl text compresses ~5-10x, which
  matters on DCN, not ICI;
- frame = 4-byte big-endian length + compressed JSON payload, so a byte
  stream can be incrementally decoded (`decode_frames`).
"""

from __future__ import annotations

import json
import struct
import zlib
from dataclasses import dataclass, field
from datetime import datetime
from typing import Any, Dict, Iterator, List, Optional, Tuple

try:
    import zstandard as _zstd

    _ZSTD_C = _zstd.ZstdCompressor(level=3)
    _ZSTD_D = _zstd.ZstdDecompressor()
except Exception:  # pragma: no cover - zstd is present in the target image
    _zstd = None

from ..datamodel import Post
from ..datamodel.post import format_time, parse_time
from ..state.datamodels import new_id, utcnow
from .messages import (
    MSG_ALERT,
    MSG_AUDIO_BATCH,
    MSG_CHAOS_FAULT,
    MSG_CLUSTER_UPDATE,
    MSG_DISCOVERED_PAGES,
    MSG_HEARTBEAT,
    MSG_PAUSE,
    MSG_POISON_PILL,
    MSG_RESUME,
    MSG_SPAN_BATCH,
    MSG_STOP,
    MSG_TRANSCRIPT,
    MSG_WORK_ITEM,
    MSG_WORK_RESULT,
    MSG_WORKER_STARTED,
    MSG_WORKER_STOPPING,
    AlertMessage,
    AudioBatchMessage,
    ChaosMessage,
    ClusterUpdateMessage,
    ControlMessage,
    ResultMessage,
    SpanBatchMessage,
    StatusMessage,
    TranscriptMessage,
    WorkQueueMessage,
    DEFAULT_TENANT,
    new_trace_id,
    normalize_tenant,
)

CODEC_VERSION = 1
COMPRESSION_ZSTD = "zstd"
COMPRESSION_ZLIB = "zlib"
COMPRESSION_NONE = "none"

_MAGIC = b"DCTB"  # frame magic for sanity checking
_HEADER = struct.Struct(">4sBB I")  # magic, version, compression, length


def _compress(data: bytes, method: str) -> bytes:
    if method == COMPRESSION_ZSTD:
        if _zstd is None:
            # Never mislabel: a frame stamped zstd must BE zstd.
            raise ValueError("zstd compression requested but zstandard unavailable")
        return _ZSTD_C.compress(data)
    if method == COMPRESSION_ZLIB:
        return zlib.compress(data, 6)
    return data


# Decompression-bomb bound: a few-KB adversarial body must not be able to
# allocate unbounded memory in the worker.  Sized above any legitimate
# payload (the transport's own frame cap is 201 MB compressed; crawl-text
# batches expand ~3-5x).
MAX_DECOMPRESSED_BYTES = 1 << 30


def _decompress(data: bytes, method: str) -> bytes:
    if method == COMPRESSION_ZSTD:
        if _zstd is None:
            raise ValueError("zstd frame received but zstandard unavailable")
        try:
            # A declared content size wins over max_output_size inside the
            # library, so the bomb check must read it explicitly.
            declared = _zstd.frame_content_size(data)
            if declared > MAX_DECOMPRESSED_BYTES:
                raise ValueError(
                    f"zstd frame declares {declared} bytes "
                    f"(limit {MAX_DECOMPRESSED_BYTES})")
            return _ZSTD_D.decompress(
                data, max_output_size=MAX_DECOMPRESSED_BYTES)
        except _zstd.ZstdError as e:  # corrupted body off the wire
            raise ValueError(f"zstd frame corrupt: {e}") from e
    if method == COMPRESSION_ZLIB:
        d = zlib.decompressobj()
        try:
            out = d.decompress(data, MAX_DECOMPRESSED_BYTES)
        except zlib.error as e:
            raise ValueError(f"zlib frame corrupt: {e}") from e
        if d.unconsumed_tail:
            raise ValueError(
                f"zlib frame exceeds {MAX_DECOMPRESSED_BYTES} bytes")
        return out
    return data


_COMP_IDS = {COMPRESSION_NONE: 0, COMPRESSION_ZLIB: 1, COMPRESSION_ZSTD: 2}
_COMP_NAMES = {v: k for k, v in _COMP_IDS.items()}


def default_compression() -> str:
    return COMPRESSION_ZSTD if _zstd is not None else COMPRESSION_ZLIB


# --- typed envelope registry ------------------------------------------------
# The ONE table mapping every wire `message_type` to the dataclass that
# decodes it.  Handlers that today re-dispatch by hand (`from_dict` on a
# guessed class) can use `decode_message`; crawlint's BUS checker
# (`tools/analyze/busreg.py`) statically enforces that every envelope
# dataclass in `bus/messages.py` appears here and carries a trace_id, so
# adding a message type without wiring its decode path fails the tier-1
# gate instead of surfacing as a dropped message in production.
MESSAGE_REGISTRY: Dict[str, type] = {
    MSG_WORK_ITEM: WorkQueueMessage,
    MSG_POISON_PILL: WorkQueueMessage,
    MSG_WORK_RESULT: ResultMessage,
    MSG_DISCOVERED_PAGES: ResultMessage,
    MSG_HEARTBEAT: StatusMessage,
    MSG_WORKER_STARTED: StatusMessage,
    MSG_WORKER_STOPPING: StatusMessage,
    MSG_PAUSE: ControlMessage,
    MSG_RESUME: ControlMessage,
    MSG_STOP: ControlMessage,
    MSG_CHAOS_FAULT: ChaosMessage,
    MSG_AUDIO_BATCH: AudioBatchMessage,
    MSG_TRANSCRIPT: TranscriptMessage,
    MSG_SPAN_BATCH: SpanBatchMessage,
    MSG_ALERT: AlertMessage,
    MSG_CLUSTER_UPDATE: ClusterUpdateMessage,
}


def decode_message(payload: Dict[str, Any]):
    """Typed decode of a bus envelope dict by its ``message_type``.

    RecordBatch payloads have no message_type (they are identified by
    their dedicated topics) and decode via `RecordBatch.from_dict`.
    """
    mtype = payload.get("message_type")
    cls = MESSAGE_REGISTRY.get(mtype)
    if cls is None:
        raise ValueError(f"unknown message_type: {mtype!r}")
    return cls.from_dict(payload)


@dataclass
class RecordBatch:
    """A batch of Post records bound for (or back from) the TPU worker.

    `results` carries the inference outputs on the return path: one dict per
    record (embedding, label scores, transcript, ...).
    """

    batch_id: str = ""
    crawl_id: str = ""
    source_topic: str = ""
    created_at: Optional[datetime] = None
    trace_id: str = ""
    # Workload provenance: who this batch's chip-seconds are billed to.
    # Legacy frames (pre-tenant spools/outboxes) decode to DEFAULT_TENANT.
    tenant: str = DEFAULT_TENANT
    records: List[Dict[str, Any]] = field(default_factory=list)
    results: List[Dict[str, Any]] = field(default_factory=list)

    @classmethod
    def from_posts(cls, posts: List[Post], crawl_id: str = "",
                   trace_id: str = "",
                   tenant: str = DEFAULT_TENANT) -> "RecordBatch":
        # Every batch gets a trace id at birth: the TPU worker's queue-wait
        # / coalesce / engine-stage spans hang off it, so a batch with no
        # id would be invisible to /traces.
        return cls(batch_id=new_id(), crawl_id=crawl_id, created_at=utcnow(),
                   trace_id=trace_id or new_trace_id(),
                   tenant=normalize_tenant(tenant),
                   records=[p.to_dict() for p in posts])

    def posts(self) -> List[Post]:
        return [Post.from_dict(r) for r in self.records]

    def texts(self) -> List[str]:
        """The text each record contributes to embed+classify."""
        return [Post.from_dict(r).text_for_inference() for r in self.records]

    def __len__(self) -> int:
        return len(self.records)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "batch_id": self.batch_id,
            "crawl_id": self.crawl_id,
            "source_topic": self.source_topic,
            "created_at": format_time(self.created_at),
            "trace_id": self.trace_id,
            "tenant": self.tenant,
            "records": self.records,
            "results": self.results,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RecordBatch":
        return cls(
            batch_id=d.get("batch_id", "") or "",
            crawl_id=d.get("crawl_id", "") or "",
            source_topic=d.get("source_topic", "") or "",
            created_at=parse_time(d.get("created_at")),
            trace_id=d.get("trace_id", "") or "",
            tenant=normalize_tenant(d.get("tenant")),
            records=list(d.get("records") or []),
            results=list(d.get("results") or []),
        )

    def to_bytes(self, compression: Optional[str] = None) -> bytes:
        return encode_frame(self.to_dict(), compression)

    @classmethod
    def from_bytes(cls, data: bytes) -> "RecordBatch":
        payload, rest = decode_frame(data)
        if rest:
            raise ValueError(f"{len(rest)} trailing bytes after frame")
        return cls.from_dict(payload)


def encode_frame(payload: Dict[str, Any], compression: Optional[str] = None) -> bytes:
    """Serialize one payload as a length-prefixed compressed frame."""
    method = compression or default_compression()
    if method not in _COMP_IDS:
        raise ValueError(f"unknown compression: {method}")
    raw = json.dumps(payload, ensure_ascii=False,
                     separators=(",", ":")).encode("utf-8")
    body = _compress(raw, method)
    return _HEADER.pack(_MAGIC, CODEC_VERSION, _COMP_IDS[method], len(body)) + body


def decode_frame(data: bytes) -> Tuple[Dict[str, Any], bytes]:
    """Decode one frame; returns (payload, remaining_bytes)."""
    if len(data) < _HEADER.size:
        raise ValueError("truncated frame header")
    magic, version, comp_id, length = _HEADER.unpack_from(data)
    if magic != _MAGIC:
        raise ValueError("bad frame magic")
    if version != CODEC_VERSION:
        raise ValueError(f"unsupported codec version: {version}")
    if comp_id not in _COMP_NAMES:
        raise ValueError(f"unknown compression id: {comp_id}")
    end = _HEADER.size + length
    if len(data) < end:
        raise ValueError("truncated frame body")
    raw = _decompress(data[_HEADER.size:end], _COMP_NAMES[comp_id])
    try:
        payload = json.loads(raw.decode("utf-8"))
    except RecursionError as e:
        # Adversarially deep nesting ('['*N) must still surface as the
        # drop/dead-letter signal, not crash the handler thread.
        raise ValueError("frame JSON nests too deeply") from e
    if not isinstance(payload, dict):
        raise ValueError(
            f"frame payload is {type(payload).__name__}, expected object")
    return payload, data[end:]


def decode_frames(data: bytes) -> Iterator[Dict[str, Any]]:
    """Incrementally decode a concatenated stream of frames."""
    while data:
        payload, data = decode_frame(data)
        yield payload


class BatchAccumulator:
    """Accumulates posts into fixed-size RecordBatches with a deadline.

    The host-side half of keeping the TPU fed from a bursty crawl stream
    (SURVEY.md §7 hard part (c)): emit when `batch_size` is reached, or when
    `deadline_s` has elapsed since the first queued record (whichever first).
    """

    def __init__(self, batch_size: int = 256, deadline_s: float = 0.05,
                 crawl_id: str = "", tenant: str = DEFAULT_TENANT):
        self.batch_size = batch_size
        self.deadline_s = deadline_s
        self.crawl_id = crawl_id
        self.tenant = normalize_tenant(tenant)
        self._pending: List[Post] = []
        self._first_at: Optional[float] = None

    def add(self, post: Post, now: float) -> Optional[RecordBatch]:
        """Queue a post; returns a full batch if one is ready."""
        if self._first_at is None:
            self._first_at = now
        self._pending.append(post)
        if len(self._pending) >= self.batch_size:
            return self._emit()
        return None

    def poll(self, now: float) -> Optional[RecordBatch]:
        """Returns a partial batch if the deadline has passed."""
        if self._pending and self._first_at is not None \
                and now - self._first_at >= self.deadline_s:
            return self._emit()
        return None

    def flush(self) -> Optional[RecordBatch]:
        return self._emit() if self._pending else None

    def _emit(self) -> RecordBatch:
        batch = RecordBatch.from_posts(self._pending, crawl_id=self.crawl_id,
                                       tenant=self.tenant)
        self._pending = []
        self._first_at = None
        return batch

    def __len__(self) -> int:
        return len(self._pending)
